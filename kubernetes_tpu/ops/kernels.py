"""Fused scheduling-cycle kernels: filter + score + select over all nodes.

One jitted computation replaces the reference's per-cycle goroutine fan-out
(core/generic_scheduler.go:457 findNodesThatFit, :672 PrioritizeNodes, :286
selectHost): every node is evaluated at once on the MXU/VPU, and the
reference's *sequential* semantics are reproduced exactly:

- adaptive partial search (numFeasibleNodesToFind :434): feasibility is
  computed for all nodes, then the first `num_to_find` feasible nodes *in
  rotation order from last_index* are kept (a cumsum emulates the
  sequential walk's stopping point — same feasible set, same "evaluated"
  count, same last_index advance).
- integer 0-10 scores with the reference's exact int64/float64 formulas,
  normalized over the kept set only.
- round-robin tie-break among max-score nodes via last_node_index (:292).

The batched variant runs a `lax.scan` over a burst of pending pods against
one snapshot, folding each decision's resource deltas into the node state on
device — serially-equivalent decisions at one kernel launch for the burst.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

import kubernetes_tpu.ops  # noqa: F401  (enables x64)

MAX_PRIORITY = 10
MB = 1024 * 1024
IMAGE_MIN = 23 * MB
IMAGE_MAX = 1000 * MB
ZONE_WEIGHTING = 2.0 / 3.0

# fail-first codes (order of the default predicate set in
# predicates.PREDICATE_ORDERING)
FAIL_NONE = 0
FAIL_UNSCHEDULABLE = 1
FAIL_GENERAL = 2
FAIL_DISK = 3          # NoDiskConflict (ordering: before taints)
FAIL_TAINTS = 4
FAIL_MAXVOL = 5        # Max*VolumeCount family
FAIL_VOLBIND = 6       # CheckVolumeBinding
FAIL_VOLZONE = 7       # NoVolumeZoneConflict
FAIL_INTERPOD = 8

# general_bits layout (GeneralPredicates sub-failures, predicates.go:1112)
BIT_PODS = 0
BIT_CPU = 1
BIT_MEM = 2
BIT_EPH = 3
BIT_SCALAR0 = 4          # bit 4+s for scalar resource s (s < 36)
BIT_UNKNOWN_SCALAR = 59     # pod wants a scalar no node advertises
BIT_HOST = 60
BIT_PORTS = 61
BIT_SELECTOR = 62

# default priority weights (reference: defaults.go:108, register_priorities.go)
DEFAULT_WEIGHTS = {
    "selector_spread": 1,
    "interpod": 1,
    "least_requested": 1,
    "most_requested": 0,      # ClusterAutoscalerProvider swaps this for least
    "rtcr": 0,                # RequestedToCapacityRatioPriority (default shape)
    "balanced": 1,
    "prefer_avoid": 10000,
    "node_affinity": 1,
    "taint_toleration": 1,
    "image_locality": 1,
}

# profile scoring tensor (round 19): column order of the
# [profiles x priorities] int64 weight table the profile-aware kernels
# gather per-pod rows from (`wtab[pod["profile_id"]]`). The last column,
# "gang_locality", is the rank-aware gang set-scoring objective — zero
# for placement-blind profiles, so the default row reproduces today's
# scoring exactly. profiles.ProfileSet.weight_table() builds tables in
# THIS order; changing it is a wire-format change for resident tensors.
PRIORITY_AXIS = ("selector_spread", "interpod", "least_requested",
                 "most_requested", "rtcr", "balanced", "prefer_avoid",
                 "node_affinity", "taint_toleration", "image_locality",
                 "gang_locality")
_AXIS_INDEX = {n: i for i, n in enumerate(PRIORITY_AXIS)}


def _wsel(weights, wrow, name):
    """Effective weight of one priority family: the static python int
    (single-profile path — folds at trace time, today's programs) or the
    pod's gathered tensor-row lane (tensor mode — the STATIC `weights`
    dict then only gates which families compile in: a family any profile
    weights is computed once and scaled per pod, including to zero)."""
    if wrow is None:
        return weights[name]
    return wrow[_AXIS_INDEX[name]]


def _i64(x):
    return jnp.asarray(x, dtype=jnp.int64)


def _inert(arr) -> bool:
    """True when a per-node pod field was left at its default: the encoder
    emits shape-(1,) arrays for features the pod/cluster doesn't exercise
    (tpu_scheduler._pod_arrays), so whole priority/predicate families can be
    skipped at *trace* time — the shape is static."""
    return arr.ndim >= 1 and arr.shape[-1] == 1


def _local_total(weights, req_cpu, req_mem, alloc_cpu, alloc_mem,
                 wrow=None):
    """The four row-local resource priorities (least/most/RTCR/balanced),
    exact integer/float formulas. `req_*` is pod-nonzero + node-nonzero.
    Works elementwise on [N] vectors and on single-row scalars — both the
    full-cycle kernel and the uniform-burst incremental rescore call this,
    so the two paths cannot drift. `wrow` (optional) is one pod's gathered
    [K] weight-tensor row: families gate on the STATIC `weights` union and
    scale by the traced lane (_wsel)."""
    total = jnp.zeros_like(alloc_cpu)

    if weights["least_requested"]:
        def least(req, cap):
            ok = (cap > 0) & (req <= cap)
            return jnp.where(ok, (cap - req) * MAX_PRIORITY // jnp.maximum(cap, 1), 0)
        total = total + _wsel(weights, wrow, "least_requested") * (
            (least(req_cpu, alloc_cpu) + least(req_mem, alloc_mem)) // 2)

    if weights["most_requested"]:
        def most(req, cap):
            ok = (cap > 0) & (req <= cap)
            return jnp.where(ok, req * MAX_PRIORITY // jnp.maximum(cap, 1), 0)
        total = total + _wsel(weights, wrow, "most_requested") * (
            (most(req_cpu, alloc_cpu) + most(req_mem, alloc_mem)) // 2)

    if weights["rtcr"]:
        # RequestedToCapacityRatio, default broken-linear shape {0->10,100->0}
        # (requested_to_capacity_ratio.go:39): score(p) = 10 + trunc(-10p/100);
        # Go int64 division truncates toward zero -> -(10p // 100) for p >= 0
        def rtcr_res(req, cap):
            p = jnp.where((cap == 0) | (req > cap), 100,
                          100 - (cap - req) * 100 // jnp.maximum(cap, 1))
            return 10 - (10 * p) // 100
        total = total + _wsel(weights, wrow, "rtcr") * (
            (rtcr_res(req_cpu, alloc_cpu) + rtcr_res(req_mem, alloc_mem)) // 2)

    if weights["balanced"]:
        cpu_f = jnp.where(alloc_cpu == 0, 1.0, req_cpu / alloc_cpu)
        mem_f = jnp.where(alloc_mem == 0, 1.0, req_mem / alloc_mem)
        balanced = jnp.where(
            (cpu_f >= 1.0) | (mem_f >= 1.0), 0,
            ((1.0 - jnp.abs(cpu_f - mem_f)) * float(MAX_PRIORITY)).astype(jnp.int64))
        total = total + _wsel(weights, wrow, "balanced") * balanced

    return total


def _fit_scores(nodes, pod, kept, weights, z_pad, wrow=None, gang=None):
    """Enabled priorities, masked-normalized over `kept`. Returns total[N] i64.

    Zero-weight priorities and inert (default-valued, shape-[1]) pod fields
    are skipped at trace time: a plain-pod burst compiles down to
    LeastRequested + BalancedAllocation + integer constants — int64 division
    and f64 emulation on the MXU-less VPU path are the cost drivers, so ops
    that provably contribute a constant are folded into one scalar.

    `wrow` (tensor mode) is this pod's [K] weight row — the STATIC
    `weights` dict becomes the cross-profile union gate and every family
    scales by its lane. `gang` = (gz[z_pad], member) is the rank-aware
    gang set-scoring input: gz counts THIS segment's already-placed
    members per zone, and nodes score min(count, 10) * gang weight — the
    group objective that prefers packing a gang into few zones, via the
    same one-hot zone reduction the spread family uses."""
    alloc_cpu, alloc_mem = nodes["alloc_cpu"], nodes["alloc_mem"]
    req_cpu = pod["nz_cpu"] + nodes["nz_cpu"]
    req_mem = pod["nz_mem"] + nodes["nz_mem"]

    const = 0   # python-int accumulator for provably-constant scores
    total = jnp.zeros(nodes["valid"].shape, dtype=jnp.int64) + _local_total(
        weights, req_cpu, req_mem, alloc_cpu, alloc_mem, wrow=wrow)

    if gang is not None and weights.get("gang_locality"):
        # gang-locality (rank-aware set-scoring): zone member counts of the
        # current gang segment, gathered per node through a dense one-hot
        # [N, Z] reduction (no scatter/gather serialization), clipped at
        # MAX_PRIORITY like every integer priority. Zone 0 = "no zone"
        # scores 0; non-members contribute and read nothing.
        gz, gmember = gang
        zone_id = nodes["zone_id"]
        gw = _wsel(weights, wrow, "gang_locality")
        zh = zone_id[:, None] == jnp.arange(z_pad, dtype=zone_id.dtype)[None, :]
        glc = jnp.sum(jnp.where(zh, gz[None, :], 0), axis=1)
        gl = jnp.minimum(glc, MAX_PRIORITY)
        total = total + jnp.where(gmember & (zone_id > 0), gw * gl, 0)

    if weights["node_affinity"]:
        na = pod["node_aff_counts"]
        if _inert(na):
            pass   # all counts 0 -> normalized score 0 everywhere
        else:
            # NodeAffinity: NormalizeReduce(10, reverse=False) over kept
            na_max = jnp.max(jnp.where(kept, na, 0))
            total = total + _wsel(weights, wrow, "node_affinity") * jnp.where(
                na_max == 0, na, MAX_PRIORITY * na // jnp.maximum(na_max, 1))

    if weights["taint_toleration"]:
        tt = pod["taint_counts"]
        if _inert(tt):
            const = const + _wsel(weights, wrow, "taint_toleration") \
                * MAX_PRIORITY
        else:
            # TaintToleration: NormalizeReduce(10, reverse=True) over kept
            tt_max = jnp.max(jnp.where(kept, tt, 0))
            total = total + _wsel(weights, wrow, "taint_toleration") * jnp.where(
                tt_max == 0, MAX_PRIORITY,
                MAX_PRIORITY - MAX_PRIORITY * tt // jnp.maximum(tt_max, 1))

    if weights["selector_spread"]:
        sc = pod["spread_counts"]
        if _inert(sc):
            # all counts 0 -> node and zone fractions are both max -> 10
            const = const + _wsel(weights, wrow, "selector_spread") \
                * MAX_PRIORITY
        else:
            # SelectorSpread: node + zone blend (selector_spreading.go:99).
            # Zone aggregation runs as dense one-hot [N, Z] reductions —
            # z_pad is tiny and the former .at[zone_id].add/.max scatters +
            # zone_counts[zone_id] gather serialize badly (XLA lowers them
            # to scalar loops on CPU and slow scatter paths on TPU); inside
            # the burst scan that cost repeated PER POD and was the
            # dominant term of the spread lane's 0.27x-of-plain cliff
            zone_id = nodes["zone_id"]
            max_by_node = jnp.max(jnp.where(kept, sc, 0))
            f = jnp.where(max_by_node > 0,
                          float(MAX_PRIORITY) * ((max_by_node - sc)
                                                 / jnp.maximum(max_by_node, 1)),
                          float(MAX_PRIORITY))
            in_zone = kept & (zone_id > 0)
            zh = zone_id[:, None] == jnp.arange(z_pad, dtype=zone_id.dtype)[None, :]
            izh = zh & in_zone[:, None]                       # [N, Z]
            zone_counts = jnp.sum(jnp.where(izh, sc[:, None], 0), axis=0)
            zone_present = jnp.any(izh, axis=0)
            have_zones = jnp.any(in_zone)
            max_by_zone = jnp.max(jnp.where(zone_present, zone_counts, 0))
            # each row has exactly one true lane in zh -> the sum IS the
            # node's zone count (the gather, without the gather)
            zc = jnp.sum(jnp.where(zh, zone_counts[None, :], 0), axis=1)
            zs = jnp.where(max_by_zone > 0,
                           float(MAX_PRIORITY) * ((max_by_zone - zc)
                                                  / jnp.maximum(max_by_zone, 1)),
                           float(MAX_PRIORITY))
            f = jnp.where(have_zones & (zone_id > 0),
                          f * (1.0 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zs, f)
            total = total + _wsel(weights, wrow, "selector_spread") \
                * f.astype(jnp.int64)

    if weights["interpod"]:
        ic = pod["interpod_counts"]
        tracked = pod["interpod_tracked"]
        if _inert(ic) and _inert(tracked):
            pass   # nothing tracked -> 0 everywhere
        else:
            # InterPodAffinity preferred: min-max over kept∩tracked
            sel = kept & tracked
            ic_max = jnp.maximum(
                jnp.max(jnp.where(sel, ic, jnp.iinfo(jnp.int64).min)), 0)
            ic_min = jnp.minimum(
                jnp.min(jnp.where(sel, ic, jnp.iinfo(jnp.int64).max)), 0)
            diff = ic_max - ic_min
            total = total + _wsel(weights, wrow, "interpod") * jnp.where(
                (diff > 0) & tracked,
                (float(MAX_PRIORITY) * ((ic - ic_min)
                                        / jnp.maximum(diff, 1))).astype(jnp.int64),
                0)

    if weights["image_locality"]:
        s = pod["image_sums"]
        if _inert(s):
            pass   # sum 0 -> clip to IMAGE_MIN -> score 0
        else:
            # ImageLocality (image_locality.go:42)
            sc = jnp.clip(s, IMAGE_MIN, IMAGE_MAX)
            total = total + _wsel(weights, wrow, "image_locality") * (
                MAX_PRIORITY * (sc - IMAGE_MIN) // (IMAGE_MAX - IMAGE_MIN))

    if weights["prefer_avoid"]:
        pa = pod["prefer_avoid"]
        if _inert(pa):
            const = const + _wsel(weights, wrow, "prefer_avoid") \
                * MAX_PRIORITY
        else:
            total = total + _wsel(weights, wrow, "prefer_avoid") * pa

    return total + const


def _feasibility(nodes, pod):
    """Returns (feasible[N], fail_first[N] i8, general_bits[N] i64).

    Inert (shape-[1], default all-pass) mask families drop out at trace time."""
    valid = nodes["valid"]
    # GeneralPredicates: resources
    bits = jnp.zeros(valid.shape, dtype=jnp.int64)
    check_res = pod["check_resources"]
    pods_over = check_res & (nodes["pod_count"] + 1 > nodes["allowed_pods"])
    bits |= jnp.where(pods_over, 1 << BIT_PODS, 0)
    has_req = pod["has_request"] & check_res
    over_cpu = nodes["alloc_cpu"] < pod["req_cpu"] + nodes["req_cpu"]
    over_mem = nodes["alloc_mem"] < pod["req_mem"] + nodes["req_mem"]
    over_eph = nodes["alloc_eph"] < pod["req_eph"] + nodes["req_eph"]
    bits |= jnp.where(has_req & over_cpu, 1 << BIT_CPU, 0)
    bits |= jnp.where(has_req & over_mem, 1 << BIT_MEM, 0)
    bits |= jnp.where(has_req & over_eph, 1 << BIT_EPH, 0)
    # scalar resources: [N,S]
    over_scalar = nodes["alloc_scalar"] < pod["req_scalar"][None, :] + nodes["req_scalar"]
    wants_scalar = pod["req_scalar"][None, :] > 0
    scalar_fail = has_req & wants_scalar & over_scalar          # [N,S]
    s_count = scalar_fail.shape[1]
    scalar_bits = jnp.sum(
        jnp.where(scalar_fail,
                  (1 << (BIT_SCALAR0 + jnp.arange(s_count, dtype=jnp.int64)))[None, :],
                  0), axis=1)
    bits |= scalar_bits
    bits |= jnp.where(check_res & pod["unknown_scalar"],
                      _i64(1) << BIT_UNKNOWN_SCALAR, 0)
    if not _inert(pod["host_ok"]):
        bits |= jnp.where(~pod["host_ok"], 1 << BIT_HOST, 0)
    if not _inert(pod["ports_ok"]):
        bits |= jnp.where(~pod["ports_ok"], 1 << BIT_PORTS, 0)
    if not _inert(pod["sel_ok"]):
        bits |= jnp.where(~pod["sel_ok"], 1 << BIT_SELECTOR, 0)

    general_fail = bits != 0
    # padding entries in a burst bucket: infeasible everywhere, no state fold
    skip = pod["skip"]

    # PREDICATE_ORDERING: unschedulable, general, disk, taints, max-volume,
    # volume binding, volume zone, inter-pod affinity. Built lowest-priority
    # first; each later overwrite wins, so the result is the FIRST failing
    # predicate in the ordering. Inert families emit no ops.
    fail_first = FAIL_NONE
    for mask_key, code in (("interpod_code", FAIL_INTERPOD),
                           ("volzone_ok", FAIL_VOLZONE),
                           ("volbind_ok", FAIL_VOLBIND),
                           ("maxvol_ok", FAIL_MAXVOL),
                           ("taints_ok", FAIL_TAINTS),
                           ("disk_ok", FAIL_DISK)):
        field = pod[mask_key]
        if _inert(field):
            continue
        failed = (field > 0) if mask_key == "interpod_code" else ~field
        fail_first = jnp.where(failed, code, fail_first)
    fail_first = jnp.where(general_fail, FAIL_GENERAL, fail_first)
    if not _inert(pod["unsched_ok"]):
        fail_first = jnp.where(~pod["unsched_ok"], FAIL_UNSCHEDULABLE, fail_first)
    feasible = valid & (fail_first == FAIL_NONE) & ~skip
    return feasible, fail_first.astype(jnp.int8), bits


def _cycle_core(nodes, pod, last_index, last_node_index, num_to_find, n_real,
                weights, z_pad, perm=None, inv_perm=None, pos=None,
                ghost=None, wtab=None, gang=None):
    """One fused cycle. The reference's sequential walk from last_index
    (generic_scheduler.go:486,519) is emulated WITHOUT materializing the
    rotation permutation: for natural index j, its 1-based rank in rotation
    order among feasible nodes is S[j]-pre (j >= li) or F-pre+S[j] (j < li),
    where S is the natural-order feasibility cumsum, pre = S[li-1], F = S[-1]
    — no gathers, int32 counters (TPU has no native int64).

    When the per-cycle NodeTree enumeration differs from the device axis
    (uneven zones rotate the zone-interleaved order between cycles —
    node_tree.py rotation_map), `perm`/`inv_perm` supply THIS cycle's order:
    perm[p] = natural row at enumeration position p, inv_perm its inverse.
    The walk/tie math then runs in position space (the cumsums act on
    permuted masks, one gather each way) and last_index keeps its positional
    meaning; perm=None is the identity fast path.

    `pos` is the GATHER-FREE rotation mode for the full-scan regime (the
    caller guarantees num_to_find >= n_real): pos[j] = node j's position in
    this cycle's enumeration (the inverse permutation). With a full scan
    kept == feasible and evaluated == n, so the only order-dependent step
    is selectHost's k-th-tie pick — resolved by one [N] sort of tie
    positions instead of the three [N] gathers of the perm path, which
    serialize badly on TPU (30x per-cycle cost at 1k nodes).

    `wtab` (tensor mode) is the resident [profiles x priorities] weight
    table; this pod's row is gathered by `pod["profile_id"]` and every
    score family scales by its lane (the static `weights` dict gates
    which families compile in — the cross-profile union). `gang` threads
    the rank-aware gang set-scoring input into _fit_scores."""
    n_pad = nodes["valid"].shape[0]
    i32 = jnp.int32
    i = jnp.arange(n_pad, dtype=i32)
    nr = jnp.asarray(n_real, i32)
    n_safe = jnp.maximum(n_real, 1)
    # last_index persists across cycles while the cluster may shrink; the
    # oracle's walk is modulo n (generic_scheduler.py:148), so clamp the
    # rotation origin before use or ranks go negative after node removals
    li = jnp.asarray(last_index % n_safe, i32)
    ntf = jnp.asarray(num_to_find, i32)
    in_range = i < nr

    # Nominated-ghost two-pass (podFitsOnNode :598,627) for resource-only
    # ghosts: pass 1 filters against ghost-augmented usage; pass 2 (without
    # ghosts) is implied, since removing pods only frees resources. Scores
    # run on the RAW rows — PrioritizeNodes never adds nominated pods.
    if ghost is not None:
        fnodes = {**nodes,
                  "req_cpu": nodes["req_cpu"] + ghost["cpu"],
                  "req_mem": nodes["req_mem"] + ghost["mem"],
                  "req_eph": nodes["req_eph"] + ghost["eph"],
                  "pod_count": nodes["pod_count"] + ghost["cnt"]}
    else:
        fnodes = nodes
    feasible, fail_first, general_bits = _feasibility(fnodes, pod)
    feas = feasible & in_range

    if pos is not None:
        # full-scan regime (num_to_find >= n by caller contract): every
        # feasible node is kept and the walk always evaluates all n, so no
        # position-space cumsum machinery is needed at all
        F = jnp.sum(feas.astype(i32))
        kept = feas
        found = jnp.minimum(F, ntf)
        evaluated = jnp.where(pod["skip"], 0, nr).astype(jnp.int64)
    else:
        feas_p = feas if perm is None else feas[perm]
        S = jnp.cumsum(feas_p.astype(i32))
        F = S[-1]                                   # total feasible
        pre = jnp.where(li > 0, S[jnp.maximum(li - 1, 0)], 0)
        after = i >= li                              # position space
        rank_p = jnp.where(after, S - pre, F - pre + S)  # rank at position p
        kept_p = feas_p & (rank_p <= ntf)
        kept = kept_p if perm is None else kept_p[inv_perm]
        found = jnp.minimum(F, ntf)
        reached = F >= ntf
        # the position where the sequential walk stops: unique feasible p
        # with rank == num_to_find; evaluated = its rotation offset + 1
        pstar = jnp.argmax(kept_p & (rank_p == ntf)).astype(i32)
        stop_pos = jnp.where(pstar >= li, pstar - li, nr - li + pstar)
        evaluated = jnp.where(reached, stop_pos + 1, nr)
        # a skip (bucket-padding) pod consumes no rotation state
        evaluated = jnp.where(pod["skip"], 0, evaluated).astype(jnp.int64)

    wrow = None if wtab is None else wtab[pod["profile_id"]]
    total = _fit_scores(nodes, pod, kept, weights, z_pad, wrow=wrow,
                        gang=gang)

    tmask = jnp.where(kept, total, jnp.iinfo(jnp.int64).min)
    max_score = jnp.max(tmask)
    is_tie = kept & (tmask == max_score)
    num_ties = jnp.maximum(jnp.sum(is_tie.astype(i32)), 1)
    # round-robin k-th tie in rotation order (selectHost :286-295)
    k = (last_node_index % num_ties.astype(jnp.int64)).astype(i32)
    if pos is not None:
        # k-th tie by enumeration position relative to the walk origin:
        # one sort replaces the permuted cumsum + two gathers. Positions of
        # valid nodes are distinct in [0, n); ties exclude invalid rows.
        rel = jnp.where(pos >= li, pos - li, nr - li + pos)
        t_pos = jnp.where(is_tie, rel, jnp.int32(2 ** 30))
        kth = jax.lax.dynamic_slice(jnp.sort(t_pos), (k,), (1,))[0]
        sel = jnp.argmax(is_tie & (rel == kth)).astype(jnp.int64)
    elif perm is None:
        tie_p = is_tie
        T = jnp.cumsum(tie_p.astype(i32))
        preT = jnp.where(li > 0, T[jnp.maximum(li - 1, 0)], 0)
        trank = jnp.where(after, T - preT, T[-1] - preT + T)
        sel = jnp.argmax(tie_p & (trank == k + 1)).astype(jnp.int64)
    else:
        tie_p = is_tie[perm]
        T = jnp.cumsum(tie_p.astype(i32))
        preT = jnp.where(li > 0, T[jnp.maximum(li - 1, 0)], 0)
        trank = jnp.where(after, T - preT, T[-1] - preT + T)
        sel_p = jnp.argmax(tie_p & (trank == k + 1)).astype(jnp.int64)
        sel = perm[sel_p].astype(jnp.int64)
    selected = jnp.where(found > 0, sel, -1)

    return {
        "selected": selected,
        "found": found.astype(jnp.int64),
        "evaluated": evaluated,
        "max_score": jnp.where(found > 0, max_score, 0),
        "total": total,
        "kept": kept,
        "feasible": feasible,
        "fail_first": fail_first,
        "general_bits": general_bits,
        "next_last_index": (last_index + evaluated) % n_safe,
        # selectHost is skipped when only one node is feasible
        # (generic_scheduler.go:244-250), so the tie counter doesn't move
        "next_last_node_index": last_node_index + jnp.where(found > 1, 1, 0),
    }


@partial(jax.jit, static_argnames=("z_pad", "weights_tuple"))
def _schedule_cycle_jit(nodes, pod, last_index, last_node_index, num_to_find,
                        n_real, z_pad, weights_tuple):
    weights = dict(weights_tuple)
    return _cycle_core(nodes, pod, last_index, last_node_index, num_to_find,
                       n_real, weights, z_pad)


@partial(jax.jit, static_argnames=("z_pad", "weights_tuple"))
def _schedule_cycle_wtab_jit(nodes, pod, wtab, last_index, last_node_index,
                             num_to_find, n_real, z_pad, weights_tuple):
    return _cycle_core(nodes, pod, last_index, last_node_index, num_to_find,
                       n_real, dict(weights_tuple), z_pad, wtab=wtab)


def schedule_cycle(nodes, pod, last_index, last_node_index, num_to_find, n_real,
                   z_pad, weights=None, wtab=None):
    """One scheduling cycle. `nodes`/`pod` are dicts of device arrays.
    (Nominated-ghost cycles run only inside the pressure batch —
    _pressure_batch_jit — which calls _cycle_core with its carried ghost.)

    `wtab` (tensor mode) is the resident [P, K] profile weight table;
    `pod` must then carry `profile_id` and `weights` is the static union
    gate dict — ONE compiled program scores every profile."""
    weights_tuple = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    if wtab is not None:
        return _schedule_cycle_wtab_jit(
            nodes, pod, wtab, _i64(last_index), _i64(last_node_index),
            _i64(num_to_find), _i64(n_real), z_pad, weights_tuple)
    return _schedule_cycle_jit(
        nodes, pod, _i64(last_index), _i64(last_node_index), _i64(num_to_find),
        _i64(n_real), z_pad, weights_tuple)


# ---------------------------------------------------------------------------
# Batched burst: lax.scan over pods, folding decisions into node state
# ---------------------------------------------------------------------------
_MUTABLE = ("req_cpu", "req_mem", "req_eph", "req_scalar",
            "nz_cpu", "nz_mem", "pod_count")


def gang_carry_checkpoint(dev_nodes):
    """Group-boundary checkpoint of the device-resident carry (the gang
    generalization of the per-wave rewind contract). Device arrays are
    immutable: every in-trial fold builds NEW arrays (`state.at[...]` /
    `{**dev, **rows}`), leaving the checkpointed rows untouched on device —
    so a shallow dict copy pins the pre-gang matrix, and restoring it is a
    ZERO-COPY rewind (no host re-upload, no dispatch). The copy guards
    against in-place dict mutation only; the arrays themselves cannot be
    written. Invalidated by any dirty-row scatter or full re-upload between
    checkpoint and rewind (the caller tracks that with an epoch counter and
    falls back to discarding the matrix)."""
    return None if dev_nodes is None else dict(dev_nodes)


def _fold_state(state, pod, sel, hit):
    """Fold one decision's resource delta into the mutable node state.

    Mirrors the cache's NodeInfo.AddPod aggregate update
    (reference: nodeinfo/node_info.go:498) applied to the dense matrix.
    """
    idx = jnp.maximum(sel, 0)
    delta = jnp.where(hit, 1, 0)
    return {
        "req_cpu": state["req_cpu"].at[idx].add(jnp.where(hit, pod["upd_cpu"], 0)),
        "req_mem": state["req_mem"].at[idx].add(jnp.where(hit, pod["upd_mem"], 0)),
        "req_eph": state["req_eph"].at[idx].add(jnp.where(hit, pod["upd_eph"], 0)),
        "req_scalar": state["req_scalar"].at[idx].add(
            jnp.where(hit, pod["upd_scalar"], jnp.zeros_like(pod["upd_scalar"]))),
        "nz_cpu": state["nz_cpu"].at[idx].add(jnp.where(hit, pod["nz_cpu"], 0)),
        "nz_mem": state["nz_mem"].at[idx].add(jnp.where(hit, pod["nz_mem"], 0)),
        "pod_count": state["pod_count"].at[idx].add(delta),
    }


def _batch_core(nodes, mut0, pods, last_index, last_node_index,
                num_to_find, n_real, perms, inv_perms, oid_seq,
                spread0, z_pad, weights, rotate, carry_spread,
                rotate_pos=False, constrain=None, wtab=None):
    """Body of the generic lax.scan burst kernel. `constrain` (optional)
    pins the node-axis carry — the mutable state rows and the carried
    spread vector — to a mesh sharding every iteration, so the O(N) sweep
    stays split across chips while the scalar select epilogue replicates
    (parallel/sharding.py wraps this for mesh mode; None = single-chip
    identity, the exact program the jit wrapper below compiles). `wtab`
    (tensor mode) makes the scan profile-aware: `pods["profile_id"]` [B]
    rides the xs, and each step's cycle gathers that pod's weight row —
    a window MIXING tenants scores in the one launch."""
    if constrain is None:
        constrain = lambda v: v
    static = {k: v for k, v in nodes.items() if k not in _MUTABLE}
    # selector-spread counts evolve with in-burst placements: the caller
    # guarantees every pod shares one selector set (spec-identical), so the
    # shared dense base counts (spread0 [N]) are carried and each placement
    # folds +1 on its node (selector_spreading.go:66 counting semantics)

    def step(carry, xs):
        perm = inv_perm = pos = None
        if rotate_pos:
            # gather-free rotation: perms holds per-order POSITION vectors
            state, li, lni, spread = carry
            pod, oid = xs
            pos = perms[oid]
        elif rotate:
            state, li, lni, spread = carry
            pod, oid = xs
            perm, inv_perm = perms[oid], inv_perms[oid]
        else:
            state, li, lni, spread = carry
            pod = xs
        if carry_spread:
            pod = {**pod, "spread_counts": spread}
        full = {**static, **state}
        out = _cycle_core(full, pod, li, lni, num_to_find, n_real, weights,
                          z_pad, perm=perm, inv_perm=inv_perm, pos=pos,
                          wtab=wtab)
        sel = out["selected"]
        hit = out["found"] > 0
        new_state = constrain(_fold_state(state, pod, sel, hit))
        if carry_spread:
            spread = constrain(spread.at[jnp.maximum(sel, 0)].add(
                jnp.where(hit & ~pod["skip"], 1, 0)))
        return ((new_state, out["next_last_index"],
                 out["next_last_node_index"], spread), {
            "selected": sel,
            "found": out["found"],
            "evaluated": out["evaluated"],
            "max_score": out["max_score"],
            "li_after": out["next_last_index"].astype(jnp.int32),
            "lni_after": out["next_last_node_index"],
        })

    if carry_spread:
        pods = {k: v for k, v in pods.items() if k != "spread_counts"}
    xs = (pods, oid_seq) if (rotate or rotate_pos) else pods
    init = (constrain(mut0), last_index, last_node_index, constrain(spread0))
    (state, li, lni, spread), outs = jax.lax.scan(step, init, xs)
    # ONE packed fetch block [3B] i32: selections, then the walk counters
    # AFTER each pod (li absolute — it is < n; lni as a delta from the
    # launch's start so it fits i32) — a mid-burst failure's prefix rewind
    # reads the counters straight out of the single fetched block instead
    # of paying a second round trip for the evaluated/found vectors
    outs["packed"] = jnp.concatenate([
        outs["selected"].astype(jnp.int32),
        outs["li_after"],
        (outs["lni_after"] - last_node_index).astype(jnp.int32)])
    return state, li, lni, spread, outs


@partial(jax.jit, static_argnames=("z_pad", "weights_tuple", "rotate",
                                   "carry_spread", "rotate_pos"))
def _schedule_batch_jit(nodes, mut0, pods, last_index, last_node_index,
                        num_to_find, n_real, perms, inv_perms, oid_seq,
                        spread0, z_pad, weights_tuple, rotate, carry_spread,
                        rotate_pos=False):
    return _batch_core(nodes, mut0, pods, last_index, last_node_index,
                       num_to_find, n_real, perms, inv_perms, oid_seq,
                       spread0, z_pad, dict(weights_tuple), rotate,
                       carry_spread, rotate_pos=rotate_pos)


@partial(jax.jit, static_argnames=("z_pad", "weights_tuple", "rotate",
                                   "carry_spread", "rotate_pos"))
def _schedule_batch_wtab_jit(nodes, mut0, pods, wtab, last_index,
                             last_node_index, num_to_find, n_real, perms,
                             inv_perms, oid_seq, spread0, z_pad,
                             weights_tuple, rotate, carry_spread,
                             rotate_pos=False):
    return _batch_core(nodes, mut0, pods, last_index, last_node_index,
                       num_to_find, n_real, perms, inv_perms, oid_seq,
                       spread0, z_pad, dict(weights_tuple), rotate,
                       carry_spread, rotate_pos=rotate_pos, wtab=wtab)


def schedule_batch(nodes, pods, last_index, last_node_index, num_to_find, n_real,
                   z_pad, weights=None, rotation=None, spread0=None,
                   rotation_pos=None, carry_in=None, mesh=None, wtab=None):
    """Schedule a burst of pods against one snapshot, decisions serially
    equivalent to per-pod cycles. `pods` is a dict of [B, ...] arrays.

    `rotation` = (perms[L, n_pad], inv_perms[L, n_pad], oid_seq[B]) supplies
    each in-burst cycle's NodeTree enumeration order when it differs from
    the device axis (uneven zones); None = the axis order every cycle.
    `rotation_pos` = (pos_arr[L, n_pad], oid_seq[B]) is the gather-free
    variant for the full-scan regime (caller guarantees
    num_to_find >= n_real): pos_arr[l][j] = node j's enumeration position
    under order l (the inverse permutation). Mutually exclusive with
    `rotation`. `spread0` [n_pad] carries selector-spread counts across the
    burst (requires spec-identical pods — one shared selector set).

    `carry_in` = (mut_state, spread) chains a pipelined wave straight off
    the previous wave's device-resident carry (no host round trip):
    mut_state is the prior return's `state` dict (the _MUTABLE rows),
    spread its carried count vector. `last_index`/`last_node_index` may
    likewise be the prior launch's device scalars. Returns
    (state, li, lni, spread, outs); outs["packed"] is the ONE-fetch block
    [3B] i32 — selected | li-after-each-pod | lni-delta-after-each-pod —
    so a caller fetches a single array per launch and re-derives any
    failure-prefix rewind from slices of it.

    `mesh` shards the node axis of the scan across a jax.sharding.Mesh
    (parallel/sharding.py): the SAME _batch_core program runs with the
    carried state pinned to NamedSharding(mesh, P("nodes")) and the select
    epilogue's tiny per-node vectors riding an ICI all-gather — sharded vs
    single-device is one code path parameterized by the sharding spec, so
    decisions are bit-identical by construction (pinned by
    tests/test_sharding.py + the sharded fuzz variants).

    `wtab` (tensor mode) is the [P, K] profile weight table (PRIORITY_AXIS
    columns); `pods` must then carry a `profile_id` [B] column and
    `weights` the static cross-profile union gate dict."""
    weights_tuple = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    z = jnp.zeros((1, 1), jnp.int32)
    if rotation_pos is not None:
        assert rotation is None
        perms = jnp.asarray(rotation_pos[0], jnp.int32)
        inv_perms = z
        oid_seq = jnp.asarray(rotation_pos[1], jnp.int32)
    elif rotation is None:
        perms = inv_perms = z
        oid_seq = jnp.zeros(1, jnp.int32)
    else:
        perms, inv_perms, oid_seq = (jnp.asarray(a, jnp.int32)
                                     for a in rotation)
    carry_spread = spread0 is not None or (
        carry_in is not None and carry_in[1] is not None)
    if carry_in is not None:
        mut0, s0 = carry_in
        if s0 is None:
            s0 = jnp.zeros((), jnp.int64)
    else:
        mut0 = {k: nodes[k] for k in _MUTABLE}
        s0 = jnp.asarray(spread0, jnp.int64) if spread0 is not None \
            else jnp.zeros((), jnp.int64)
    if wtab is not None:
        wtab = jnp.asarray(wtab, jnp.int64)
    if mesh is not None:
        from kubernetes_tpu.parallel import sharding as S
        fn = S.sharded_scan_fn(mesh, z_pad, weights_tuple,
                               rotation is not None, carry_spread,
                               rotation_pos is not None,
                               use_wtab=wtab is not None)
        if wtab is not None:
            return fn(nodes, mut0, pods, wtab, _i64(last_index),
                      _i64(last_node_index), _i64(num_to_find),
                      _i64(n_real), perms, inv_perms, oid_seq, s0)
        return fn(nodes, mut0, pods, _i64(last_index),
                  _i64(last_node_index), _i64(num_to_find), _i64(n_real),
                  perms, inv_perms, oid_seq, s0)
    if wtab is not None:
        return _schedule_batch_wtab_jit(
            nodes, mut0, pods, wtab, _i64(last_index),
            _i64(last_node_index), _i64(num_to_find), _i64(n_real), perms,
            inv_perms, oid_seq, s0, z_pad, weights_tuple,
            rotation is not None, carry_spread,
            rotate_pos=rotation_pos is not None)
    return _schedule_batch_jit(
        nodes, mut0, pods, _i64(last_index), _i64(last_node_index),
        _i64(num_to_find), _i64(n_real), perms, inv_perms, oid_seq, s0,
        z_pad, weights_tuple, rotation is not None, carry_spread,
        rotate_pos=rotation_pos is not None)


# ---------------------------------------------------------------------------
# Segmented burst: the whole wave chain — singleton runs AND gang segments —
# in ONE launch, with gang boundaries as scan segment boundaries
# ---------------------------------------------------------------------------
# The round-8 gang contract moved the atomicity boundary from the wave to the
# group, but the trial still ran as its own launch (one dispatch+fetch per
# gang — ruinous over a tunneled chip at hundreds of small gangs per drain).
# This kernel fuses a whole drain window: the carry holds BOTH the live state
# (mutable rows, li, lni, spread, t) and a CHECKPOINT of it taken at each
# segment start; a gang member that finds no node rewinds the live carry to
# the checkpoint in-scan (gang_checkpoint/gang_rewind semantics, now inside
# the scan), the rest of its segment is skipped, and the next segment
# proceeds against the rewound state — exactly the serial shell's
# trial→reject→park→continue sequence, with zero extra round trips.
#
# `t` counts NodeTree enumerations actually consumed: each non-skipped cycle
# advances it, a gang rewind restores it, and the per-cycle rotation order is
# looked up as oid_seq[t] (not the scan position) — so a rejected gang leaves
# the rotation walk exactly where it found it, matching the serial world's
# tree.checkpoint()/restore(). The host pre-slices the walk long enough for
# the all-segments-succeed case; consumed entries never exceed that.
#
# A failed SINGLETON (non-gang) pod does not rewind anything: the host-side
# burst contract still discards everything from the first singleton failure
# (its serial rerun may preempt), and the packed block carries the per-pod
# walk counters so the prefix rewind costs no second fetch.


def _segments_core(nodes, mut0, pods, seg_start, gang, n_pods,
                   last_index, last_node_index, num_to_find, n_real,
                   perms, inv_perms, oid_seq, spread0, z_pad,
                   weights, rot_mode, carry_spread, constrain=None,
                   wtab=None, gang_score=False):
    """rot_mode: 0 = stable axis order, 1 = perm/inv-perm gathers,
    2 = gather-free positions (full-scan regime).

    The pod count is a DYNAMIC operand of a single lax.while_loop (the
    uniform kernel's trick): the [B, ...] operands are padded to the
    caller's bucket for one compile per bucket, but the loop runs exactly
    `n_pods` iterations — a 1.5k-pod gang window inside a 16k bucket pays
    for 1.5k cycles, not 16k padded scan steps.

    `constrain` (optional) pins the node-axis pieces of BOTH carries — the
    live mutable rows/spread AND the in-scan gang checkpoint — to a mesh
    sharding each iteration (parallel/sharding.py wraps this for mesh
    mode; None = single-chip identity). The checkpoint/rewind pick() is a
    per-element where over identically-sharded operands, so a gang rewind
    stays shard-local — no collective beyond the select epilogue's
    all-gather.

    `wtab`/`gang_score` (round 19): profile weight-tensor gathering per
    pod, plus the rank-aware gang set-scoring carry — a tiny [z_pad]
    zone-count vector `gz` rides the live carry (and therefore the gang
    checkpoint/rewind machinery for free): it RESETS at every segment
    start, each placed GANG member one-hot-folds its node's zone, and
    later members of the same segment score nodes by
    min(members_in_zone, 10) * the member's profile gang weight
    (_fit_scores). A rewound gang restores gz with the rest of the
    carry; singleton segments never read it."""
    if constrain is None:
        constrain = lambda v: v
    i32 = jnp.int32
    static = {k: v for k, v in nodes.items() if k not in _MUTABLE}
    B = seg_start.shape[0]

    def pick(pred, new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(pred, a, b), new, old)

    def body(carry):
        cur, chk, t, chk_t, failed, i, out = carry
        pod = {k: jax.lax.dynamic_index_in_dim(v, i, keepdims=False)
               for k, v in pods.items()}
        sflag = seg_start[i]
        gflag = gang[i]
        if gang_score:
            # the gang zone-count vector resets at every segment start
            # BEFORE the checkpoint pick, so a rewind restores the reset
            # (zero) counts — exactly the serial trial's fresh tracker
            st_g, li_g, lni_g, sp_g, gz_g = cur
            gz_g = jnp.where(sflag, jnp.zeros_like(gz_g), gz_g)
            cur = (st_g, li_g, lni_g, sp_g, gz_g)
        # segment boundary: re-checkpoint the whole live carry (device
        # arrays are immutable, so this pins the pre-segment rows the same
        # way gang_carry_checkpoint does host-side — zero-copy)
        chk = pick(sflag, cur, chk)
        chk_t = jnp.where(sflag, t, chk_t)
        failed = jnp.where(sflag, False, failed)
        if gang_score:
            state, li, lni, spread, gz = cur
        else:
            state, li, lni, spread = cur
            gz = None
        # a member behind its segment's first failure consumes nothing:
        # the serial trial's post-failure decisions are discarded anyway
        eskip = pod["skip"] | (gflag & failed)
        pod = {**pod, "skip": eskip}
        perm = inv_perm = pos = None
        if rot_mode == 2:
            pos = perms[oid_seq[t]]
        elif rot_mode == 1:
            oid = oid_seq[t]
            perm, inv_perm = perms[oid], inv_perms[oid]
        if carry_spread:
            pod = {**pod, "spread_counts": spread}
        full = {**static, **state}
        out_c = _cycle_core(full, pod, li, lni, num_to_find, n_real,
                            weights, z_pad, perm=perm, inv_perm=inv_perm,
                            pos=pos, wtab=wtab,
                            gang=(gz, gflag) if gang_score else None)
        sel = out_c["selected"]
        hit = out_c["found"] > 0
        new_state = constrain(_fold_state(state, pod, sel, hit))
        new_spread = spread
        if carry_spread:
            new_spread = constrain(spread.at[jnp.maximum(sel, 0)].add(
                jnp.where(hit & ~eskip, 1, 0)))
        if gang_score:
            # a placed gang member one-hot-folds its node's zone into the
            # segment's count vector (zone 0 = "no zone" never counts)
            selz = static["zone_id"][jnp.maximum(sel, 0)]
            gadd = hit & ~eskip & gflag & (selz > 0)
            new_gz = gz + ((jnp.arange(z_pad, dtype=selz.dtype) == selz)
                           & gadd).astype(gz.dtype)
            new_cur = (new_state, out_c["next_last_index"],
                       out_c["next_last_node_index"], new_spread, new_gz)
        else:
            new_cur = (new_state, out_c["next_last_index"],
                       out_c["next_last_node_index"], new_spread)
        new_t = t + jnp.where(eskip, 0, jnp.int32(1))
        # gang member found no node: rewind the live carry to the segment
        # checkpoint — the in-scan gang_rewind
        fail_now = gflag & ~hit & ~eskip
        cur2 = pick(fail_now, chk, new_cur)
        t2 = jnp.where(fail_now, chk_t, new_t)
        failed = failed | fail_now
        li2, lni2 = cur2[1], cur2[2]
        col = jnp.stack([
            jnp.where(hit & ~eskip, sel, jnp.int64(-1)).astype(i32),
            li2.astype(i32),
            (lni2 - last_node_index).astype(i32),
            t2])
        return (cur2, chk, t2, chk_t, failed, i + 1, out.at[:, i].set(col))

    if gang_score:
        init_cur = (constrain(mut0), last_index, last_node_index,
                    constrain(spread0), jnp.zeros(z_pad, jnp.int64))
    else:
        init_cur = (constrain(mut0), last_index, last_node_index,
                    constrain(spread0))
    out0 = jnp.full((4, B), -1, i32)
    init = (init_cur, init_cur, jnp.int32(0), jnp.int32(0),
            jnp.zeros((), bool), jnp.int32(0), out0)
    Bn = jnp.asarray(n_pods, i32)
    (cur, _chk, _t, _ct, _f, _i, out) = jax.lax.while_loop(
        lambda c: c[5] < Bn, body, init)
    state, li, lni, spread = cur[0], cur[1], cur[2], cur[3]
    # ONE packed fetch block [4B] i32: selections (−1 = miss / rewound gang
    # member / padding), then the post-pod walk counters and the consumed-
    # enumeration count — every boundary the host commit needs (decided
    # prefixes, rejected-gang detection, rewind targets, NodeTree advance)
    # is a slice of this single array
    return state, li, lni, spread, out.reshape(4 * B)


@partial(jax.jit, static_argnames=("z_pad", "weights_tuple", "rot_mode",
                                   "carry_spread"))
def _schedule_batch_seg_jit(nodes, mut0, pods, seg_start, gang, n_pods,
                            last_index, last_node_index, num_to_find, n_real,
                            perms, inv_perms, oid_seq, spread0, z_pad,
                            weights_tuple, rot_mode, carry_spread):
    return _segments_core(nodes, mut0, pods, seg_start, gang, n_pods,
                          last_index, last_node_index, num_to_find, n_real,
                          perms, inv_perms, oid_seq, spread0, z_pad,
                          dict(weights_tuple), rot_mode, carry_spread)


@partial(jax.jit, static_argnames=("z_pad", "weights_tuple", "rot_mode",
                                   "carry_spread", "gang_score", "use_wtab"))
def _schedule_batch_seg_prof_jit(nodes, mut0, pods, seg_start, gang, n_pods,
                                 last_index, last_node_index, num_to_find,
                                 n_real, perms, inv_perms, oid_seq, spread0,
                                 wtab, z_pad, weights_tuple, rot_mode,
                                 carry_spread, gang_score, use_wtab):
    return _segments_core(nodes, mut0, pods, seg_start, gang, n_pods,
                          last_index, last_node_index, num_to_find, n_real,
                          perms, inv_perms, oid_seq, spread0, z_pad,
                          dict(weights_tuple), rot_mode, carry_spread,
                          wtab=wtab if use_wtab else None,
                          gang_score=gang_score)


def schedule_batch_segments(nodes, pods, seg_start, gang, n_pods,
                            last_index, last_node_index, num_to_find,
                            n_real, z_pad, weights=None, rotation=None,
                            rotation_pos=None, spread0=None, mesh=None,
                            wtab=None, gang_score=False):
    """Schedule a segmented drain window — singleton runs and all-or-nothing
    gang segments — in ONE launch with ONE packed fetch (see block comment).

    `pods` is a dict of [B, ...] stacked arrays padded to the caller's
    bucket (one compile per bucket); `n_pods` is the DYNAMIC real count —
    the while_loop runs exactly that many cycles, so bucket padding costs
    nothing at run time. `seg_start[B]` marks each segment's first pod;
    `gang[B]` marks members of all-or-nothing segments.
    `rotation`/`rotation_pos` follow schedule_batch's contract except the
    per-cycle order id sequence is indexed by enumerations CONSUMED (gang
    rewinds restore the cursor), so it must be the plain burst-wide walk,
    unsliced. Returns (state, li, lni, spread, packed[4B] i32) with
    packed = selected | li_after | lni_delta | t_after (entries past
    n_pods are -1 filler).

    `mesh` runs the SAME _segments_core program with the node axis of the
    live carry AND the gang checkpoint sharded across the mesh
    (parallel/sharding.py) — in-scan gang rewinds, rotation by consumed
    count t, and spread carries all run sharded, decisions bit-identical
    to the single-device kernel.

    `wtab` (tensor mode) is the [P, K] profile weight table (pods carry
    `profile_id` [B]); `gang_score=True` compiles the rank-aware gang
    set-scoring carry in (see _segments_core) — both off reproduce the
    pre-profile program exactly."""
    weights_tuple = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    z = jnp.zeros((1, 1), jnp.int32)
    if rotation_pos is not None:
        assert rotation is None
        rot_mode = 2
        perms = jnp.asarray(rotation_pos[0], jnp.int32)
        inv_perms = z
        oid_seq = jnp.asarray(rotation_pos[1], jnp.int32)
    elif rotation is not None:
        rot_mode = 1
        perms, inv_perms, oid_seq = (jnp.asarray(a, jnp.int32)
                                     for a in rotation)
    else:
        rot_mode = 0
        perms = inv_perms = z
        oid_seq = jnp.zeros(1, jnp.int32)
    mut0 = {k: nodes[k] for k in _MUTABLE}
    carry_spread = spread0 is not None
    s0 = jnp.asarray(spread0, jnp.int64) if carry_spread \
        else jnp.zeros((), jnp.int64)
    profile_mode = wtab is not None or gang_score
    if wtab is not None:
        wtab = jnp.asarray(wtab, jnp.int64)
    if mesh is not None:
        from kubernetes_tpu.parallel import sharding as S
        fn = S.sharded_segments_fn(mesh, z_pad, weights_tuple, rot_mode,
                                   carry_spread,
                                   use_wtab=wtab is not None,
                                   gang_score=bool(gang_score))
        if profile_mode:
            w = wtab if wtab is not None else jnp.zeros(
                (1, len(PRIORITY_AXIS)), jnp.int64)
            return fn(nodes, mut0, pods, jnp.asarray(seg_start, bool),
                      jnp.asarray(gang, bool), _i64(n_pods),
                      _i64(last_index), _i64(last_node_index),
                      _i64(num_to_find), _i64(n_real), perms, inv_perms,
                      oid_seq, s0, w)
        return fn(nodes, mut0, pods, jnp.asarray(seg_start, bool),
                  jnp.asarray(gang, bool), _i64(n_pods), _i64(last_index),
                  _i64(last_node_index), _i64(num_to_find), _i64(n_real),
                  perms, inv_perms, oid_seq, s0)
    if profile_mode:
        w = wtab if wtab is not None else jnp.zeros(
            (1, len(PRIORITY_AXIS)), jnp.int64)
        return _schedule_batch_seg_prof_jit(
            nodes, mut0, pods, jnp.asarray(seg_start, bool),
            jnp.asarray(gang, bool), _i64(n_pods), _i64(last_index),
            _i64(last_node_index), _i64(num_to_find), _i64(n_real), perms,
            inv_perms, oid_seq, s0, w, z_pad, weights_tuple, rot_mode,
            carry_spread, bool(gang_score), wtab is not None)
    return _schedule_batch_seg_jit(
        nodes, mut0, pods, jnp.asarray(seg_start, bool),
        jnp.asarray(gang, bool), _i64(n_pods), _i64(last_index),
        _i64(last_node_index), _i64(num_to_find), _i64(n_real), perms,
        inv_perms, oid_seq, s0, z_pad, weights_tuple, rot_mode,
        carry_spread)


# ---------------------------------------------------------------------------
# Uniform-class burst: every pod in the burst shares one feature class
# ---------------------------------------------------------------------------
# The throughput workloads (ReplicaSet scale-ups; the scheduler_perf plain
# matrix) enqueue thousands of identical pods. For those, per-pod O(N) work
# is provably wasted: at percentageOfNodesToScore=100 with last_index == 0,
# selectHost's round-robin tie walk (generic_scheduler.go:286-295) assigns
# CONSECUTIVE pods to CONSECUTIVE tie ranks — `ix = lastNodeIndex % len(ties)`
# with lastNodeIndex incrementing by 1 — for as long as the tie set itself
# does not change. A node leaves the tie set only when a fold crosses one of
# the integer-truncation boundaries of the score formulas (every ~4th pod on
# a node at the scheduler_perf shape), so in the common regime the tie set is
# stable across hundreds of consecutive decisions.
#
# This kernel therefore schedules K pods per O(N) pass in one of two batch
# modes, chosen each pass by probing lane 0's post-fold state:
#
# - STAY: while every fold leaves its node AT max score and feasible, the
#   tie set is constant and consecutive pods take consecutive tie ranks
#   (lni+j mod T). Validated per lane; cut at the first leaver.
# - ELIM: while every fold REMOVES its node from the tie set (score drops
#   below max, or the placement bans the node — host-port conflicts and
#   self-matching hostname anti-affinity), the serial walk's shrinking
#   modulo `(lni+i) mod (T-i)` resolves to ORIGINAL tie ranks lni+2i for as
#   long as lni+i < T-i (quotient-0 prefix) and found_i = F-i stays > 1.
#   Validated per lane; cut at the first stayer.
#
# Ranks resolve with a vectorized searchsorted, K fold deltas scatter to
# (provably distinct) rows, and the longest valid prefix is accepted (always
# >= 1: pod 0's decision depends only on the pass-start state); the rest
# retry next pass, so the worst case degrades to one pod per pass and
# decisions stay bit-identical to the serial scan in all cases. Failure
# *reasons* are not computed — the shell re-runs unschedulable pods through
# the serial path, which reports them.
#
# The pod count is a DYNAMIC operand of a single lax.while_loop: one compile
# serves every burst size (no bucket padding, no trailing-segment waste).
#
# Eligibility (checked by the caller, tpu_scheduler._uniform_class): pods
# value-identical in requests, fold deltas, labels, and affinity/port specs;
# num_to_find >= n_real and last_index == 0. Per-node masks that cannot
# change in-burst (node selector/affinity, taints, unschedulable, hostname,
# existing-pod affinity state) merge into the static `extra_ok`; in-burst
# interactions reduce to the banned-node fold (`ban`: each placement bans
# its own node for the rest of the burst — exact for identical pods with
# host ports or self-matching hostname anti-affinity). Row-local scores
# shift all nodes equally when constant families (inert taint/spread/
# prefer-avoid, constant interpod counts) are dropped, so argmax and the
# round-robin tie walk match the generic kernel.

K_BATCH = 512        # pods resolved per O(N) pass (static)
B_CAP = 16384        # output-buffer capacity (static); callers chunk above it

# per-window device-arg conversion caches (round 17, serving prologue):
# uniform class scalars keyed by VALUE, the rotation perm table keyed by
# host-array identity (the entry pins the np object so ids cannot recycle)
_UNIFORM_CLS_CACHE: dict = {}
_PERM_DEV_CACHE: dict = {}


def _uniform_core(nodes, cls, n_pods, last_node_index, n_real,
                  perm, oid_seq, extra_ok, weights, flags,
                  b_cap, k_batch, rotate, ban, has_extra, constrain=None,
                  wtab=None, pid=None):
    """Body of the uniform-class burst kernel. `constrain` (optional) pins
    node-axis arrays — the carried [R, N1]/[N1] state and the static alloc
    vectors — to a mesh sharding so the O(N) sweep splits across chips while
    the scalar tie-walk epilogue replicates (parallel/sharding.py wraps this
    for the north-star multi-chip config; None = single-chip identity).

    `wtab`/`pid` (tensor mode): the window's shared weight row is gathered
    ONCE from the resident [P, K] table by the class's profile id — a
    uniform window is single-profile by construction (the profile id is
    part of the window's uniformity contract: different rows change the
    tie structure the K-batch modes rely on), so one compiled program
    serves every profile and the row is just data."""
    if constrain is None:
        constrain = lambda v: v
    wrow = None if wtab is None else wtab[pid]
    check_res, has_req, carry_eph, static_eph, carried_s, static_s = flags
    i32 = jnp.int32
    n_pad = nodes["valid"].shape[0]
    in_range = jnp.arange(n_pad, dtype=i32) < jnp.asarray(n_real, i32)
    ok = nodes["valid"] & in_range
    if has_extra:
        # static per-node masks: node selector/affinity, taints,
        # unschedulable, hostname, existing-pod (anti-)affinity state
        ok &= extra_ok
    if check_res and has_req:
        # resource families whose node-side state cannot change in-burst
        # (fold delta zero) collapse to a static mask
        if static_eph:
            ok &= ~(nodes["alloc_eph"] < cls["req_eph"] + nodes["req_eph"])
        for s in static_s:
            ok &= ~(nodes["alloc_scalar"][:, s]
                    < cls["req_scalar"][s] + nodes["req_scalar"][:, s])

    # one scratch column at index n_pad: inactive scatter/gather lanes park
    # there so active lanes (distinct by construction) never collide
    def pad1(v):
        return jnp.concatenate([v, jnp.zeros(1, v.dtype)])
    ok = constrain(pad1(ok))
    alloc_cpu = constrain(pad1(nodes["alloc_cpu"]))
    alloc_mem = constrain(pad1(nodes["alloc_mem"]))
    allowed = constrain(pad1(nodes["allowed_pods"]))
    alloc_eph = constrain(pad1(nodes["alloc_eph"]))

    rows = [nodes["req_cpu"], nodes["req_mem"], nodes["nz_cpu"],
            nodes["nz_mem"], nodes["pod_count"]]
    delta = [cls["upd_cpu"], cls["upd_mem"], cls["nz_cpu"], cls["nz_mem"], 1]
    ieph = None
    if carry_eph:
        ieph = len(rows)
        rows.append(nodes["req_eph"])
        delta.append(cls["upd_eph"])
    isc0 = len(rows)
    alloc_sc = []
    for s in carried_s:
        rows.append(nodes["req_scalar"][:, s])
        delta.append(cls["upd_scalar"][s])
        alloc_sc.append(constrain(pad1(nodes["alloc_scalar"][:, s])))
    st0 = constrain(jnp.stack([pad1(r) for r in rows]))
    delta_vec = jnp.stack([jnp.asarray(d, jnp.int64) for d in delta])
    I32_MIN = jnp.int32(-2**31)

    tot0 = constrain(_local_total(
        weights, cls["nz_cpu"] + st0[2], cls["nz_mem"] + st0[3],
        alloc_cpu, alloc_mem, wrow=wrow).astype(i32))
    jlane = jnp.arange(k_batch, dtype=i32)
    B = jnp.asarray(n_pods, i32)

    def resource_fit(rowvals, idx):
        """PodFitsResources for the incoming pod against row state `rowvals`
        ([R] or [R, K]) at node(s) `idx` — shared by the sweep and the
        post-fold stays check so the two cannot drift."""
        fit = ok[idx] if idx is not None else ok
        a_cpu = alloc_cpu[idx] if idx is not None else alloc_cpu
        a_mem = alloc_mem[idx] if idx is not None else alloc_mem
        a_pods = allowed[idx] if idx is not None else allowed
        if check_res:
            fit &= rowvals[4] + 1 <= a_pods
            if has_req:
                fit &= (a_cpu >= cls["req_cpu"] + rowvals[0]) \
                    & (a_mem >= cls["req_mem"] + rowvals[1])
                if carry_eph:
                    a_eph = alloc_eph[idx] if idx is not None else alloc_eph
                    fit &= a_eph >= cls["req_eph"] + rowvals[ieph]
                for jj, s in enumerate(carried_s):
                    a_s = alloc_sc[jj][idx] if idx is not None else alloc_sc[jj]
                    fit &= a_s >= cls["req_scalar"][s] + rowvals[isc0 + jj]
        return fit

    def lane_fit(rowvals, idx):
        """Post-fold score + feasibility of selected rows — shared by the
        lane-0 probe and the batch validation."""
        nt = _local_total(
            weights, cls["nz_cpu"] + rowvals[2], cls["nz_mem"] + rowvals[3],
            alloc_cpu[idx], alloc_mem[idx], wrow=wrow).astype(i32)
        return nt, resource_fit(rowvals, idx)

    def body(carry):
        st, tot, banned, lni, done, out = carry
        feas = resource_fit(st, None)
        if ban:
            feas &= ~banned
        tm = jnp.where(feas, tot, I32_MIN)
        mx = jnp.max(tm)
        tie = feas & (tm == mx)
        T = jnp.sum(tie, dtype=i32)
        F = jnp.sum(feas, dtype=i32)
        T64 = T.astype(jnp.int64)
        remaining = B - done
        # the multi-pod paths need >= 2 ties (a single-tie fold can change
        # num_ties, shifting the modulo walk) and F > 1 (so lastNodeIndex
        # advances exactly 1 per pod); F == 0 means every remaining pod is
        # equally unschedulable -> emit-all -1
        kbig = (T >= 2) & (F > 1)
        if rotate:
            oid = jax.lax.dynamic_slice(oid_seq, (done,), (k_batch,))
            tie_perm = tie[perm]                     # [L, N1]
            C_all = jnp.cumsum(tie_perm.astype(i32), axis=1)
        else:
            C = jnp.cumsum(tie.astype(i32))

        # -- lane-0 probe: pick STAY vs ELIM batching (identical position
        # formula at lane 0, so the probe is mode-neutral)
        if ban:
            elim = kbig        # a placement always bans its own node
        else:
            pos0 = (lni % jnp.maximum(T64, 1)).astype(i32)
            if rotate:
                c0 = C_all[oid[0]]
                p0 = jnp.sum(c0 < pos0 + 1, dtype=i32)
                sel0 = perm[oid[0], jnp.minimum(p0, n_pad)]
            else:
                sel0 = jnp.searchsorted(C, pos0 + 1,
                                        method="compare_all").astype(i32)
            nt0, fit0 = lane_fit(st[:, sel0] + delta_vec, sel0)
            elim = ((nt0 != mx) | ~fit0) & kbig

        m_stay = jnp.minimum(jnp.minimum(remaining, k_batch), T)
        # ELIM quotient-0 prefix: lni + i < T - i, i.e. m <= (T - lni + 1)/2;
        # bans shrink F, so m <= F - 1 keeps found_i > 1 for every lane
        max_elim = jnp.maximum(((T64 - lni + 1) // 2).astype(i32), 1)
        m_elim = jnp.minimum(jnp.minimum(remaining, k_batch),
                             jnp.minimum(max_elim, jnp.maximum(F - 1, 1)))
        if rotate:
            # the original-rank formula assumes ONE tie order; limit the
            # batch to this pass's constant-order prefix (ranks are distinct
            # within one order, so the rank->node map stays consistent).
            # Identity-heavy walks — uneven-zone clusters whose cursor sits
            # at a fixed point — keep FULL ELIM batching this way.
            same = jnp.cumprod((oid == oid[0]).astype(i32), dtype=i32)
            m_elim = jnp.minimum(m_elim, jnp.maximum(
                jnp.sum(same, dtype=i32), 1))
        m = jnp.where(F == 0, jnp.minimum(remaining, k_batch),
                      jnp.where(elim, m_elim,
                                jnp.where(kbig, m_stay, 1)))
        active = (jlane < m) & (F > 0)
        j64 = jlane.astype(jnp.int64)
        pos_stay = ((lni + j64) % jnp.maximum(T64, 1)).astype(i32)
        pos_elim = jnp.minimum(lni + 2 * j64,
                               jnp.maximum(T64 - 1, 0)).astype(i32)
        pos = jnp.where(elim & (m > 1), pos_elim, pos_stay)
        if not rotate:
            # stable per-cycle order == the device axis: tie rank -> node via
            # one cumsum (positions are distinct for the chosen mode's valid
            # prefix, so active lanes never collide)
            selq = jnp.searchsorted(C, pos + 1, method="compare_all").astype(i32)
            sel = jnp.where(active, selq, n_pad)
        else:
            # per-cycle rotated orders: lane j ranks ties in the order of ITS
            # cycle (done + j), one of the <= L distinct zone-interleaved
            # enumerations in `perm` (NodeTree.order_for_start)
            crows = C_all[oid]                       # [K, N1]
            posp = jnp.sum(crows < (pos + 1)[:, None], axis=1, dtype=i32)
            selq = perm[oid, jnp.minimum(posp, n_pad)]
            sel = jnp.where(active, selq, n_pad)
        rows_after = st[:, sel] + delta_vec[:, None]
        new_tot, fit_after = lane_fit(rows_after, sel)
        # serial equivalence per lane: STAY needs every earlier fold to leave
        # its node AT max score and feasible (tie set unchanged); ELIM needs
        # every earlier fold to REMOVE its node (rank formula). Either way
        # the first offender's own decision is still exact -> cut after it.
        leaves = jnp.ones_like(fit_after) if ban \
            else ((new_tot != mx) | ~fit_after)
        fail = jnp.where(elim, ~leaves, leaves) & active
        first_bad = jnp.where(jnp.any(fail), jnp.argmax(fail).astype(i32),
                              jnp.int32(k_batch))
        v = jnp.where(F == 0, m, jnp.minimum(first_bad + 1, m))
        if rotate:
            # distinct ranks under DIFFERENT orders can name the same node;
            # the second fold would see stale state — cut the batch before
            # the first duplicate (it retries next pass)
            owner = jnp.full(n_pad + 1, k_batch, i32).at[sel].min(
                jnp.where(active, jlane, k_batch))
            dup = active & (owner[sel] != jlane)
            first_dup = jnp.where(jnp.any(dup), jnp.argmax(dup).astype(i32),
                                  jnp.int32(k_batch))
            v = jnp.minimum(v, first_dup)
            # F==0 emits no selections, so the dup cut (which needs F>0
            # lanes) cannot zero it: active is all-False there and v stays m
            v = jnp.where(F == 0, m, jnp.maximum(v, 1))
        accept = active & (jlane < v)
        st = st.at[:, sel].add(
            jnp.where(accept[None, :], delta_vec[:, None], 0))
        # route non-accepted lanes to the scratch column: under rotation a
        # rejected lane's sel may DUPLICATE an accepted lane's node, and a
        # duplicate .set would clobber the accepted score write
        selw = jnp.where(accept, sel, n_pad)
        tot = tot.at[selw].set(new_tot)
        if ban:
            banned = banned.at[selw].max(accept)
        emit = jnp.where((jlane < v) & (F > 0), sel, -1)
        out = jax.lax.dynamic_update_slice(out, emit, (done,))
        lni = lni + jnp.where(F > 1, v, 0).astype(jnp.int64)
        return (constrain(st), constrain(tot), constrain(banned),
                lni, done + v, out)

    out0 = jnp.full(b_cap + k_batch, -1, i32)
    lni0 = jnp.asarray(last_node_index, jnp.int64)
    banned0 = constrain(jnp.zeros(n_pad + 1, dtype=bool))
    st, tot, _banned, lni, done, out = jax.lax.while_loop(
        lambda c: c[4] < B, body, (st0, tot0, banned0, lni0, jnp.int32(0), out0))
    # pack the lastNodeIndex advance into the selection buffer so the caller
    # fetches ONE array — each separate device->host read pays a full
    # dispatch round trip (~100ms over a tunneled device)
    out = out.at[b_cap].set((lni - lni0).astype(i32))

    unpad = lambda v: v[:n_pad]
    out_rows = {"req_cpu": unpad(st[0]), "req_mem": unpad(st[1]),
                "nz_cpu": unpad(st[2]), "nz_mem": unpad(st[3]),
                "pod_count": unpad(st[4])}
    if carry_eph:
        out_rows["req_eph"] = unpad(st[ieph])
    if carried_s:
        rs = nodes["req_scalar"]
        for jj, s in enumerate(carried_s):
            rs = rs.at[:, s].set(unpad(st[isc0 + jj]))
        out_rows["req_scalar"] = rs
    # the absolute lastNodeIndex stays DEVICE-RESIDENT so a pipelined wave
    # k+1 can launch from wave k's counter without a host round trip (the
    # packed delta above still lets the host track it from the fetch)
    return out_rows, out[: b_cap + 1], lni


@partial(jax.jit, static_argnames=("weights_tuple", "flags", "b_cap", "k_batch",
                                   "rotate", "ban", "has_extra"))
def _schedule_batch_uniform_jit(nodes, cls, n_pods, last_node_index, n_real,
                                perm, oid_seq, extra_ok, weights_tuple, flags,
                                b_cap, k_batch, rotate, ban, has_extra):
    return _uniform_core(nodes, cls, n_pods, last_node_index, n_real, perm,
                         oid_seq, extra_ok, dict(weights_tuple), flags, b_cap,
                         k_batch, rotate, ban, has_extra)


@partial(jax.jit, static_argnames=("weights_tuple", "flags", "b_cap",
                                   "k_batch", "rotate", "ban", "has_extra"))
def _schedule_batch_uniform_prof_jit(nodes, cls, n_pods, last_node_index,
                                     n_real, perm, oid_seq, extra_ok, wtab,
                                     pid, weights_tuple, flags, b_cap,
                                     k_batch, rotate, ban, has_extra):
    return _uniform_core(nodes, cls, n_pods, last_node_index, n_real, perm,
                         oid_seq, extra_ok, dict(weights_tuple), flags, b_cap,
                         k_batch, rotate, ban, has_extra, wtab=wtab, pid=pid)


def schedule_batch_uniform(nodes, cls, n_pods, last_node_index, n_real,
                           check_resources, weights=None, rotation=None,
                           extra_ok=None, ban=False, mesh=None, cap=None,
                           wtab=None, pid=0):
    """Uniform-class burst (see block comment above). `cls` holds the shared
    per-pod scalars: req_cpu/req_mem/req_eph, req_scalar[S], nz_cpu/nz_mem,
    upd_cpu/upd_mem/upd_eph, upd_scalar[S], has_request. Returns
    (folded_state_rows, packed[B_CAP+1], lni_device) where packed[:n_pods]
    are per-pod node indices (-1 = unschedulable), packed[B_CAP] is the
    lastNodeIndex advance — one array, one host fetch — and lni_device is
    the absolute post-burst lastNodeIndex as a device scalar, so a
    pipelined wave can pass it straight into the next launch
    (`last_node_index` accepts a device scalar or a host int). `n_pods`
    must be <= B_CAP; chunk larger bursts.

    `cap` (static, default B_CAP) sizes the packed output buffer: wave
    callers pass their fixed wave bucket so the per-wave fetch ships
    cap+1 int32s instead of the full 16K buffer (the lni-advance slot
    moves to packed[cap]).

    `rotation` = None when the per-cycle NodeTree enumeration is stable and
    equals the device axis; otherwise (perm[L, n_pad+1] int32 — the <= L
    distinct per-cycle orders as axis indices, scratch-padded — and
    oid_seq[B_CAP + K_BATCH] int32 — cycle t's order id, t counted from this
    burst's first pod).

    `extra_ok` [n_pad] bool merges burst-static per-node masks into
    feasibility; `ban=True` makes every placement ban its own node for the
    rest of the burst (identical pods with host ports / self-matching
    hostname anti-affinity)."""
    cap = B_CAP if cap is None else int(cap)
    if n_pods > cap:
        raise ValueError(f"uniform burst of {n_pods} exceeds cap={cap}")
    weights_tuple = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    # class scalars + derived flags + device conversion, cached by VALUE:
    # a serving loop dispatches hundreds of same-class windows per second,
    # and the eleven per-field jnp conversions were a measurable slice of
    # each window's encode span
    cls_key = (int(cls["req_cpu"]), int(cls["req_mem"]),
               int(cls["req_eph"]), cls["req_scalar"].tobytes(),
               int(cls["nz_cpu"]), int(cls["nz_mem"]),
               int(cls["upd_cpu"]), int(cls["upd_mem"]),
               int(cls["upd_eph"]), cls["upd_scalar"].tobytes(),
               bool(cls["has_request"]))
    hit = _UNIFORM_CLS_CACHE.get(cls_key)
    if hit is None:
        has_req = bool(cls.pop("has_request"))
        carry_eph = bool(cls["upd_eph"] != 0)
        static_eph = bool(not carry_eph and cls["req_eph"] != 0)
        carried_s = tuple(int(s) for s in range(len(cls["req_scalar"]))
                          if cls["upd_scalar"][s] != 0)
        static_s = tuple(int(s) for s in range(len(cls["req_scalar"]))
                         if cls["req_scalar"][s] != 0
                         and cls["upd_scalar"][s] == 0)
        cls_dev = {k: jnp.asarray(v, jnp.int64) for k, v in cls.items()}
        if len(_UNIFORM_CLS_CACHE) >= 64:
            _UNIFORM_CLS_CACHE.clear()
        hit = _UNIFORM_CLS_CACHE[cls_key] = (
            has_req, carry_eph, static_eph, carried_s, static_s, cls_dev)
    has_req, carry_eph, static_eph, carried_s, static_s, cls = hit
    flags = (bool(check_resources), has_req, carry_eph, static_eph,
             carried_s, static_s)
    if rotation is None:
        perm = jnp.zeros((1, 1), jnp.int32)      # unused placeholder
        oid_seq = jnp.zeros(1, jnp.int32)
    else:
        # the perm table is stable across a serving run's windows (cached
        # rows upstream): convert once per distinct host array, verified
        # by identity (the cache pins the np object, so ids can't recycle)
        ent = _PERM_DEV_CACHE.get(id(rotation[0]))
        if ent is None or ent[0] is not rotation[0]:
            if len(_PERM_DEV_CACHE) >= 64:
                _PERM_DEV_CACHE.clear()
            ent = (rotation[0], jnp.asarray(rotation[0], jnp.int32))
            _PERM_DEV_CACHE[id(rotation[0])] = ent
        perm = ent[1]
        oid_seq = jnp.asarray(rotation[1], jnp.int32)
    has_extra = extra_ok is not None
    extra = jnp.asarray(extra_ok, bool) if has_extra \
        else jnp.zeros(1, dtype=bool)
    if wtab is not None:
        wtab = jnp.asarray(wtab, jnp.int64)
    if mesh is not None:
        # north-star multi-chip config: node-axis state sharded over the
        # mesh, tie-walk epilogue replicated (parallel/sharding.py)
        from kubernetes_tpu.parallel import sharding as S
        fn = S.sharded_uniform_fn(mesh, weights_tuple, flags, cap, K_BATCH,
                                  rotation is not None, bool(ban), has_extra,
                                  use_wtab=wtab is not None)
        if wtab is not None:
            return fn(nodes, cls, _i64(n_pods), _i64(last_node_index),
                      _i64(n_real), perm, oid_seq, extra, wtab, _i64(pid))
        return fn(nodes, cls, _i64(n_pods), _i64(last_node_index),
                  _i64(n_real), perm, oid_seq, extra)
    if wtab is not None:
        return _schedule_batch_uniform_prof_jit(
            nodes, cls, _i64(n_pods), _i64(last_node_index), _i64(n_real),
            perm, oid_seq, extra, wtab, _i64(pid), weights_tuple, flags,
            cap, K_BATCH, rotation is not None, bool(ban), has_extra)
    return _schedule_batch_uniform_jit(
        nodes, cls, _i64(n_pods), _i64(last_node_index), _i64(n_real),
        perm, oid_seq, extra, weights_tuple, flags, cap, K_BATCH,
        rotation is not None, bool(ban), has_extra)


# ---------------------------------------------------------------------------
# Device preemption: vmapped victim selection + node pick
# ---------------------------------------------------------------------------
# Mirror of selectNodesForPreemption/selectVictimsOnNode/pickOneNode
# (generic_scheduler.go:966,1054,837). The reference fans victim selection
# out over 16 goroutines; here every candidate node runs at once:
#
#   1. remove ALL lower-priority pods per node, check the incoming pod fits
#   2. reprieve loop: victims arrive ALREADY SORTED by the host into the
#      reference's processing order (PDB-violating first, each group by
#      descending importance = priority desc, start asc); a lax.scan re-adds
#      one per step and keeps it iff the pod still fits
#   3. per-node aggregates feed the staged 5-criteria pick: fewest PDB
#      violations -> lowest FIRST-victim priority (the reference reads
#      Pods[0], :876) -> smallest sum of (priority + 2^31) -> fewest victims
#      -> latest earliest-start among the highest-priority victims -> first
#      in candidate order.
#
# Eligibility (host-checked): the fit that matters is resources + static
# masks only — no affinity/ports/volumes on the incoming pod or any
# potential victim, no active nominations. Anything else runs the oracle.

PREEMPT_P = 128    # victim slots per node (>= AllowedPodNumber cap of 110)


def _victim_select(nodes, vic, valid_v, req_cpu, req_mem, req_eph,
                   ghost, feas_static, check_res, has_req, constrain=None):
    """selectVictimsOnNode over every node at once (:1054): remove all
    masked victims, check fit, then the order-dependent reprieve scan.
    `valid_v` [N, P] masks which slots are potential victims FOR THIS
    preemptor (priority < preemptor's); `ghost` ({cpu,mem,eph,cnt} [N] or
    None) adds non-removable nominated-pod usage — selectVictimsOnNode's
    fit runs the two-pass with them added (preemption.py:277), and for
    resource-only ghosts the without-pass is implied. `check_res`/`has_req`
    may be Python bools or traced booleans. Returns (feas0[N], victims[N,P],
    aggregates dict for the node pick). `constrain` (optional) pins the
    reprieve scan's [N] carry to a mesh sharding — the per-slot scan then
    runs every node row shard-local."""
    if constrain is None:
        constrain = lambda v: v
    i64, f64 = jnp.int64, jnp.float64
    n_pad = nodes["alloc_cpu"].shape[0]
    cr = jnp.asarray(check_res, bool)
    hr = jnp.asarray(has_req, bool) & cr
    nvic_all = jnp.sum(valid_v, axis=1, dtype=i64)
    base_cpu = nodes["req_cpu"] - jnp.sum(
        jnp.where(valid_v, vic["cpu"], 0), axis=1)
    base_mem = nodes["req_mem"] - jnp.sum(
        jnp.where(valid_v, vic["mem"], 0), axis=1)
    base_eph = nodes["req_eph"] - jnp.sum(
        jnp.where(valid_v, vic["eph"], 0), axis=1)
    base_cnt = nodes["pod_count"] - nvic_all
    if ghost is not None:
        base_cpu = base_cpu + ghost["cpu"]
        base_mem = base_mem + ghost["mem"]
        base_eph = base_eph + ghost["eph"]
        base_cnt = base_cnt + ghost["cnt"]

    def fits(rc, rm, re, pc):
        f = jnp.ones(n_pad, dtype=bool)
        f &= ~cr | (pc + 1 <= nodes["allowed_pods"])
        f &= ~hr | ((nodes["alloc_cpu"] >= req_cpu + rc)
                    & (nodes["alloc_mem"] >= req_mem + rm)
                    & (nodes["alloc_eph"] >= req_eph + re))
        return f

    feas0 = feas_static & fits(base_cpu, base_mem, base_eph, base_cnt)

    def step(carry, xs):
        rc, rm, re, pc = carry
        vcpu, vmem, veph, vval = xs
        nrc, nrm, nre = rc + vcpu, rm + vmem, re + veph
        npc = pc + jnp.where(vval, 1, 0)
        keep = fits(nrc, nrm, nre, npc) & vval & feas0
        return (constrain((jnp.where(keep, nrc, rc), jnp.where(keep, nrm, rm),
                           jnp.where(keep, nre, re), jnp.where(keep, npc, pc))),
                vval & ~keep)

    xs = (vic["cpu"].T, vic["mem"].T, vic["eph"].T, valid_v.T)   # [P, N]
    _carry, victim_t = jax.lax.scan(
        step, (base_cpu, base_mem, base_eph, base_cnt), xs)
    victims = victim_t.T & feas0[:, None]            # [N, P]

    nv = jnp.sum(victims, axis=1, dtype=i64)
    viol_ct = jnp.sum(victims & vic["violating"], axis=1, dtype=i64)
    first_idx = jnp.argmax(victims, axis=1)
    first_prio = jnp.take_along_axis(
        vic["prio"], first_idx[:, None], axis=1)[:, 0]
    sum_prio = jnp.sum(
        jnp.where(victims, vic["prio"] + (1 << 31), 0), axis=1)
    I64_MIN = jnp.iinfo(i64).min
    high = jnp.max(jnp.where(victims, vic["prio"], I64_MIN), axis=1)
    INF = jnp.asarray(jnp.inf, f64)
    earliest_high = jnp.min(
        jnp.where(victims & (vic["prio"] == high[:, None]),
                  vic["start"], INF), axis=1)
    return feas0, victims, {"nv": nv, "viol_ct": viol_ct,
                            "first_prio": first_prio, "sum_prio": sum_prio,
                            "earliest_high": earliest_high}


def _pick_one_node(feas0, agg, order_rank):
    """pickOneNodeForPreemption (:837): zero-victim instant win, then the
    staged 5-criteria reduction, ties broken by first-in-candidate-order
    (`order_rank` — any strictly order-isomorphic ranking works)."""
    i32, i64, f64 = jnp.int32, jnp.int64, jnp.float64
    INF = jnp.asarray(jnp.inf, f64)
    any_cand = jnp.any(feas0)
    zerov = feas0 & (agg["nv"] == 0)
    rank = jnp.asarray(order_rank, i64)
    BIGR = jnp.asarray(1 << 60, i64)

    def argmin_rank(mask):
        return jnp.argmin(jnp.where(mask, rank, BIGR)).astype(i32)

    m = feas0
    for crit in (agg["viol_ct"].astype(f64),
                 agg["first_prio"].astype(f64),
                 agg["sum_prio"].astype(f64),
                 agg["nv"].astype(f64),
                 -agg["earliest_high"]):
        # +-inf criteria are fine: IEEE inf == inf keeps the equality
        # matching exact (None start times read as +inf, :176-180)
        best = jnp.min(jnp.where(m, crit, INF))
        m &= jnp.where(m, crit, INF) == best
    winner = jnp.where(jnp.any(zerov), argmin_rank(zerov), argmin_rank(m))
    return jnp.where(any_cand, winner, -1)


def _preempt_scan_core(nodes, vic, pod, feas_static, order_rank, n_real,
                       max_prio, check_res, has_req, constrain=None):
    i32 = jnp.int32
    n_pad = nodes["alloc_cpu"].shape[0]
    in_range = jnp.arange(n_pad, dtype=i32) < jnp.asarray(n_real, i32)
    # the resident victim table holds EVERY snapshot pod in reprieve order;
    # this preemptor's potential-victim mask is one device-side compare
    # (the sort key is priority-monotone, so masking preserves the order)
    valid_v = vic["valid"] & (vic["prio"] < max_prio)
    feas0, victims, agg = _victim_select(
        nodes, vic, valid_v, pod["req_cpu"], pod["req_mem"],
        pod["req_eph"], None, feas_static & in_range, check_res, has_req,
        constrain=constrain)
    winner = _pick_one_node(feas0, agg, order_rank)
    w = jnp.maximum(winner, 0)
    out = jnp.concatenate([
        jnp.stack([winner.astype(i32),
                   agg["nv"][w].astype(i32), agg["viol_ct"][w].astype(i32)]),
        victims[w].astype(i32)])
    return out


@partial(jax.jit, static_argnames=("check_res", "has_req"))
def _preemption_scan_jit(nodes, vic, pod, feas_static, order_rank, n_real,
                         max_prio, check_res, has_req):
    return _preempt_scan_core(nodes, vic, pod, feas_static, order_rank,
                              n_real, max_prio, check_res, has_req)


def preemption_scan(nodes, vic, pod, feas_static, order_rank, n_real,
                    check_resources, has_request, max_prio, mesh=None):
    """One launch over all candidate nodes. `vic` arrays are [N, P] slot
    planes of the persistent victim table — ALL snapshot pods pre-sorted
    into reprieve processing order per node; slots of priority >= `max_prio`
    (the preemptor's) are masked out on device. Returns packed i32
    [3 + P]: winner node index (-1 = no candidate), its victim count and
    PDB-violation count, then the winner's per-slot victim flags (aligned
    to the sorted order the host supplied). `mesh` runs the same scan with
    the node axis (rows + victim planes) sharded across the mesh."""
    if mesh is not None:
        from kubernetes_tpu.parallel import sharding as S
        fn = S.sharded_preempt_fn(mesh, bool(check_resources),
                                  bool(has_request))
        return fn(nodes, vic, pod, feas_static, order_rank, _i64(n_real),
                  _i64(max_prio))
    return _preemption_scan_jit(nodes, vic, pod, feas_static, order_rank,
                                _i64(n_real), _i64(max_prio),
                                bool(check_resources), bool(has_request))


# ---------------------------------------------------------------------------
# Batched preemption pressure: schedule-else-preempt scan over a failed tail
# ---------------------------------------------------------------------------
# The serial failure path pays one dispatch+readback round trip (~100ms over
# a tunneled chip) PER failed pod: schedule -> FitError -> victim scan ->
# nominate. This kernel runs the whole failed tail in ONE launch, replaying
# the reference's serial semantics exactly (scheduleOne -> preempt per pod,
# scheduler.go:438,292):
#
#   per pod, in queue order (priorities non-increasing — host-gated):
#   1. one _cycle_core schedule attempt with accumulated nominated-ghost
#      usage (podFitsOnNode two-pass, :598,627 — for resource-only ghosts
#      pass 2 is implied); a success folds its delta into the node state
#      like the burst kernel and consumes rotation/tie counters.
#   2. on failure, the victim scan (selectVictimsOnNode :1054 semantics,
#      _victim_select) over every node with this preemptor's victim mask
#      (slot priority < preemptor priority) and the ghost-augmented base
#      load; the 5-criteria pick chooses the node (:837); the winner's
#      usage folds into the ghost vector so later pods see the nomination.
#
#   `any_cand` replays nodesWherePreemptionMightHelp (:1142) from the
#   cycle's fail-first codes: a node is a candidate unless its FIRST
#   failing predicate's reasons contain an unresolvable member (:65-84) —
#   the caller needs this to distinguish "no candidates" (clear the pod's
#   own stale nomination, :330-333) from "candidates but no fit".


def _resolvable_candidates(fail_first, general_bits):
    """nodesWherePreemptionMightHelp from device fail codes: recorded
    failure reasons are the FIRST failing predicate's (pod_fits_on_node
    breaks on first failure); GENERAL carries host/selector bits whose
    reasons are unresolvable (generic_scheduler.go:65-84)."""
    unresolv = ((fail_first == FAIL_UNSCHEDULABLE)
                | (fail_first == FAIL_TAINTS)
                | (fail_first == FAIL_VOLZONE)
                | (fail_first == FAIL_VOLBIND)
                | ((fail_first == FAIL_GENERAL)
                   & (((general_bits >> BIT_HOST) & 1)
                      | ((general_bits >> BIT_SELECTOR) & 1)).astype(bool)))
    return ~unresolv


def _pressure_core(nodes, mut0, ghost0, pods, vic, last_index,
                   last_node_index, num_to_find, n_real, z_pad,
                   weights, constrain=None):
    """Body of the schedule-else-preempt pressure kernel. `constrain`
    (optional) pins the node-axis carries — the mutable rows and the
    accumulated nominated-ghost load — to a mesh sharding each step
    (parallel/sharding.py; None = single-chip identity). The victim planes
    are [N, P] node-axis-first and ride the callers' sharded upload."""
    if constrain is None:
        constrain = lambda v: v
    i32 = jnp.int32
    static = {k: v for k, v in nodes.items() if k not in _MUTABLE}
    n_pad = nodes["alloc_cpu"].shape[0]
    in_range = jnp.arange(n_pad, dtype=i32) < jnp.asarray(n_real, i32)
    axis_rank = jnp.arange(n_pad, dtype=jnp.int64)

    def step(carry, pod):
        mut, ghost, li, lni = carry
        full = {**static, **mut}
        out = _cycle_core(full, pod, li, lni, num_to_find, n_real, weights,
                          z_pad, ghost=ghost)
        sel = out["selected"]
        hit = out["found"] > 0
        skip = jnp.any(pod["skip"])
        mut2 = constrain(_fold_state(mut, pod, sel, hit))
        # victim scan with this preemptor's mask and the ghost base. The
        # static feasibility is the pod's own mask families (victim removal
        # cannot change them — eligibility host-gated): a winner must pass
        # every non-resource predicate outright.
        feas_stat = in_range & static["valid"]
        for key in ("sel_ok", "taints_ok", "unsched_ok", "host_ok",
                    "ports_ok", "disk_ok", "maxvol_ok", "volbind_ok",
                    "volzone_ok"):
            feas_stat = feas_stat & pod[key]
        feas_stat = feas_stat & (pod["interpod_code"] == 0)
        valid_k = vic["valid"] & (vic["prio"] < pod["pprio"])
        feas0, victims, agg = _victim_select(
            {**static, **mut}, vic, valid_k, pod["req_cpu"], pod["req_mem"],
            pod["req_eph"], ghost, feas_stat, pod["check_resources"],
            pod["has_request"], constrain=constrain)
        winner_raw = _pick_one_node(feas0, agg, axis_rank)
        cand = in_range & _resolvable_candidates(out["fail_first"],
                                                 out["general_bits"])
        any_cand = jnp.any(cand) & ~hit & ~skip
        preempted = (~hit) & (~skip) & (winner_raw >= 0)
        winner = jnp.where(hit, -2, jnp.where(skip, -1, winner_raw))
        w = jnp.maximum(winner_raw, 0)
        ghost2 = constrain({
            "cpu": ghost["cpu"].at[w].add(
                jnp.where(preempted, pod["upd_cpu"], 0)),
            "mem": ghost["mem"].at[w].add(
                jnp.where(preempted, pod["upd_mem"], 0)),
            "eph": ghost["eph"].at[w].add(
                jnp.where(preempted, pod["upd_eph"], 0)),
            "cnt": ghost["cnt"].at[w].add(jnp.where(preempted, 1, 0)),
        })
        return ((mut2, ghost2, out["next_last_index"],
                 out["next_last_node_index"]), {
            "selected": jnp.where(hit, sel, -1),
            "winner": winner,
            "any_cand": any_cand,
            "victims": victims[w].astype(jnp.int8),
        })

    init = (constrain(mut0), constrain(ghost0), last_index, last_node_index)
    (mut, ghost, li, lni), outs = jax.lax.scan(step, init, pods)
    return mut, ghost, li, lni, outs


@partial(jax.jit, static_argnames=("z_pad", "weights_tuple"))
def _pressure_batch_jit(nodes, mut0, ghost0, pods, vic, last_index,
                        last_node_index, num_to_find, n_real, z_pad,
                        weights_tuple):
    return _pressure_core(nodes, mut0, ghost0, pods, vic, last_index,
                          last_node_index, num_to_find, n_real, z_pad,
                          dict(weights_tuple))


def pressure_batch(nodes, mut0, ghost0, pods, vic, last_index,
                   last_node_index, num_to_find, n_real, z_pad, weights=None,
                   mesh=None):
    """Schedule-else-preempt a failed burst tail in one launch. `pods` is a
    dict of [B, ...] stacked arrays (including `pprio` [B] preemptor
    priorities and the upd_* fold fields); `vic` arrays are [N, P] with ALL
    pods of priority < the batch maximum, pre-sorted per node into the
    reprieve processing order. Returns (mut_state, ghost, li, lni, outs)
    where outs carries per-pod: selected (>=0 bound host row, -1 failed),
    winner (-2 bound, -1 no preemption, >=0 nominated node row), any_cand,
    and the winner's victim slot flags [P]. `mesh` runs the same
    _pressure_core program with the node axis (mutable rows, ghost load,
    victim planes) sharded across the mesh — decisions bit-identical."""
    weights_tuple = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    if mesh is not None:
        from kubernetes_tpu.parallel import sharding as S
        fn = S.sharded_pressure_fn(mesh, z_pad, weights_tuple)
        return fn(nodes, mut0, ghost0, pods, vic, _i64(last_index),
                  _i64(last_node_index), _i64(num_to_find), _i64(n_real))
    return _pressure_batch_jit(nodes, mut0, ghost0, pods, vic,
                               _i64(last_index), _i64(last_node_index),
                               _i64(num_to_find), _i64(n_real), z_pad,
                               weights_tuple)
