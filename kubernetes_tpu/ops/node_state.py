"""Dense node-state encoding: the NodeInfo snapshot as a struct-of-arrays.

The host keeps a numpy mirror of the per-node aggregates the predicates and
priorities read (reference: pkg/scheduler/nodeinfo/node_info.go:47,139); each
scheduling cycle uploads it (or just the changed rows) to HBM, where the
fused kernel evaluates every node at once. The node axis is ordered by the
cache's zone-interleaved NodeTree enumeration, padded to a static capacity so
XLA never recompiles as the cluster grows within a bucket.

String-world features (labels, taints, selectors, topology keys) are
dictionary-encoded host-side per pod into dense masks/counts — the shape the
device consumes (SURVEY §7 "Set/string matching on device").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kubernetes_tpu.api.types import (
    Pod, Taint, NO_SCHEDULE, NO_EXECUTE, PREFER_NO_SCHEDULE,
    TAINT_NODE_UNSCHEDULABLE, get_resource_request, get_pod_nonzero_requests,
    get_container_ports, get_zone_key, tolerations_tolerate_taint,
    find_intolerable_taint, has_pod_affinity_terms,
)
from kubernetes_tpu.cache.node_info import NodeInfo, normalized_image_name
from kubernetes_tpu.oracle.predicates import (
    pod_matches_node_selector_and_affinity, pod_matches_term_props,
    pod_matches_term_props_mask, selector_match_mask,
    InterPodAffinityChecker,
)
from kubernetes_tpu.oracle.priorities import get_selectors
from kubernetes_tpu import obs

# mirror-maintenance counters: how often the host mirror pays a per-row
# re-extract vs the cheap whole-mirror permute vs a full rebuild (the
# encode-path cost hierarchy PR 1 optimized; /metrics now shows which
# branch a workload actually takes)
ROW_REENCODES = obs.counter(
    "tpu_encoder_dirty_row_reencodes_total",
    "Mirror rows re-extracted because their NodeInfo generation moved.")
MIRROR_PERMUTES = obs.counter(
    "tpu_encoder_mirror_permutes_total",
    "Whole-mirror permutations for a rotated enumeration of the same "
    "node set (instead of per-row re-encodes).")
MIRROR_REBUILDS = obs.counter(
    "tpu_encoder_mirror_rebuilds_total",
    "Full mirror rebuilds (capacity, vocab, or node-membership change).")
VICTIM_ROW_RESORTS = obs.counter(
    "tpu_victim_table_row_resorts_total",
    "Victim-table node rows re-sorted (generation moved or the PDB set "
    "changed); the steady state is zero — scans read the cached table.")
VICTIM_REBUILDS = obs.counter(
    "tpu_victim_table_rebuilds_total",
    "Full victim-table rebuilds (capacity or node-membership change).")


def _pad_capacity(n: int, minimum: int = 8) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


@dataclass
class NodeBatch:
    """Host-side numpy mirror of the device node matrix.

    All integer fields are int64 (reference resource math is int64). Rows
    [n_real:] are padding with valid=False.
    """
    names: list[str]
    index: dict[str, int]
    n_real: int
    n_pad: int
    scalar_names: list[str]            # extended-resource vocab
    zone_names: list[str]              # zone vocab; index 0 reserved for ""
    valid: np.ndarray                  # [N] bool
    alloc_cpu: np.ndarray              # [N] i64 milli
    alloc_mem: np.ndarray              # [N] i64 bytes
    alloc_eph: np.ndarray              # [N] i64 bytes
    allowed_pods: np.ndarray           # [N] i64
    req_cpu: np.ndarray                # [N] i64
    req_mem: np.ndarray                # [N] i64
    req_eph: np.ndarray                # [N] i64
    nz_cpu: np.ndarray                 # [N] i64 (NonZeroRequest)
    nz_mem: np.ndarray                 # [N] i64
    pod_count: np.ndarray              # [N] i64
    alloc_scalar: np.ndarray           # [N,S] i64
    req_scalar: np.ndarray             # [N,S] i64
    zone_id: np.ndarray                # [N] i32 (0 = no zone)
    # rows rewritten by the latest encode(); None = full rebuild. Consumed by
    # the device mirror to upload only generation-dirty rows (SURVEY §2.4).
    dirty_rows: Optional[list] = None


class NodeStateEncoder:
    """Builds/refreshes a NodeBatch from a cache snapshot.

    Incremental: rows are rewritten only when the NodeInfo generation changed
    or the node moved within the enumeration order — mirroring the cache's
    own generation walk (reference: cache.go:210).
    """

    def __init__(self):
        self._batch: Optional[NodeBatch] = None
        self._generations: dict[str, int] = {}
        self._scalar_vocab: list[str] = []
        self._zone_vocab: list[str] = [""]
        # columnar pod-table cache (pod_table): per-node blocks keyed by
        # NodeInfo generation; vocabs grow monotonically so ids are stable
        self._pt_blocks: dict[str, tuple] = {}
        self._pt_ns_vocab: dict[str, int] = {}
        self._pt_key_vocab: dict[str, int] = {}
        self._pt_val_vocab: dict[str, int] = {}
        self._pt_val_ints: list[float] = []
        # assembled-table memo: when no block re-extracted and the batch is
        # the same object, the concatenated arrays are bit-identical — skip
        # the O(total pods) reassembly (victim_table + the per-burst
        # PodEncoder both read the table, often in the same cycle)
        self._pt_built: Optional["PodTable"] = None
        self._pt_built_key: Optional[tuple] = None
        # calculate_resource memo keyed by the containers tuple: victim
        # columns and uniform waves re-read the same specs constantly
        self._cr_memo: dict = {}
        # persistent victim table (victim_table): [N, P] reprieve-ordered
        # slot columns cached per node by NodeInfo generation, permuted on
        # NodeTree rotation with the mirror, all-dirty on a PDB-set change
        self._vt: Optional[VictimStack] = None
        self._vt_gens: dict[str, int] = {}
        self._vt_pdb_key: Optional[tuple] = None
        # per-row SPEC flag planes (round 17): node-spec facts the
        # PodEncoder's cluster-wide feature gates read (taints present,
        # unschedulable, prefer-avoid annotations, image states) —
        # maintained in _write_row exactly like the aggregate mirror, so
        # a serving window reads four numpy any()s instead of four O(N)
        # python attribute scans per window. Spec fields are untouched by
        # assumes (which sync generations without _write_row), so the
        # generation-gated maintenance is exact.
        self._spec_flags: Optional[dict] = None

    def encode(self, node_infos: dict[str, NodeInfo],
               node_order: list[str]) -> NodeBatch:
        # ONE generation walk collects the vocab additions AND the dirty
        # row list (the old _collect_vocab pass folded in): the serving
        # loop re-encodes every window, and at cluster scale each full
        # O(N) python pass over the snapshot is a measurable slice of the
        # window's host prologue
        gens = self._generations
        dirty_pairs: list = []
        known = zones = None
        scalar_vocab = self._scalar_vocab
        zone_vocab = self._zone_vocab
        for i, name in enumerate(node_order):
            ni = node_infos[name]
            if gens.get(name) == ni.generation:
                continue
            dirty_pairs.append((i, name, ni))
            if known is None:
                known = set(scalar_vocab)
                zones = set(zone_vocab)
            for sname in ni.allocatable.scalar:
                if sname not in known:
                    known.add(sname)
                    scalar_vocab.append(sname)
            for sname in ni.requested.scalar:
                if sname not in known:
                    known.add(sname)
                    scalar_vocab.append(sname)
            if ni.node is not None:
                z = get_zone_key(ni.node)
                if z not in zones:
                    zones.add(z)
                    zone_vocab.append(z)
        n_real = len(node_order)
        n_pad = _pad_capacity(n_real)
        s = max(1, len(self._scalar_vocab))
        b = self._batch
        rebuild = (
            b is None or b.n_pad != n_pad
            or len(b.scalar_names) != len(self._scalar_vocab)
            or b.names != node_order
        )
        if rebuild:
            if (b is not None and b.n_pad == n_pad and b.n_real == n_real
                    and len(b.scalar_names) == len(self._scalar_vocab)
                    and set(b.names) == set(node_order)):
                # same nodes, new enumeration order (uneven-zone clusters
                # rotate between bursts): permute the mirror rows instead
                # of re-extracting every NodeInfo through _write_row —
                # generations are name-keyed, so they stay valid. The
                # victim table's row planes ride the same permutation.
                self._vt_permute(b, node_order, n_real)
                self._flags_permute(b, node_order, n_real)
                b = self._permuted(b, node_order, n_real)
                MIRROR_PERMUTES.inc()
            else:
                b = self._fresh(node_order, n_real, n_pad, s)
                self._generations = {}
                self._vt = None           # rows realign on next victim scan
                self._vt_gens = {}
                self._spec_flags = {
                    k: np.zeros(n_pad, dtype=bool)
                    for k in ("taints", "unsched", "avoid", "images")}
                MIRROR_REBUILDS.inc()
            self._batch = b
        scalar_idx = {name: i for i, name in enumerate(self._scalar_vocab)}
        zone_idx = {name: i for i, name in enumerate(self._zone_vocab)}
        dirty = []
        reencoded = 0
        gens = self._generations   # rebind: _fresh resets the map
        if gens:
            # steady state: only the rows the single walk above found
            # dirty (positions in node_order == batch rows, permute
            # included — _permuted rebuilds the index from node_order)
            iter_rows = dirty_pairs
        else:
            iter_rows = [(i, name, node_infos[name])
                         for i, name in enumerate(node_order)]
        for i, name, ni in iter_rows:
            if gens.get(name) == ni.generation:
                continue
            gens[name] = ni.generation
            reencoded += 1
            # value-compare: a generation bump with identical aggregates
            # (assume→confirm, status-only updates, folds already applied on
            # device) must not trigger a device re-upload
            if self._write_row(b, i, ni, scalar_idx, zone_idx):
                dirty.append(i)
        if reencoded:
            ROW_REENCODES.inc(reencoded)
        # accumulate until the device mirror consumes (resets) the list;
        # None = full re-upload required
        if rebuild:
            b.dirty_rows = None
        elif b.dirty_rows is not None:
            b.dirty_rows.extend(dirty)
        return b

    def _permuted(self, b: NodeBatch, node_order: list[str],
                  n_real: int) -> NodeBatch:
        """Reorder an existing mirror to a new enumeration of the SAME node
        set: one numpy gather per field. Returned as a fresh NodeBatch
        (dirty_rows=None) so the device mirror re-uploads — row positions
        moved, the delta path can't express that."""
        perm = np.fromiter((b.index[nm] for nm in node_order), np.int64,
                           n_real)

        def take(arr):
            out = arr.copy()
            out[:n_real] = arr[perm]
            return out

        return NodeBatch(
            names=list(node_order),
            index={name: i for i, name in enumerate(node_order)},
            n_real=n_real, n_pad=b.n_pad,
            scalar_names=list(self._scalar_vocab),
            zone_names=list(self._zone_vocab),
            valid=b.valid.copy(),
            alloc_cpu=take(b.alloc_cpu), alloc_mem=take(b.alloc_mem),
            alloc_eph=take(b.alloc_eph), allowed_pods=take(b.allowed_pods),
            req_cpu=take(b.req_cpu), req_mem=take(b.req_mem),
            req_eph=take(b.req_eph),
            nz_cpu=take(b.nz_cpu), nz_mem=take(b.nz_mem),
            pod_count=take(b.pod_count),
            alloc_scalar=take(b.alloc_scalar), req_scalar=take(b.req_scalar),
            zone_id=take(b.zone_id),
        )

    def _fresh(self, node_order: list[str], n_real: int, n_pad: int, s: int) -> NodeBatch:
        z = lambda dt=np.int64: np.zeros(n_pad, dtype=dt)
        b = NodeBatch(
            names=list(node_order),
            index={name: i for i, name in enumerate(node_order)},
            n_real=n_real, n_pad=n_pad,
            scalar_names=list(self._scalar_vocab),
            zone_names=list(self._zone_vocab),
            valid=np.zeros(n_pad, dtype=bool),
            alloc_cpu=z(), alloc_mem=z(), alloc_eph=z(), allowed_pods=z(),
            req_cpu=z(), req_mem=z(), req_eph=z(),
            nz_cpu=z(), nz_mem=z(), pod_count=z(),
            alloc_scalar=np.zeros((n_pad, s), dtype=np.int64),
            req_scalar=np.zeros((n_pad, s), dtype=np.int64),
            zone_id=np.zeros(n_pad, dtype=np.int32),
        )
        b.valid[:n_real] = True
        return b

    def _write_row(self, b: NodeBatch, i: int, ni: NodeInfo,
                   scalar_idx: dict[str, int], zone_idx: dict[str, int]) -> bool:
        """Write one mirror row from its NodeInfo; returns True when any
        device-visible value actually changed."""
        changed = False

        def setf(arr, val):
            nonlocal changed
            if arr[i] != val:
                arr[i] = val
                changed = True

        setf(b.alloc_cpu, ni.allocatable.milli_cpu)
        setf(b.alloc_mem, ni.allocatable.memory)
        setf(b.alloc_eph, ni.allocatable.ephemeral_storage)
        setf(b.allowed_pods, ni.allocatable.allowed_pod_number)
        setf(b.req_cpu, ni.requested.milli_cpu)
        setf(b.req_mem, ni.requested.memory)
        setf(b.req_eph, ni.requested.ephemeral_storage)
        setf(b.nz_cpu, ni.nonzero_cpu)
        setf(b.nz_mem, ni.nonzero_mem)
        setf(b.pod_count, len(ni.pods))
        s = b.alloc_scalar.shape[1]
        new_alloc = np.zeros(s, dtype=np.int64)
        for name, q in ni.allocatable.scalar.items():
            new_alloc[scalar_idx[name]] = q
        if not np.array_equal(b.alloc_scalar[i], new_alloc):
            b.alloc_scalar[i] = new_alloc
            changed = True
        new_req = np.zeros(s, dtype=np.int64)
        for name, q in ni.requested.scalar.items():
            new_req[scalar_idx[name]] = q
        if not np.array_equal(b.req_scalar[i], new_req):
            b.req_scalar[i] = new_req
            changed = True
        if ni.node is not None:
            setf(b.zone_id, zone_idx[get_zone_key(ni.node)])
        flags = self._spec_flags
        if flags is not None:
            # spec facts for the PodEncoder's cluster-wide gates (not
            # device-visible: never feeds `changed`)
            flags["taints"][i] = bool(ni.taints)
            flags["unsched"][i] = (ni.node is not None
                                   and ni.node.unschedulable)
            flags["avoid"][i] = (ni.node is not None
                                 and bool(ni.node.prefer_avoid_pod_uids))
            flags["images"][i] = bool(ni.image_states)
        return changed

    def _flags_permute(self, b_old: NodeBatch, node_order: list[str],
                       n_real: int) -> None:
        """Reorder the spec-flag planes to a rotated enumeration of the
        same node set, mirroring _permuted."""
        flags = self._spec_flags
        if flags is None:
            return
        perm = np.fromiter((b_old.index[nm] for nm in node_order),
                           np.int64, n_real)
        for k, arr in flags.items():
            out = arr.copy()
            out[:n_real] = arr[perm]
            flags[k] = out

    def cluster_spec_flags(self, b: NodeBatch) -> Optional[dict]:
        """The four cluster-wide spec gates as O(1)-ish numpy any()s —
        valid only for the encoder's CURRENT batch (every row written at
        its generation); None tells the caller to fall back to the
        per-node scans."""
        if self._spec_flags is None or self._batch is not b:
            return None
        n = b.n_real
        f = self._spec_flags
        return {
            "any_taints": bool(f["taints"][:n].any()),
            "any_unschedulable": bool(f["unsched"][:n].any()),
            "any_prefer_avoid": bool(f["avoid"][:n].any()),
            "any_images": bool(f["images"][:n].any()),
        }

    # -- columnar pod table --------------------------------------------------
    def _pt_val_id(self, v: str) -> int:
        vid = self._pt_val_vocab.get(v)
        if vid is None:
            vid = self._pt_val_vocab[v] = len(self._pt_val_ints)
            try:
                self._pt_val_ints.append(float(int(v)))
            except ValueError:
                self._pt_val_ints.append(float("nan"))
        return vid

    def _pt_block(self, ni: NodeInfo):
        """One node's pods as dictionary-encoded rows. Vocab ids are
        monotonic (never reassigned) so cached blocks stay valid across
        encodes. Alongside the label rows, each pod's VICTIM columns are
        extracted here — priority, start time, calculate_resource sums
        (memoized by the containers tuple), and the inertness-class flags
        (affinity terms / container ports / scalar resources) — so the
        preemption path reads cached per-generation facts instead of
        re-deriving them per scan."""
        pods = list(ni.pods)
        p = len(pods)
        aff_ids = set(map(id, ni.pods_with_affinity))
        lmax = max((len(pd.labels) for pd in pods), default=0)
        kid = np.full((p, max(lmax, 1)), -1, np.int32)
        vid = np.full((p, max(lmax, 1)), -1, np.int32)
        ns = np.empty(p, np.int32)
        deleted = np.empty(p, bool)
        has_aff = np.empty(p, bool)
        prio = np.empty(p, np.int64)
        start = np.empty(p, np.float64)
        rcpu = np.empty(p, np.int64)
        rmem = np.empty(p, np.int64)
        reph = np.empty(p, np.int64)
        rscalar = np.empty(p, bool)
        aterms = np.empty(p, bool)
        ports = np.empty(p, bool)
        names = []
        nsv, kvoc = self._pt_ns_vocab, self._pt_key_vocab
        cr_memo = self._cr_memo
        for j, pd in enumerate(pods):
            nid = nsv.get(pd.namespace)
            if nid is None:
                nid = nsv[pd.namespace] = len(nsv)
            ns[j] = nid
            deleted[j] = pd.deleted
            has_aff[j] = id(pd) in aff_ids
            prio[j] = pd.priority
            start[j] = pd.start_time if pd.start_time is not None else np.inf
            key = pd.containers
            got = cr_memo.get(key)
            if got is None:
                from kubernetes_tpu.cache.node_info import calculate_resource
                r = calculate_resource(pd)
                got = cr_memo[key] = (r.milli_cpu, r.memory,
                                      r.ephemeral_storage, bool(r.scalar),
                                      bool(get_container_ports(pd)))
            rcpu[j], rmem[j], reph[j], rscalar[j], ports[j] = got
            aterms[j] = has_pod_affinity_terms(pd)
            names.append(pd.node_name)
            for l, (k, v) in enumerate(pd.labels.items()):
                kk = kvoc.get(k)
                if kk is None:
                    kk = kvoc[k] = len(kvoc)
                kid[j, l] = kk
                vid[j, l] = self._pt_val_id(v)
        return (pods, ns, kid, vid, deleted, has_aff, names,
                (prio, start, rcpu, rmem, reph, rscalar, aterms, ports))

    def pod_table(self, node_infos: dict[str, NodeInfo],
                  b: NodeBatch) -> "PodTable":
        """Columnar table of every snapshot pod, cached per node by the
        NodeInfo generation exactly like the dirty-row encode: only nodes
        whose generation moved re-extract their pods' label rows; assembly
        of the cached blocks is pure numpy. Callers that feed the table to
        the vectorized matchers assume the batch axis covers the snapshot
        (node_infos keys ⊆ batch names), which is how every encoder
        consumer builds it."""
        blocks = []
        new_cache = {}
        all_hit = True
        for name, ni in node_infos.items():
            cached = self._pt_blocks.get(name)
            if cached is not None and cached[0] == ni.generation:
                blk = cached[1]
            else:
                blk = self._pt_block(ni)
                all_hit = False
            new_cache[name] = (ni.generation, blk)
            blocks.append((name, blk))
        if len(new_cache) != len(self._pt_blocks):
            all_hit = False              # a node left or joined the snapshot
        self._pt_blocks = new_cache   # prunes nodes that left the snapshot
        key = (id(b), len(blocks))
        if all_hit and self._pt_built is not None \
                and self._pt_built_key == key:
            # no block re-extracted against the same batch: the assembled
            # arrays are bit-identical — reuse them
            return self._pt_built
        total = sum(len(blk[0]) for _, blk in blocks)
        lmax = max((blk[2].shape[1] for _, blk in blocks if len(blk[0])),
                   default=1)
        pods: list = []
        holder_row = np.full(total, -1, np.int32)
        holder_has_obj = np.zeros(total, bool)
        name_row = np.full(total, -1, np.int32)
        ns_id = np.empty(total, np.int32)
        deleted = np.empty(total, bool)
        has_aff = np.empty(total, bool)
        key_ids = np.full((total, lmax), -1, np.int32)
        val_ids = np.full((total, lmax), -1, np.int32)
        prio = np.empty(total, np.int64)
        start = np.empty(total, np.float64)
        res_cpu = np.empty(total, np.int64)
        res_mem = np.empty(total, np.int64)
        res_eph = np.empty(total, np.int64)
        has_scalar = np.empty(total, bool)
        has_aff_terms = np.empty(total, bool)
        has_ports = np.empty(total, bool)
        off = 0
        for name, blk in blocks:
            bpods, ns, kid, vid, dele, haff, names, vcols = blk
            p = len(bpods)
            if not p:
                continue
            pods.extend(bpods)
            sl = slice(off, off + p)
            hrow = b.index.get(name, -1)
            holder_row[sl] = hrow
            holder_has_obj[sl] = node_infos[name].node is not None
            ns_id[sl] = ns
            deleted[sl] = dele
            has_aff[sl] = haff
            key_ids[sl, : kid.shape[1]] = kid
            val_ids[sl, : vid.shape[1]] = vid
            (prio[sl], start[sl], res_cpu[sl], res_mem[sl], res_eph[sl],
             has_scalar[sl], has_aff_terms[sl], has_ports[sl]) = vcols
            for j, nm in enumerate(names):
                if nm == name:
                    name_row[off + j] = hrow
                elif nm in node_infos:
                    name_row[off + j] = b.index.get(nm, -1)
            off += p
        out = PodTable(
            pods=pods, holder_row=holder_row, holder_has_obj=holder_has_obj,
            name_row=name_row, has_affinity=has_aff, deleted=deleted,
            ns_id=ns_id, key_ids=key_ids, val_ids=val_ids,
            ns_vocab=self._pt_ns_vocab, key_vocab=self._pt_key_vocab,
            val_vocab=self._pt_val_vocab,
            val_ints=np.asarray(self._pt_val_ints, dtype=np.float64),
            prio=prio, start=start, res_cpu=res_cpu, res_mem=res_mem,
            res_eph=res_eph, has_scalar=has_scalar,
            has_aff_terms=has_aff_terms, has_ports=has_ports)
        self._pt_built = out
        self._pt_built_key = key
        return out

    # -- persistent victim table --------------------------------------------
    def victim_table(self, node_infos: dict[str, NodeInfo], b: NodeBatch,
                     pdbs: list, cap: int = 128) -> VictimStack:
        """Build/refresh the persistent [N, P] victim table against `b`.

        Incremental exactly like encode(): only nodes whose NodeInfo
        generation moved since the last call re-sort their slots — one
        vectorized np.lexsort over the dirty nodes' pod-table rows replaces
        the per-node Python `importance_key` sorts of the old per-scan
        encode. A PDB-set change (object identity or disruptionsAllowed)
        dirties every node, since the violating flags feed the sort key.
        The NodeTree rotation case never lands here: encode()'s permute
        branch reorders the victim rows with the mirror rows.

        Assumed pods arrive through the cache's generation bump (the
        note_assumed hooks deliberately do NOT sync `_vt_gens`, unlike the
        aggregate mirror: the mirror gets the delta applied manually, the
        victim table needs the new pod's row — so the next call here
        re-extracts exactly the bound-to nodes)."""
        t = self.pod_table(node_infos, b)
        pdb_key = tuple(sorted(
            (id(p), p.namespace, int(p.disruptions_allowed),
             p.selector is None) for p in pdbs))
        n_pad = b.n_pad
        hr = t.holder_row
        on_axis = hr >= 0
        counts = np.bincount(hr[on_axis], minlength=n_pad).astype(np.int64)
        maxp = int(counts.max()) if counts.size else 0
        P = min(_pad_capacity(max(maxp, 1), 8), cap)
        vt = self._vt
        if vt is not None and vt.valid.shape[0] == n_pad:
            P = max(P, vt.P)   # never shrink: avoids rebuild thrash
        if vt is None or vt.P != P or vt.valid.shape[0] != n_pad:
            zeros2 = lambda dt: np.zeros((n_pad, P), dtype=dt)
            vt = VictimStack(
                P=P, cpu=zeros2(np.int64), mem=zeros2(np.int64),
                eph=zeros2(np.int64), prio=zeros2(np.int64),
                start=np.full((n_pad, P), np.inf, np.float64),
                valid=zeros2(bool), viol=zeros2(bool), aff=zeros2(bool),
                ports=zeros2(bool), scalar=zeros2(bool),
                count=np.zeros(n_pad, np.int64),
                overflow=np.zeros(n_pad, bool),
                slots={}, table=t, dirty_rows=None)
            self._vt = vt
            self._vt_gens = {}
            self._vt_pdb_key = None
            VICTIM_REBUILDS.inc()
        vt.table = t
        if pdb_key != self._vt_pdb_key:
            # the violating flags are part of the sort key: re-sort all
            self._vt_gens = {}
            self._vt_pdb_key = pdb_key
        gens = self._vt_gens
        dirty = []
        for i, name in enumerate(b.names):
            g = node_infos[name].generation
            if gens.get(name) != g:
                gens[name] = g
                dirty.append(i)
        if not dirty:
            return vt
        VICTIM_ROW_RESORTS.inc(len(dirty))
        d = np.asarray(dirty, np.int64)
        # reset the dirty rows, then scatter the re-sorted slots
        for f in ("cpu", "mem", "eph", "prio"):
            getattr(vt, f)[d] = 0
        vt.start[d] = np.inf
        for f in ("valid", "viol", "aff", "ports", "scalar"):
            getattr(vt, f)[d] = False
        vt.count[d] = counts[d]
        vt.overflow[d] = counts[d] > P
        for i in dirty:
            vt.slots[b.names[i]] = []
        is_dirty = np.zeros(n_pad, bool)
        is_dirty[d] = True
        rows = np.flatnonzero(on_axis & is_dirty[np.where(on_axis, hr, 0)])
        if rows.size:
            from kubernetes_tpu.oracle.preemption import \
                pods_violating_pdbs_mask
            viol = pods_violating_pdbs_mask(t, pdbs)[rows] if pdbs \
                else np.zeros(rows.size, bool)
            holder = hr[rows].astype(np.int64)
            # reprieve processing order per node in ONE stable lexsort
            # (last key is primary): group by node row, violating first,
            # then descending importance = priority desc, start asc —
            # np.lexsort is stable, so ties keep ni.pods order exactly
            # like the old per-node Python sort
            order = np.lexsort((t.start[rows], -t.prio[rows],
                                (~viol).astype(np.int8), holder))
            sr = rows[order]
            h = holder[order]
            viol_s = viol[order]
            newgrp = np.r_[True, h[1:] != h[:-1]]
            gstart = np.flatnonzero(newgrp)
            slot = np.arange(len(h)) - gstart[np.cumsum(newgrp) - 1]
            keep = slot < P
            hs, ss = h[keep], slot[keep]
            ks = sr[keep]
            vt.cpu[hs, ss] = t.res_cpu[ks]
            vt.mem[hs, ss] = t.res_mem[ks]
            vt.eph[hs, ss] = t.res_eph[ks]
            vt.prio[hs, ss] = t.prio[ks]
            vt.start[hs, ss] = t.start[ks]
            vt.valid[hs, ss] = True
            vt.viol[hs, ss] = viol_s[keep]
            vt.aff[hs, ss] = t.has_aff_terms[ks]
            vt.ports[hs, ss] = t.has_ports[ks]
            vt.scalar[hs, ss] = t.has_scalar[ks]
            pods_list = t.pods
            names_list = b.names
            slots = vt.slots
            for r, hi in zip(ks.tolist(), hs.tolist()):
                slots[names_list[hi]].append(pods_list[r])
        if vt.dirty_rows is not None:
            vt.dirty_rows.extend(dirty)
        return vt

    def _vt_permute(self, b_old: NodeBatch, node_order: list[str],
                    n_real: int) -> None:
        """Reorder the victim table to a rotated enumeration of the same
        node set — one gather per plane, mirroring _permuted. Row positions
        moved, so the device copy needs a full re-upload (dirty_rows=None);
        slot content and the name-keyed slots/generation maps stay valid."""
        vt = self._vt
        if vt is None:
            return
        perm = np.fromiter((b_old.index[nm] for nm in node_order), np.int64,
                           n_real)
        for f in VictimStack._ROW_FIELDS:
            arr = getattr(vt, f)
            out = arr.copy()
            out[:n_real] = arr[perm]
            setattr(vt, f, out)
        vt.dirty_rows = None

    def note_assumed(self, b: NodeBatch, node_name: str, pod: Pod,
                     generation: Optional[int] = None,
                     mark_dirty: bool = True) -> None:
        """Apply an assume to the host mirror without a full re-encode,
        matching NodeInfo.add_pod's aggregate update (calculate_resource —
        regular containers only — NOT the predicate-side GetResourceRequest
        which maxes in init containers; reference: node_info.go:578).

        With `generation`, syncs `_generations` to the cache's post-assume
        generation; with mark_dirty=False the row is NOT queued for device
        upload — callers use that when the device already folded the same
        delta in-scan (the burst path), making the resident matrix
        authoritative."""
        from kubernetes_tpu.cache.node_info import calculate_resource
        i = b.index[node_name]
        req = calculate_resource(pod)
        b.req_cpu[i] += req.milli_cpu
        b.req_mem[i] += req.memory
        b.req_eph[i] += req.ephemeral_storage
        if req.scalar:
            scalar_idx = {name: j for j, name in enumerate(b.scalar_names)}
            for name, q in req.scalar.items():
                b.req_scalar[i, scalar_idx[name]] += q
        ncpu, nmem = get_pod_nonzero_requests(pod)
        b.nz_cpu[i] += ncpu
        b.nz_mem[i] += nmem
        b.pod_count[i] += 1
        if generation is not None:
            self._generations[node_name] = generation
        if mark_dirty and b.dirty_rows is not None:
            b.dirty_rows.append(i)

    def note_assumed_many(self, b: NodeBatch, pods: list, hosts: list,
                          generations: list) -> None:
        """Vectorized note_assumed for a committed burst wave: the per-pod
        deltas land in the mirror via bincount-style scatters (np.add.at —
        duplicate hosts accumulate) and the generation map syncs in one
        dict.update, replacing one Python call chain per pod with one per
        wave. Never marks rows dirty: callers use this exactly when the
        device already folded the same deltas in-scan (the burst commit
        path), making the resident matrix authoritative.

        Delta extraction is memoized by the containers tuple — a uniform
        wave of spec-identical pods computes calculate_resource once."""
        from kubernetes_tpu.cache.node_info import calculate_resource
        k = len(pods)
        if not k:
            return
        rows = np.fromiter((b.index[h] for h in hosts), np.int64, k)
        cache: dict = {}
        cpu = np.empty(k, np.int64)
        mem = np.empty(k, np.int64)
        eph = np.empty(k, np.int64)
        ncpu = np.empty(k, np.int64)
        nmem = np.empty(k, np.int64)
        scalar_pods = []
        for j, pod in enumerate(pods):
            key = pod.containers
            got = cache.get(key)
            if got is None:
                req = calculate_resource(pod)
                got = cache[key] = (req, get_pod_nonzero_requests(pod))
            req, (nc, nm) = got
            cpu[j] = req.milli_cpu
            mem[j] = req.memory
            eph[j] = req.ephemeral_storage
            ncpu[j] = nc
            nmem[j] = nm
            if req.scalar:
                scalar_pods.append((j, req.scalar))
        np.add.at(b.req_cpu, rows, cpu)
        np.add.at(b.req_mem, rows, mem)
        np.add.at(b.req_eph, rows, eph)
        np.add.at(b.nz_cpu, rows, ncpu)
        np.add.at(b.nz_mem, rows, nmem)
        np.add.at(b.pod_count, rows, 1)
        if scalar_pods:
            scalar_idx = {name: j for j, name in enumerate(b.scalar_names)}
            for j, scal in scalar_pods:
                for name, q in scal.items():
                    b.req_scalar[rows[j], scalar_idx[name]] += q
        # generations are read once per wave AFTER every assume, so the
        # name-keyed map lands at each touched node's final generation
        self._generations.update(
            (h, g) for h, g in zip(hosts, generations) if g is not None)


@dataclass
class PodTable:
    """Columnar snapshot pod table: one row per pod of every NodeInfo, with
    namespaces and label (key, value) pairs dictionary-encoded — the
    existing-pod axis twin of the node matrix (SURVEY §2.3 applied to
    selector matching). Consumed through the shared vectorized matchers in
    oracle.predicates (selector_match_mask / pod_matches_term_props_mask),
    so the per-existing-pod Python of selector-spread counting and
    inter-pod affinity scans becomes one boolean mask per selector/term.
    """
    pods: list                  # row -> Pod
    holder_row: np.ndarray      # [P] i32 batch row of the holding NodeInfo (-1 off-axis)
    holder_has_obj: np.ndarray  # [P] bool: holder NodeInfo.node is not None
    name_row: np.ndarray        # [P] i32 batch row of the node named pod.node_name (-1 unknown)
    has_affinity: np.ndarray    # [P] bool (mirrors NodeInfo.pods_with_affinity)
    deleted: np.ndarray         # [P] bool
    ns_id: np.ndarray           # [P] i32
    key_ids: np.ndarray         # [P, L] i32, -1 padding
    val_ids: np.ndarray         # [P, L] i32, -1 padding
    ns_vocab: dict
    key_vocab: dict
    val_vocab: dict
    val_ints: np.ndarray        # [V] f64 parsed-integer value (NaN unparseable)
    # victim columns (cached per node generation in the same blocks): the
    # facts preemption reads about every snapshot pod, so a victim scan
    # never re-derives them per pod
    prio: np.ndarray = None          # [P] i64 pod priority
    start: np.ndarray = None         # [P] f64 start time (+inf when None)
    res_cpu: np.ndarray = None       # [P] i64 calculate_resource milli-CPU
    res_mem: np.ndarray = None       # [P] i64 bytes
    res_eph: np.ndarray = None       # [P] i64 bytes
    has_scalar: np.ndarray = None    # [P] bool — extended resources requested
    has_aff_terms: np.ndarray = None  # [P] bool — any pod (anti-)affinity term
    has_ports: np.ndarray = None     # [P] bool — declares container ports


@dataclass
class VictimStack:
    """Persistent [N, P] victim table: every snapshot pod in its node's
    reprieve processing order (PDB-violating first, each group by descending
    importance — oracle.preemption.select_victims_on_node), maintained
    incrementally alongside the node mirror instead of re-encoded per scan.

    Slots hold ALL pods (not just one preemptor's potential victims): the
    sort key (violating, -priority, start) is priority-monotone, so masking
    to `prio < max_prio` on device preserves the per-preemptor reprieve
    order exactly — one table serves every preemptor priority. The
    inertness-class flag planes (aff/ports/scalar) make the eligibility
    gates O(1) mask reads instead of per-pod Python, and `dirty_rows` feeds
    the device mirror's sparse re-upload exactly like NodeBatch."""
    P: int                      # slot bucket (power of two, <= kernel cap)
    cpu: np.ndarray             # [N, P] i64 calculate_resource milli-CPU
    mem: np.ndarray             # [N, P] i64
    eph: np.ndarray             # [N, P] i64
    prio: np.ndarray            # [N, P] i64
    start: np.ndarray           # [N, P] f64 (+inf padding)
    valid: np.ndarray           # [N, P] bool
    viol: np.ndarray            # [N, P] bool — PDB-violating
    aff: np.ndarray             # [N, P] bool — pod carries affinity terms
    ports: np.ndarray           # [N, P] bool — pod declares container ports
    scalar: np.ndarray          # [N, P] bool — pod requests scalar resources
    count: np.ndarray           # [N] i64 total pods on the node
    overflow: np.ndarray        # [N] bool — count exceeded the slot cap
    slots: dict                 # node name -> ordered slot Pod list
    table: PodTable             # the pod table the rows were built from
    # rows rewritten since the device mirror last consumed the list;
    # None = full re-upload required (rebuild or permute)
    dirty_rows: Optional[list] = None

    _ROW_FIELDS = ("cpu", "mem", "eph", "prio", "start", "valid", "viol",
                   "aff", "ports", "scalar", "count", "overflow")


def build_pod_table(node_infos: dict[str, NodeInfo], b: NodeBatch) -> PodTable:
    """Uncached one-shot table build (standalone PodEncoder uses); the
    scheduler path goes through NodeStateEncoder.pod_table for the
    generation cache."""
    return NodeStateEncoder().pod_table(node_infos, b)


# ---------------------------------------------------------------------------
# Per-pod encoding: masks + score counts over the node axis
# ---------------------------------------------------------------------------
# interpod failure codes (kernel output decoding)
IPA_OK = 0
IPA_EXISTING_ANTI = 1
IPA_OWN_AFFINITY = 2
IPA_OWN_ANTI = 3


@dataclass
class PodFeatures:
    """Everything the kernel needs about one pod, over a NodeBatch's axis.

    Mask arrays are None when the pod/cluster doesn't exercise the feature
    (all-pass) so the common case uploads nothing.
    """
    req_cpu: int
    req_mem: int
    req_eph: int
    req_scalar: np.ndarray             # [S] i64
    has_request: bool                  # reference: predicates.go:786 early-out
    nz_cpu: int
    nz_mem: int
    # filter masks (None => all pass)
    sel_ok: Optional[np.ndarray] = None        # [N] bool — selector + req. node affinity
    taints_ok: Optional[np.ndarray] = None     # [N] bool
    unsched_ok: Optional[np.ndarray] = None    # [N] bool
    ports_ok: Optional[np.ndarray] = None      # [N] bool
    host_ok: Optional[np.ndarray] = None       # [N] bool
    disk_ok: Optional[np.ndarray] = None       # [N] bool (NoDiskConflict)
    maxvol_ok: Optional[np.ndarray] = None     # [N] bool (Max*VolumeCount)
    volbind_ok: Optional[np.ndarray] = None    # [N] bool (CheckVolumeBinding)
    volzone_ok: Optional[np.ndarray] = None    # [N] bool (NoVolumeZoneConflict)
    volbind_reasons: Optional[dict] = None     # node idx -> reasons (decode)
    interpod_code: Optional[np.ndarray] = None  # [N] i8 IPA_* codes
    # scalars requested by the pod but absent from every node's capacity:
    # they fail PodFitsResources on all nodes (reference: predicates.go:806)
    unknown_scalars: tuple = ()
    # score inputs (None => zeros)
    node_aff_counts: Optional[np.ndarray] = None   # [N] i64
    taint_counts: Optional[np.ndarray] = None      # [N] i64
    spread_counts: Optional[np.ndarray] = None     # [N] i64
    interpod_counts: Optional[np.ndarray] = None   # [N] i64
    interpod_tracked: Optional[np.ndarray] = None  # [N] bool
    image_sums: Optional[np.ndarray] = None        # [N] i64
    prefer_avoid: Optional[np.ndarray] = None      # [N] i64 (0 or 10)


class PodEncoder:
    """Encodes one pod against a snapshot into dense per-node arrays.

    The string-matching work (selectors, taints, topology pairs) happens here
    once per pod in O(N) dict lookups; the reference instead does it inside
    every per-node goroutine (predicates.go:889,1531).
    """

    def __init__(self, node_infos: dict[str, NodeInfo], batch: NodeBatch,
                 services=None, replicasets=None, total_num_nodes: Optional[int] = None,
                 hard_pod_affinity_weight: int = 1,
                 enabled: Optional[set] = None,
                 volume_listers=None, volume_binder=None,
                 state_encoder: Optional[NodeStateEncoder] = None):
        self.node_infos = node_infos
        self.batch = batch
        # predicate names enabled by the provider/policy; None = all
        self.enabled = enabled
        self.volume_listers = volume_listers
        self.volume_binder = volume_binder
        self.services = services or []
        self.replicasets = replicasets or []
        self.total_num_nodes = total_num_nodes or max(1, batch.n_real)
        self.hard_weight = hard_pod_affinity_weight
        # columnar pod table: generation-cached when the scheduler's
        # NodeStateEncoder is supplied, one-shot otherwise (lazy either way)
        self.state_encoder = state_encoder
        self._ptable: Optional[PodTable] = None
        self._taint_rows: Optional[dict] = None
        self._image_locality_rows: Optional[dict] = None
        self._ipa = InterPodAffinityChecker(node_infos)
        self._ipa.set_table_source(self._table, self._topo_values)
        # cluster-wide feature flags: skip whole mask families when inert.
        # Spec-derived flags read the state encoder's maintained planes
        # (four numpy any()s) instead of four O(N) python attribute scans
        # per window — bit-identical by the generation-gated row contract;
        # the affinity flag depends on held PODS (assumes change it), so
        # it keeps the direct scan.
        flags = state_encoder.cluster_spec_flags(batch) \
            if state_encoder is not None else None
        if flags is None:
            self._any_taints = any(ni.taints for ni in node_infos.values())
            self._any_unschedulable = any(
                ni.node is not None and ni.node.unschedulable
                for ni in node_infos.values())
            self._any_prefer_avoid = any(
                ni.node is not None and ni.node.prefer_avoid_pod_uids
                for ni in node_infos.values())
            self._any_images = any(
                ni.image_states for ni in node_infos.values())
        else:
            self._any_taints = flags["any_taints"]
            self._any_unschedulable = flags["any_unschedulable"]
            self._any_prefer_avoid = flags["any_prefer_avoid"]
            self._any_images = flags["any_images"]
        self._any_affinity_pods = any(
            ni.pods_with_affinity for ni in node_infos.values())
        # per-(topologyKey) dictionary encoding of node label values, built
        # lazily for the inter-pod segment-sum counting (SURVEY §2.3)
        self._topo_cache: dict[str, tuple[np.ndarray, dict]] = {}

    def _nodes(self):
        b = self.batch
        for i in range(b.n_real):
            yield i, self.node_infos[b.names[i]]

    def _table(self) -> PodTable:
        if self._ptable is None:
            if self.state_encoder is not None:
                self._ptable = self.state_encoder.pod_table(
                    self.node_infos, self.batch)
            else:
                self._ptable = build_pod_table(self.node_infos, self.batch)
        return self._ptable

    def _on(self, *names: str) -> bool:
        return self.enabled is None or any(n in self.enabled for n in names)

    def encode(self, pod: Pod) -> PodFeatures:
        b = self.batch
        req = get_resource_request(pod)
        req_scalar = np.zeros(max(1, len(b.scalar_names)), dtype=np.int64)
        scalar_idx = {name: i for i, name in enumerate(b.scalar_names)}
        unknown = []
        for name, q in req.scalar.items():
            if name in scalar_idx:
                req_scalar[scalar_idx[name]] = q
            elif q > 0:
                unknown.append(name)
        nz_cpu, nz_mem = get_pod_nonzero_requests(pod)
        f = PodFeatures(
            req_cpu=req.milli_cpu, req_mem=req.memory, req_eph=req.ephemeral_storage,
            req_scalar=req_scalar,
            has_request=bool(req.milli_cpu or req.memory or req.ephemeral_storage
                             or req.scalar),
            nz_cpu=nz_cpu, nz_mem=nz_mem,
            unknown_scalars=tuple(unknown),
        )
        self._encode_filters(pod, f)
        self._encode_scores(pod, f)
        return f

    # -- filter masks -------------------------------------------------------
    def _encode_filters(self, pod: Pod, f: PodFeatures) -> None:
        b = self.batch
        if (pod.node_selector or (pod.affinity and pod.affinity.node_affinity)) \
                and self._on("GeneralPredicates", "MatchNodeSelector"):
            m = np.zeros(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                m[i] = ni.node is not None and \
                    pod_matches_node_selector_and_affinity(pod, ni.node)
            f.sel_ok = m
        if self._any_taints and self._on("PodToleratesNodeTaints"):
            m = np.ones(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                bad = find_intolerable_taint(
                    ni.taints, pod.tolerations,
                    lambda t: t.effect in (NO_SCHEDULE, NO_EXECUTE))
                m[i] = bad is None
            f.taints_ok = m
        if self._any_unschedulable and self._on("CheckNodeUnschedulable"):
            tolerates = any(
                t.tolerates(Taint(key=TAINT_NODE_UNSCHEDULABLE, effect=NO_SCHEDULE))
                for t in pod.tolerations)
            m = np.ones(b.n_pad, dtype=bool)
            if not tolerates:
                for i, ni in self._nodes():
                    m[i] = not (ni.node is not None and ni.node.unschedulable)
            f.unsched_ok = m
        ports = get_container_ports(pod)
        if ports and self._on("GeneralPredicates", "PodFitsHostPorts"):
            m = np.ones(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                m[i] = not any(
                    ni.used_ports.check_conflict(p.host_ip, p.protocol, p.host_port)
                    for p in ports)
            f.ports_ok = m
        if pod.node_name and self._on("GeneralPredicates", "HostName"):
            m = np.zeros(b.n_pad, dtype=bool)
            idx = b.index.get(pod.node_name)
            if idx is not None:
                m[idx] = True
            f.host_ok = m
        if pod.volumes and self.volume_listers is not None:
            self._encode_volumes(pod, f)
        has_own_terms = pod.affinity is not None and (
            pod.affinity.pod_affinity is not None
            or pod.affinity.pod_anti_affinity is not None)
        if (self._any_affinity_pods or has_own_terms) \
                and self._on("MatchInterPodAffinity"):
            f.interpod_code = self._interpod_codes(pod)

    def _interpod_codes(self, pod: Pod) -> np.ndarray:
        """Vectorized MatchInterPodAffinity over the node axis: the same
        (topologyKey, value) metadata the oracle's per-node check reads
        (predicates.InterPodAffinityChecker._metadata, itself vectorized
        over the pod table), resolved against the dictionary-encoded node
        label values — one membership mask per term instead of a Python
        check per node. Codes keep the oracle's first-failure precedence:
        existing-pods anti-affinity, then own affinity, then own anti."""
        b = self.batch
        violating, aff_terms, anti_terms = self._ipa._metadata(pod)
        fail_exist = np.zeros(b.n_pad, dtype=bool)
        for (key, value) in violating:
            ids, vocab = self._topo_values(key)
            vid = vocab.get(value)
            if vid is not None:
                fail_exist |= ids == vid
        fail_aff = np.zeros(b.n_pad, dtype=bool)
        for term, values, total in aff_terms:
            if not values:
                # first-pod-in-cluster waiver (predicates.go:1454-1464) is
                # node-independent: no pod anywhere matches the term
                if total[0] == 0 and pod_matches_term_props(pod, pod, term):
                    continue
                fail_aff[:] = True
                continue
            ids, vocab = self._topo_values(term.topology_key)
            vids = [vocab[v] for v in values if v in vocab]
            member = np.isin(ids, vids) if vids \
                else np.zeros(b.n_pad, dtype=bool)
            fail_aff |= ~member
        fail_anti = np.zeros(b.n_pad, dtype=bool)
        for term, values, _total in anti_terms:
            ids, vocab = self._topo_values(term.topology_key)
            vids = [vocab[v] for v in values if v in vocab]
            if vids:
                fail_anti |= np.isin(ids, vids)
        codes = np.where(
            fail_exist, IPA_EXISTING_ANTI,
            np.where(fail_aff, IPA_OWN_AFFINITY,
                     np.where(fail_anti, IPA_OWN_ANTI, 0))).astype(np.int8)
        codes[b.n_real:] = 0   # padding rows carry no verdict
        return codes

    def _encode_volumes(self, pod: Pod, f: PodFeatures) -> None:
        """Volume predicate masks, via the oracle implementations per node
        (volumes are rare per pod; this path only runs when present)."""
        from kubernetes_tpu.oracle import volumes as V
        b = self.batch
        listers = self.volume_listers
        vol_preds = V.make_volume_predicates(listers, self.volume_binder)
        reason_map: dict = {}

        def mask(names: tuple) -> np.ndarray:
            m = np.ones(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                ok_all = True
                for name in names:
                    if not self._on(name):
                        continue
                    ok, reasons = vol_preds[name](pod, ni)
                    if not ok:
                        ok_all = False
                        reason_map.setdefault(i, []).extend(reasons)
                        break
                m[i] = ok_all
            return m

        if self._on("NoDiskConflict"):
            f.disk_ok = mask(("NoDiskConflict",))
        f.maxvol_ok = mask(("MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
                            "MaxAzureDiskVolumeCount", "MaxCSIVolumeCountPred"))
        if self._on("CheckVolumeBinding"):
            f.volbind_ok = mask(("CheckVolumeBinding",))
        if self._on("NoVolumeZoneConflict"):
            f.volzone_ok = mask(("NoVolumeZoneConflict",))
        f.volbind_reasons = reason_map

    # -- score inputs -------------------------------------------------------
    def _encode_scores(self, pod: Pod, f: PodFeatures) -> None:
        b = self.batch
        a = pod.affinity
        if a is not None and a.node_affinity is not None and a.node_affinity.preferred:
            counts = np.zeros(b.n_pad, dtype=np.int64)
            for i, ni in self._nodes():
                if ni.node is None:
                    continue
                c = 0
                for term in a.node_affinity.preferred:
                    if term.weight == 0:
                        continue
                    if term.preference.match_expressions and \
                            term.preference.matches(ni.node.labels):
                        c += term.weight
                counts[i] = c
            f.node_aff_counts = counts
        if self._any_taints:
            # group by unique taint (cached per snapshot): each distinct
            # PreferNoSchedule taint is toleration-checked ONCE, its node
            # rows incremented in one scatter — instead of the old
            # per-node × per-taint Python walk
            tols = [t for t in pod.tolerations
                    if not t.effect or t.effect == PREFER_NO_SCHEDULE]
            counts = np.zeros(b.n_pad, dtype=np.int64)
            for taint, rows in self._prefer_taint_rows().items():
                if not tolerations_tolerate_taint(tols, taint):
                    np.add.at(counts, rows, 1)
            f.taint_counts = counts
        selectors = get_selectors(pod, self.services, self.replicasets)
        if selectors:
            # selector-spread counting (selector_spreading.go:66): one
            # vectorized selector-match over the columnar pod table plus a
            # segment-sum by holder node, replacing the per-existing-pod
            # Python that made the spread lane the encode-side cliff
            t = self._table()
            nsid = t.ns_vocab.get(pod.namespace)
            if nsid is None:
                m = np.zeros(len(t.pods), dtype=bool)
            else:
                m = (t.ns_id == nsid) & ~t.deleted
            for s in selectors:
                if not m.any():
                    break
                m &= selector_match_mask(s, t)
            counts = np.zeros(b.n_pad, dtype=np.int64)
            rows = t.holder_row[m]
            rows = rows[rows >= 0]
            if rows.size:
                counts += np.bincount(rows, minlength=b.n_pad)
            f.spread_counts = counts
        has_pref_terms = a is not None and (
            (a.pod_affinity is not None and a.pod_affinity.preferred)
            or (a.pod_anti_affinity is not None and a.pod_anti_affinity.preferred))
        if self._any_affinity_pods or has_pref_terms:
            f.interpod_counts, f.interpod_tracked = self._interpod_pref_counts(pod)
        if self._any_images:
            sums = np.zeros(b.n_pad, dtype=np.int64)
            img_rows = self._image_rows()
            for c in pod.containers:
                ent = img_rows.get(normalized_image_name(c.image))
                if ent is not None:
                    np.add.at(sums, ent[0], ent[1])
            f.image_sums = sums
        if self._any_prefer_avoid:
            scores = np.full(b.n_pad, 10, dtype=np.int64)
            owner = pod.owner_ref
            if owner is not None and owner[0] in ("ReplicationController", "ReplicaSet"):
                for i, ni in self._nodes():
                    if ni.node is not None and owner[2] in ni.node.prefer_avoid_pod_uids:
                        scores[i] = 0
            f.prefer_avoid = scores

    def _prefer_taint_rows(self) -> dict:
        """{unique PreferNoSchedule taint -> np node rows}, built once per
        snapshot (taints are per-node state, not per-pod)."""
        got = self._taint_rows
        if got is None:
            d: dict = {}
            for i, ni in self._nodes():
                for taint in ni.taints:
                    if taint.effect == PREFER_NO_SCHEDULE:
                        d.setdefault(taint, []).append(i)
            got = self._taint_rows = {
                t: np.asarray(r, dtype=np.int64) for t, r in d.items()}
        return got

    def _image_rows(self) -> dict:
        """{normalized image name -> (node rows, int64 contributions)} with
        the reference's exact per-(node, image) truncation
        (image_locality.go:42: int(size_bytes * num_nodes/total))."""
        got = self._image_locality_rows
        if got is None:
            rows: dict = {}
            for i, ni in self._nodes():
                for name, state in ni.image_states.items():
                    rows.setdefault(name, ([], []))
                    rows[name][0].append(i)
                    rows[name][1].append(
                        int(state.size_bytes
                            * (state.num_nodes / self.total_num_nodes)))
            got = self._image_locality_rows = {
                name: (np.asarray(r, dtype=np.int64),
                       np.asarray(c, dtype=np.int64))
                for name, (r, c) in rows.items()}
        return got

    def _topo_values(self, key: str):
        """Dictionary-encode node label values for one topology key:
        (ids[N] int32, vocab value->id), id -1 where the label is absent.
        Built once per encoder (= per burst/cycle snapshot)."""
        got = self._topo_cache.get(key)
        if got is None:
            b = self.batch
            ids = np.full(b.n_pad, -1, np.int32)
            vocab: dict[str, int] = {}
            for i, ni in self._nodes():
                n = ni.node
                if n is None:
                    continue
                v = n.labels.get(key)
                if v is not None:
                    ids[i] = vocab.setdefault(v, len(vocab))
            got = self._topo_cache[key] = (ids, vocab)
        return got

    def _interpod_pref_counts(self, pod: Pod):
        """Mirror of the oracle's interpod_affinity_priority counting
        (priorities.py; reference interpod_affinity.go:116,215), emitted as
        dense arrays via the SURVEY §2.3 segment-sum formulation: each
        matching (term, existing-pod) event adds its weight to a
        (topologyKey, value) bucket — the existing pod's node fixes the
        value — and the per-node counts are one bucket gather per distinct
        key. The reference instead walks every node per event inside
        processTerm (:215); the old mirror of that walk was the
        O(events x nodes) host bottleneck of the affinity lanes."""
        b = self.batch
        t = self._table()
        a = pod.affinity
        has_aff = a is not None and a.pod_affinity is not None
        has_anti = a is not None and a.pod_anti_affinity is not None
        trk = np.zeros(b.n_pad, dtype=bool)
        if has_aff or has_anti:
            trk[: b.n_real] = True
        else:
            rows = t.holder_row[t.has_affinity]
            trk[rows[rows >= 0]] = True
        acc: dict[str, np.ndarray] = {}

        def node_of(p: Pod):
            ni = self.node_infos.get(p.node_name)
            return ni.node if ni else None

        def bucket_add_mask(term, mask, weight):
            """All of one term's (existing-pod) events at once: each
            matching pod adds `weight` to the (topologyKey, value) bucket
            its node's label value fixes."""
            key = term.topology_key
            if not key or not mask.any():
                return
            ids, vocab = self._topo_values(key)
            rows = t.name_row[mask]
            rows = rows[rows >= 0]          # fixed node unknown
            if not rows.size:
                return
            vids = ids[rows]
            vids = vids[vids >= 0]          # fixed node lacks the label
            if not vids.size:
                return
            buckets = acc.get(key)
            if buckets is None:
                buckets = acc[key] = np.zeros(len(vocab), np.int64)
            buckets += np.bincount(vids, minlength=len(vocab)) * weight

        def process_term(term, defining, to_check, fixed_node, weight):
            key = term.topology_key
            if fixed_node is None or not key:
                return   # nodes_same_topology is False for empty keys
            if not pod_matches_term_props(to_check, defining, term):
                return
            v = fixed_node.labels.get(key)
            if v is None:
                return   # the fixed node lacks the label: no node matches
            ids, vocab = self._topo_values(key)
            vid = vocab.get(v)
            if vid is None:
                return
            buckets = acc.get(key)
            if buckets is None:
                buckets = acc[key] = np.zeros(len(vocab), np.int64)
            buckets[vid] += weight

        # the incoming pod's preferred terms, vectorized over the
        # existing-pod axis (reference interpod_affinity.go:215 processTerm
        # walked every node per matching pod; the old mirror walked every
        # pod in Python): one mask per term. The reference only processes
        # pods held by nodes with objects — holder_has_obj gates that.
        on_node = t.holder_has_obj
        if has_aff:
            for wt in a.pod_affinity.preferred:
                bucket_add_mask(
                    wt.term,
                    on_node & pod_matches_term_props_mask(pod, wt.term, t),
                    wt.weight)
        if has_anti:
            for wt in a.pod_anti_affinity.preferred:
                bucket_add_mask(
                    wt.term,
                    on_node & pod_matches_term_props_mask(pod, wt.term, t),
                    -wt.weight)
        # existing pods' own terms check the single incoming pod (O(terms)
        # each): only affinity-carrying pods can contribute, so walk exactly
        # those rows instead of every pod
        for r in np.nonzero(t.has_affinity & on_node)[0].tolist():
            existing = t.pods[r]
            existing_node = node_of(existing)
            ea = existing.affinity
            if ea.pod_affinity is not None:
                if self.hard_weight > 0:
                    for term in ea.pod_affinity.required:
                        process_term(term, existing, pod, existing_node,
                                     self.hard_weight)
                for wt in ea.pod_affinity.preferred:
                    process_term(wt.term, existing, pod, existing_node,
                                 wt.weight)
            if ea.pod_anti_affinity is not None:
                for wt in ea.pod_anti_affinity.preferred:
                    process_term(wt.term, existing, pod, existing_node,
                                 -wt.weight)

        arr = np.zeros(b.n_pad, dtype=np.int64)
        for key, buckets in acc.items():
            ids, _vocab = self._topo_cache[key]
            mask = ids >= 0
            arr[mask] += buckets[ids[mask]]
        arr[~trk] = 0
        return arr, trk
