"""Dense node-state encoding: the NodeInfo snapshot as a struct-of-arrays.

The host keeps a numpy mirror of the per-node aggregates the predicates and
priorities read (reference: pkg/scheduler/nodeinfo/node_info.go:47,139); each
scheduling cycle uploads it (or just the changed rows) to HBM, where the
fused kernel evaluates every node at once. The node axis is ordered by the
cache's zone-interleaved NodeTree enumeration, padded to a static capacity so
XLA never recompiles as the cluster grows within a bucket.

String-world features (labels, taints, selectors, topology keys) are
dictionary-encoded host-side per pod into dense masks/counts — the shape the
device consumes (SURVEY §7 "Set/string matching on device").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kubernetes_tpu.api.types import (
    Pod, Taint, NO_SCHEDULE, NO_EXECUTE, PREFER_NO_SCHEDULE,
    TAINT_NODE_UNSCHEDULABLE, get_resource_request, get_pod_nonzero_requests,
    get_container_ports, get_zone_key, tolerations_tolerate_taint,
    find_intolerable_taint,
)
from kubernetes_tpu.cache.node_info import NodeInfo, normalized_image_name
from kubernetes_tpu.oracle.predicates import (
    pod_matches_node_selector_and_affinity, InterPodAffinityChecker,
)
from kubernetes_tpu.oracle.priorities import (
    get_selectors, _selector_matches,
)


def _pad_capacity(n: int, minimum: int = 8) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


@dataclass
class NodeBatch:
    """Host-side numpy mirror of the device node matrix.

    All integer fields are int64 (reference resource math is int64). Rows
    [n_real:] are padding with valid=False.
    """
    names: list[str]
    index: dict[str, int]
    n_real: int
    n_pad: int
    scalar_names: list[str]            # extended-resource vocab
    zone_names: list[str]              # zone vocab; index 0 reserved for ""
    valid: np.ndarray                  # [N] bool
    alloc_cpu: np.ndarray              # [N] i64 milli
    alloc_mem: np.ndarray              # [N] i64 bytes
    alloc_eph: np.ndarray              # [N] i64 bytes
    allowed_pods: np.ndarray           # [N] i64
    req_cpu: np.ndarray                # [N] i64
    req_mem: np.ndarray                # [N] i64
    req_eph: np.ndarray                # [N] i64
    nz_cpu: np.ndarray                 # [N] i64 (NonZeroRequest)
    nz_mem: np.ndarray                 # [N] i64
    pod_count: np.ndarray              # [N] i64
    alloc_scalar: np.ndarray           # [N,S] i64
    req_scalar: np.ndarray             # [N,S] i64
    zone_id: np.ndarray                # [N] i32 (0 = no zone)
    # rows rewritten by the latest encode(); None = full rebuild. Consumed by
    # the device mirror to upload only generation-dirty rows (SURVEY §2.4).
    dirty_rows: Optional[list] = None


class NodeStateEncoder:
    """Builds/refreshes a NodeBatch from a cache snapshot.

    Incremental: rows are rewritten only when the NodeInfo generation changed
    or the node moved within the enumeration order — mirroring the cache's
    own generation walk (reference: cache.go:210).
    """

    def __init__(self):
        self._batch: Optional[NodeBatch] = None
        self._generations: dict[str, int] = {}
        self._scalar_vocab: list[str] = []
        self._zone_vocab: list[str] = [""]

    def _collect_vocab(self, node_infos: dict[str, NodeInfo]) -> None:
        known = set(self._scalar_vocab)
        zones = set(self._zone_vocab)
        for ni in node_infos.values():
            for name in ni.allocatable.scalar:
                if name not in known:
                    known.add(name)
                    self._scalar_vocab.append(name)
            for name in ni.requested.scalar:
                if name not in known:
                    known.add(name)
                    self._scalar_vocab.append(name)
            if ni.node is not None:
                z = get_zone_key(ni.node)
                if z not in zones:
                    zones.add(z)
                    self._zone_vocab.append(z)

    def encode(self, node_infos: dict[str, NodeInfo],
               node_order: list[str]) -> NodeBatch:
        self._collect_vocab(node_infos)
        n_real = len(node_order)
        n_pad = _pad_capacity(n_real)
        s = max(1, len(self._scalar_vocab))
        b = self._batch
        rebuild = (
            b is None or b.n_pad != n_pad
            or len(b.scalar_names) != len(self._scalar_vocab)
            or b.names != node_order
        )
        if rebuild:
            b = self._fresh(node_order, n_real, n_pad, s)
            self._generations = {}
            self._batch = b
        scalar_idx = {name: i for i, name in enumerate(self._scalar_vocab)}
        zone_idx = {name: i for i, name in enumerate(self._zone_vocab)}
        dirty = []
        gens = self._generations
        for i, name in enumerate(node_order):
            ni = node_infos[name]
            if gens.get(name) == ni.generation:
                continue
            gens[name] = ni.generation
            # value-compare: a generation bump with identical aggregates
            # (assume→confirm, status-only updates, folds already applied on
            # device) must not trigger a device re-upload
            if self._write_row(b, i, ni, scalar_idx, zone_idx):
                dirty.append(i)
        # accumulate until the device mirror consumes (resets) the list;
        # None = full re-upload required
        if rebuild:
            b.dirty_rows = None
        elif b.dirty_rows is not None:
            b.dirty_rows.extend(dirty)
        return b

    def _fresh(self, node_order: list[str], n_real: int, n_pad: int, s: int) -> NodeBatch:
        z = lambda dt=np.int64: np.zeros(n_pad, dtype=dt)
        b = NodeBatch(
            names=list(node_order),
            index={name: i for i, name in enumerate(node_order)},
            n_real=n_real, n_pad=n_pad,
            scalar_names=list(self._scalar_vocab),
            zone_names=list(self._zone_vocab),
            valid=np.zeros(n_pad, dtype=bool),
            alloc_cpu=z(), alloc_mem=z(), alloc_eph=z(), allowed_pods=z(),
            req_cpu=z(), req_mem=z(), req_eph=z(),
            nz_cpu=z(), nz_mem=z(), pod_count=z(),
            alloc_scalar=np.zeros((n_pad, s), dtype=np.int64),
            req_scalar=np.zeros((n_pad, s), dtype=np.int64),
            zone_id=np.zeros(n_pad, dtype=np.int32),
        )
        b.valid[:n_real] = True
        return b

    def _write_row(self, b: NodeBatch, i: int, ni: NodeInfo,
                   scalar_idx: dict[str, int], zone_idx: dict[str, int]) -> bool:
        """Write one mirror row from its NodeInfo; returns True when any
        device-visible value actually changed."""
        changed = False

        def setf(arr, val):
            nonlocal changed
            if arr[i] != val:
                arr[i] = val
                changed = True

        setf(b.alloc_cpu, ni.allocatable.milli_cpu)
        setf(b.alloc_mem, ni.allocatable.memory)
        setf(b.alloc_eph, ni.allocatable.ephemeral_storage)
        setf(b.allowed_pods, ni.allocatable.allowed_pod_number)
        setf(b.req_cpu, ni.requested.milli_cpu)
        setf(b.req_mem, ni.requested.memory)
        setf(b.req_eph, ni.requested.ephemeral_storage)
        setf(b.nz_cpu, ni.nonzero_cpu)
        setf(b.nz_mem, ni.nonzero_mem)
        setf(b.pod_count, len(ni.pods))
        s = b.alloc_scalar.shape[1]
        new_alloc = np.zeros(s, dtype=np.int64)
        for name, q in ni.allocatable.scalar.items():
            new_alloc[scalar_idx[name]] = q
        if not np.array_equal(b.alloc_scalar[i], new_alloc):
            b.alloc_scalar[i] = new_alloc
            changed = True
        new_req = np.zeros(s, dtype=np.int64)
        for name, q in ni.requested.scalar.items():
            new_req[scalar_idx[name]] = q
        if not np.array_equal(b.req_scalar[i], new_req):
            b.req_scalar[i] = new_req
            changed = True
        if ni.node is not None:
            setf(b.zone_id, zone_idx[get_zone_key(ni.node)])
        return changed

    def note_assumed(self, b: NodeBatch, node_name: str, pod: Pod,
                     generation: Optional[int] = None,
                     mark_dirty: bool = True) -> None:
        """Apply an assume to the host mirror without a full re-encode,
        matching NodeInfo.add_pod's aggregate update (calculate_resource —
        regular containers only — NOT the predicate-side GetResourceRequest
        which maxes in init containers; reference: node_info.go:578).

        With `generation`, syncs `_generations` to the cache's post-assume
        generation; with mark_dirty=False the row is NOT queued for device
        upload — callers use that when the device already folded the same
        delta in-scan (the burst path), making the resident matrix
        authoritative."""
        from kubernetes_tpu.cache.node_info import calculate_resource
        i = b.index[node_name]
        req = calculate_resource(pod)
        b.req_cpu[i] += req.milli_cpu
        b.req_mem[i] += req.memory
        b.req_eph[i] += req.ephemeral_storage
        if req.scalar:
            scalar_idx = {name: j for j, name in enumerate(b.scalar_names)}
            for name, q in req.scalar.items():
                b.req_scalar[i, scalar_idx[name]] += q
        ncpu, nmem = get_pod_nonzero_requests(pod)
        b.nz_cpu[i] += ncpu
        b.nz_mem[i] += nmem
        b.pod_count[i] += 1
        if generation is not None:
            self._generations[node_name] = generation
        if mark_dirty and b.dirty_rows is not None:
            b.dirty_rows.append(i)


# ---------------------------------------------------------------------------
# Per-pod encoding: masks + score counts over the node axis
# ---------------------------------------------------------------------------
# interpod failure codes (kernel output decoding)
IPA_OK = 0
IPA_EXISTING_ANTI = 1
IPA_OWN_AFFINITY = 2
IPA_OWN_ANTI = 3


@dataclass
class PodFeatures:
    """Everything the kernel needs about one pod, over a NodeBatch's axis.

    Mask arrays are None when the pod/cluster doesn't exercise the feature
    (all-pass) so the common case uploads nothing.
    """
    req_cpu: int
    req_mem: int
    req_eph: int
    req_scalar: np.ndarray             # [S] i64
    has_request: bool                  # reference: predicates.go:786 early-out
    nz_cpu: int
    nz_mem: int
    # filter masks (None => all pass)
    sel_ok: Optional[np.ndarray] = None        # [N] bool — selector + req. node affinity
    taints_ok: Optional[np.ndarray] = None     # [N] bool
    unsched_ok: Optional[np.ndarray] = None    # [N] bool
    ports_ok: Optional[np.ndarray] = None      # [N] bool
    host_ok: Optional[np.ndarray] = None       # [N] bool
    disk_ok: Optional[np.ndarray] = None       # [N] bool (NoDiskConflict)
    maxvol_ok: Optional[np.ndarray] = None     # [N] bool (Max*VolumeCount)
    volbind_ok: Optional[np.ndarray] = None    # [N] bool (CheckVolumeBinding)
    volzone_ok: Optional[np.ndarray] = None    # [N] bool (NoVolumeZoneConflict)
    volbind_reasons: Optional[dict] = None     # node idx -> reasons (decode)
    interpod_code: Optional[np.ndarray] = None  # [N] i8 IPA_* codes
    # scalars requested by the pod but absent from every node's capacity:
    # they fail PodFitsResources on all nodes (reference: predicates.go:806)
    unknown_scalars: tuple = ()
    # score inputs (None => zeros)
    node_aff_counts: Optional[np.ndarray] = None   # [N] i64
    taint_counts: Optional[np.ndarray] = None      # [N] i64
    spread_counts: Optional[np.ndarray] = None     # [N] i64
    interpod_counts: Optional[np.ndarray] = None   # [N] i64
    interpod_tracked: Optional[np.ndarray] = None  # [N] bool
    image_sums: Optional[np.ndarray] = None        # [N] i64
    prefer_avoid: Optional[np.ndarray] = None      # [N] i64 (0 or 10)


class PodEncoder:
    """Encodes one pod against a snapshot into dense per-node arrays.

    The string-matching work (selectors, taints, topology pairs) happens here
    once per pod in O(N) dict lookups; the reference instead does it inside
    every per-node goroutine (predicates.go:889,1531).
    """

    def __init__(self, node_infos: dict[str, NodeInfo], batch: NodeBatch,
                 services=None, replicasets=None, total_num_nodes: Optional[int] = None,
                 hard_pod_affinity_weight: int = 1,
                 enabled: Optional[set] = None,
                 volume_listers=None, volume_binder=None):
        self.node_infos = node_infos
        self.batch = batch
        # predicate names enabled by the provider/policy; None = all
        self.enabled = enabled
        self.volume_listers = volume_listers
        self.volume_binder = volume_binder
        self.services = services or []
        self.replicasets = replicasets or []
        self.total_num_nodes = total_num_nodes or max(1, batch.n_real)
        self.hard_weight = hard_pod_affinity_weight
        self._ipa = InterPodAffinityChecker(node_infos)
        # cluster-wide feature flags: skip whole mask families when inert
        self._any_taints = any(ni.taints for ni in node_infos.values())
        self._any_unschedulable = any(
            ni.node is not None and ni.node.unschedulable for ni in node_infos.values())
        self._any_affinity_pods = any(ni.pods_with_affinity for ni in node_infos.values())
        self._any_prefer_avoid = any(
            ni.node is not None and ni.node.prefer_avoid_pod_uids
            for ni in node_infos.values())
        self._any_images = any(ni.image_states for ni in node_infos.values())
        # per-(topologyKey) dictionary encoding of node label values, built
        # lazily for the inter-pod segment-sum counting (SURVEY §2.3)
        self._topo_cache: dict[str, tuple[np.ndarray, dict]] = {}

    def _nodes(self):
        b = self.batch
        for i in range(b.n_real):
            yield i, self.node_infos[b.names[i]]

    def _on(self, *names: str) -> bool:
        return self.enabled is None or any(n in self.enabled for n in names)

    def encode(self, pod: Pod) -> PodFeatures:
        b = self.batch
        req = get_resource_request(pod)
        req_scalar = np.zeros(max(1, len(b.scalar_names)), dtype=np.int64)
        scalar_idx = {name: i for i, name in enumerate(b.scalar_names)}
        unknown = []
        for name, q in req.scalar.items():
            if name in scalar_idx:
                req_scalar[scalar_idx[name]] = q
            elif q > 0:
                unknown.append(name)
        nz_cpu, nz_mem = get_pod_nonzero_requests(pod)
        f = PodFeatures(
            req_cpu=req.milli_cpu, req_mem=req.memory, req_eph=req.ephemeral_storage,
            req_scalar=req_scalar,
            has_request=bool(req.milli_cpu or req.memory or req.ephemeral_storage
                             or req.scalar),
            nz_cpu=nz_cpu, nz_mem=nz_mem,
            unknown_scalars=tuple(unknown),
        )
        self._encode_filters(pod, f)
        self._encode_scores(pod, f)
        return f

    # -- filter masks -------------------------------------------------------
    def _encode_filters(self, pod: Pod, f: PodFeatures) -> None:
        b = self.batch
        if (pod.node_selector or (pod.affinity and pod.affinity.node_affinity)) \
                and self._on("GeneralPredicates", "MatchNodeSelector"):
            m = np.zeros(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                m[i] = ni.node is not None and \
                    pod_matches_node_selector_and_affinity(pod, ni.node)
            f.sel_ok = m
        if self._any_taints and self._on("PodToleratesNodeTaints"):
            m = np.ones(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                bad = find_intolerable_taint(
                    ni.taints, pod.tolerations,
                    lambda t: t.effect in (NO_SCHEDULE, NO_EXECUTE))
                m[i] = bad is None
            f.taints_ok = m
        if self._any_unschedulable and self._on("CheckNodeUnschedulable"):
            tolerates = any(
                t.tolerates(Taint(key=TAINT_NODE_UNSCHEDULABLE, effect=NO_SCHEDULE))
                for t in pod.tolerations)
            m = np.ones(b.n_pad, dtype=bool)
            if not tolerates:
                for i, ni in self._nodes():
                    m[i] = not (ni.node is not None and ni.node.unschedulable)
            f.unsched_ok = m
        ports = get_container_ports(pod)
        if ports and self._on("GeneralPredicates", "PodFitsHostPorts"):
            m = np.ones(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                m[i] = not any(
                    ni.used_ports.check_conflict(p.host_ip, p.protocol, p.host_port)
                    for p in ports)
            f.ports_ok = m
        if pod.node_name and self._on("GeneralPredicates", "HostName"):
            m = np.zeros(b.n_pad, dtype=bool)
            idx = b.index.get(pod.node_name)
            if idx is not None:
                m[idx] = True
            f.host_ok = m
        if pod.volumes and self.volume_listers is not None:
            self._encode_volumes(pod, f)
        has_own_terms = pod.affinity is not None and (
            pod.affinity.pod_affinity is not None
            or pod.affinity.pod_anti_affinity is not None)
        if (self._any_affinity_pods or has_own_terms) \
                and self._on("MatchInterPodAffinity"):
            codes = np.zeros(b.n_pad, dtype=np.int8)
            for i, ni in self._nodes():
                ok, reasons = self._ipa.check(pod, ni)
                if not ok:
                    from kubernetes_tpu.oracle import predicates as P
                    if P.ERR_EXISTING_PODS_ANTI_AFFINITY_RULES_NOT_MATCH in reasons:
                        codes[i] = IPA_EXISTING_ANTI
                    elif P.ERR_POD_AFFINITY_RULES_NOT_MATCH in reasons:
                        codes[i] = IPA_OWN_AFFINITY
                    else:
                        codes[i] = IPA_OWN_ANTI
            f.interpod_code = codes

    def _encode_volumes(self, pod: Pod, f: PodFeatures) -> None:
        """Volume predicate masks, via the oracle implementations per node
        (volumes are rare per pod; this path only runs when present)."""
        from kubernetes_tpu.oracle import volumes as V
        b = self.batch
        listers = self.volume_listers
        vol_preds = V.make_volume_predicates(listers, self.volume_binder)
        reason_map: dict = {}

        def mask(names: tuple) -> np.ndarray:
            m = np.ones(b.n_pad, dtype=bool)
            for i, ni in self._nodes():
                ok_all = True
                for name in names:
                    if not self._on(name):
                        continue
                    ok, reasons = vol_preds[name](pod, ni)
                    if not ok:
                        ok_all = False
                        reason_map.setdefault(i, []).extend(reasons)
                        break
                m[i] = ok_all
            return m

        if self._on("NoDiskConflict"):
            f.disk_ok = mask(("NoDiskConflict",))
        f.maxvol_ok = mask(("MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
                            "MaxAzureDiskVolumeCount", "MaxCSIVolumeCountPred"))
        if self._on("CheckVolumeBinding"):
            f.volbind_ok = mask(("CheckVolumeBinding",))
        if self._on("NoVolumeZoneConflict"):
            f.volzone_ok = mask(("NoVolumeZoneConflict",))
        f.volbind_reasons = reason_map

    # -- score inputs -------------------------------------------------------
    def _encode_scores(self, pod: Pod, f: PodFeatures) -> None:
        b = self.batch
        a = pod.affinity
        if a is not None and a.node_affinity is not None and a.node_affinity.preferred:
            counts = np.zeros(b.n_pad, dtype=np.int64)
            for i, ni in self._nodes():
                if ni.node is None:
                    continue
                c = 0
                for term in a.node_affinity.preferred:
                    if term.weight == 0:
                        continue
                    if term.preference.match_expressions and \
                            term.preference.matches(ni.node.labels):
                        c += term.weight
                counts[i] = c
            f.node_aff_counts = counts
        if self._any_taints:
            tols = [t for t in pod.tolerations
                    if not t.effect or t.effect == PREFER_NO_SCHEDULE]
            counts = np.zeros(b.n_pad, dtype=np.int64)
            for i, ni in self._nodes():
                c = 0
                for taint in ni.taints:
                    if taint.effect == PREFER_NO_SCHEDULE and \
                            not tolerations_tolerate_taint(tols, taint):
                        c += 1
                counts[i] = c
            f.taint_counts = counts
        selectors = get_selectors(pod, self.services, self.replicasets)
        if selectors:
            counts = np.zeros(b.n_pad, dtype=np.int64)
            for i, ni in self._nodes():
                c = 0
                for existing in ni.pods:
                    if existing.namespace != pod.namespace or existing.deleted:
                        continue
                    if all(_selector_matches(s, existing.labels) for s in selectors):
                        c += 1
                counts[i] = c
            f.spread_counts = counts
        has_pref_terms = a is not None and (
            (a.pod_affinity is not None and a.pod_affinity.preferred)
            or (a.pod_anti_affinity is not None and a.pod_anti_affinity.preferred))
        if self._any_affinity_pods or has_pref_terms:
            f.interpod_counts, f.interpod_tracked = self._interpod_pref_counts(pod)
        if self._any_images:
            sums = np.zeros(b.n_pad, dtype=np.int64)
            for i, ni in self._nodes():
                total = 0
                for c in pod.containers:
                    state = ni.image_states.get(normalized_image_name(c.image))
                    if state is not None:
                        spread = state.num_nodes / self.total_num_nodes
                        total += int(state.size_bytes * spread)
                sums[i] = total
            f.image_sums = sums
        if self._any_prefer_avoid:
            scores = np.full(b.n_pad, 10, dtype=np.int64)
            owner = pod.owner_ref
            if owner is not None and owner[0] in ("ReplicationController", "ReplicaSet"):
                for i, ni in self._nodes():
                    if ni.node is not None and owner[2] in ni.node.prefer_avoid_pod_uids:
                        scores[i] = 0
            f.prefer_avoid = scores

    def _topo_values(self, key: str):
        """Dictionary-encode node label values for one topology key:
        (ids[N] int32, vocab value->id), id -1 where the label is absent.
        Built once per encoder (= per burst/cycle snapshot)."""
        got = self._topo_cache.get(key)
        if got is None:
            b = self.batch
            ids = np.full(b.n_pad, -1, np.int32)
            vocab: dict[str, int] = {}
            for i, ni in self._nodes():
                n = ni.node
                if n is None:
                    continue
                v = n.labels.get(key)
                if v is not None:
                    ids[i] = vocab.setdefault(v, len(vocab))
            got = self._topo_cache[key] = (ids, vocab)
        return got

    def _interpod_pref_counts(self, pod: Pod):
        """Mirror of the oracle's interpod_affinity_priority counting
        (priorities.py; reference interpod_affinity.go:116,215), emitted as
        dense arrays via the SURVEY §2.3 segment-sum formulation: each
        matching (term, existing-pod) event adds its weight to a
        (topologyKey, value) bucket — the existing pod's node fixes the
        value — and the per-node counts are one bucket gather per distinct
        key. The reference instead walks every node per event inside
        processTerm (:215); the old mirror of that walk was the
        O(events x nodes) host bottleneck of the affinity lanes."""
        b = self.batch
        from kubernetes_tpu.oracle.predicates import pod_matches_term_props
        a = pod.affinity
        has_aff = a is not None and a.pod_affinity is not None
        has_anti = a is not None and a.pod_anti_affinity is not None
        trk = np.zeros(b.n_pad, dtype=bool)
        for name, ni in self.node_infos.items():
            if has_aff or has_anti or ni.pods_with_affinity:
                i = b.index.get(name)
                if i is not None:
                    trk[i] = True
        acc: dict[str, np.ndarray] = {}

        def node_of(p: Pod):
            ni = self.node_infos.get(p.node_name)
            return ni.node if ni else None

        def process_term(term, defining, to_check, fixed_node, weight):
            key = term.topology_key
            if fixed_node is None or not key:
                return   # nodes_same_topology is False for empty keys
            if not pod_matches_term_props(to_check, defining, term):
                return
            v = fixed_node.labels.get(key)
            if v is None:
                return   # the fixed node lacks the label: no node matches
            ids, vocab = self._topo_values(key)
            vid = vocab.get(v)
            if vid is None:
                return
            buckets = acc.get(key)
            if buckets is None:
                buckets = acc[key] = np.zeros(len(vocab), np.int64)
            buckets[vid] += weight

        def process_pod(existing: Pod):
            existing_node = node_of(existing)
            ea = existing.affinity
            if has_aff:
                for wt in a.pod_affinity.preferred:
                    process_term(wt.term, pod, existing, existing_node, wt.weight)
            if has_anti:
                for wt in a.pod_anti_affinity.preferred:
                    process_term(wt.term, pod, existing, existing_node, -wt.weight)
            if ea is not None and ea.pod_affinity is not None:
                if self.hard_weight > 0:
                    for term in ea.pod_affinity.required:
                        process_term(term, existing, pod, existing_node, self.hard_weight)
                for wt in ea.pod_affinity.preferred:
                    process_term(wt.term, existing, pod, existing_node, wt.weight)
            if ea is not None and ea.pod_anti_affinity is not None:
                for wt in ea.pod_anti_affinity.preferred:
                    process_term(wt.term, existing, pod, existing_node, -wt.weight)

        for ni in self.node_infos.values():
            if ni.node is None:
                continue
            pods = ni.pods if (has_aff or has_anti) else ni.pods_with_affinity
            for existing in pods:
                process_pod(existing)

        arr = np.zeros(b.n_pad, dtype=np.int64)
        for key, buckets in acc.items():
            ids, _vocab = self._topo_cache[key]
            mask = ids >= 0
            arr[mask] += buckets[ids[mask]]
        arr[~trk] = 0
        return arr, trk
