"""JAX device layer: dense node-state encoding and filter/score/select kernels.

Importing this package configures jax for the framework: 64-bit integers are
enabled because the reference's resource math is int64 (milliCPU ints,
memory in bytes, scores summed as int64 — pkg/scheduler/api/types.go:35) and
exact score parity requires the same arithmetic on device.
"""
import jax

jax.config.update("jax_enable_x64", True)
