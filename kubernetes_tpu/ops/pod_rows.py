"""Encode-at-admission pod-row cache — the window prologue's gather source.

PROFILE round-16's serve phase split puts the host prologue (per-pod
feature extraction + class-signature tuples, re-run on EVERY window that
drains a pod) second only to the pipelined-away device fetch. The numbers
a window needs about a pod are pure functions of the pod's SPEC, which is
immutable between resourceVersions — so this cache computes each pod's
feature row ONCE, at informer delivery, and window planning gathers
prebuilt rows (one `np.take` per field) instead of re-running the per-pod
encode loop at line rate.

Rows are keyed by (uid, resourceVersion): an update-in-place (same uid,
new rv) re-encodes on the spot, a delete frees the slot, and a stale or
missing row falls back to a fresh encode (counted, never wrong). The
bit-identity contract — a cached row equals a fresh `encode_row` for
every pod, field for field — is what keeps burst decisions oracle-parity
by construction; tests/test_pod_rows.py fuzz-pins it, and the serve
parity sweep drives it with mid-window pod updates.

Class signatures are INTERNED: equal signatures share one tuple object,
so the window's uniformity test degenerates to pointer compares and the
per-sig feature/array memos in the burst drivers hit by identity.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from kubernetes_tpu import obs
from kubernetes_tpu.api.types import (
    Pod, get_container_ports, get_pod_nonzero_requests, get_resource_request,
    has_pod_affinity_terms,
)

ROW_CACHE_HITS = obs.counter(
    "pod_row_cache_hits_total",
    "Pod-row cache lookups by outcome: hit (row served at the cached "
    "(uid, resourceVersion)), miss (pod never delivered through the "
    "informer — encoded fresh on the spot), stale (the cached row's "
    "resourceVersion lags the pod's — re-encoded fresh).", ("outcome",))
ROW_CACHE_ROWS = obs.gauge(
    "pod_row_cache_rows",
    "Live rows in the most recently constructed pod-row cache.")


def pod_class_signature(pod: Pod) -> tuple:
    """Spec fields that determine a pod's device features against a fixed
    snapshot — equal signatures imply identical encoder output. THE
    canonical definition (TPUScheduler._class_signature and the native
    commitcore.class_signatures batch are its twins; the commit-core
    parity tests pin all three element-for-element)."""
    return (pod.namespace, tuple(sorted(pod.labels.items())),
            tuple(sorted(pod.node_selector.items())), pod.affinity,
            pod.tolerations, pod.node_name, pod.containers,
            pod.init_containers)


#: columnar int64 fields, in row order (gather() does one np.take each);
#: profile_id (round 19) is the pod's scheduling-profile index — filled at
#: admission like every other flag, gathered per window so mixed-tenant
#: windows select their weight-tensor rows without touching the pod specs
_I64_FIELDS = ("req_cpu", "req_mem", "req_eph", "nz_cpu", "nz_mem",
               "upd_cpu", "upd_mem", "upd_eph", "priority", "profile_id")
#: columnar bool fields
_BOOL_FIELDS = ("has_request", "has_scalar", "has_aff_terms", "has_ports",
                "has_volumes")


def encode_row(pod: Pod, profile_fn=None) -> dict:
    """THE per-pod feature row: every spec-derived scalar the window
    prologue reads, in one place — insert() stores exactly this, the
    lookup fallback recomputes exactly this, and the bit-identity fuzz
    compares the two. Scalar (extended-resource) requests are kept as
    sorted name->quantity items, NOT vocab-aligned arrays: the scalar
    vocab belongs to the node snapshot, so alignment happens at the
    window (cheap — scalar pods are rare) while the row stays
    snapshot-independent. `profile_fn(scheduler_name) -> Optional[int]`
    maps the pod to its scheduling-profile index (profiles.ProfileSet
    .index_of); None/unset resolves to 0 — the default profile row."""
    from kubernetes_tpu.cache.node_info import calculate_resource
    req = get_resource_request(pod)
    upd = calculate_resource(pod)
    nz_cpu, nz_mem = get_pod_nonzero_requests(pod)
    pid = profile_fn(pod.scheduler_name) if profile_fn is not None else 0
    return {
        "req_cpu": req.milli_cpu, "req_mem": req.memory,
        "req_eph": req.ephemeral_storage,
        "nz_cpu": nz_cpu, "nz_mem": nz_mem,
        "upd_cpu": upd.milli_cpu, "upd_mem": upd.memory,
        "upd_eph": upd.ephemeral_storage,
        "priority": pod.priority,
        "profile_id": 0 if pid is None else int(pid),
        "has_request": bool(req.milli_cpu or req.memory
                            or req.ephemeral_storage or req.scalar),
        "has_scalar": bool(req.scalar or upd.scalar),
        "has_aff_terms": has_pod_affinity_terms(pod),
        "has_ports": bool(get_container_ports(pod)),
        "has_volumes": bool(pod.volumes),
        "req_scalar_items": tuple(sorted(req.scalar.items())),
        "upd_scalar_items": tuple(sorted(upd.scalar.items())),
        "signature": pod_class_signature(pod),
    }


class PodRowCache:
    """Columnar cache of pod feature rows keyed by (uid, resourceVersion).

    Filled at informer delivery (insert/insert_many on the pending-pod
    handlers), re-encoded on update (same uid, new rv), freed on delete.
    `lookup_rows`/`signatures`/`gather` serve the window prologue; a miss
    or stale row falls back to `encode_row` — identical values by the
    bit-identity contract, so the cache can only be fast, never wrong.

    Capacity-bounded: past `capacity` live rows, the oldest insertion is
    evicted (the window falls back to fresh encodes for it — the same
    degradation as a miss)."""

    def __init__(self, capacity: int = 1 << 17, profile_fn=None):
        self.capacity = int(capacity)
        #: scheduling-profile resolver (profiles.ProfileSet.index_of);
        #: applied at insert AND at the lookup fallback so the
        #: bit-identity contract holds column-for-column
        self.profile_fn = profile_fn
        cap0 = 1024
        self._cap = cap0
        for f in _I64_FIELDS:
            setattr(self, "_" + f, np.zeros(cap0, dtype=np.int64))
        for f in _BOOL_FIELDS:
            setattr(self, "_" + f, np.zeros(cap0, dtype=bool))
        self._sig_id = np.full(cap0, -1, dtype=np.int32)
        # signature interning: equal sigs share ONE tuple object, so the
        # window's uniformity check is a pointer compare
        self._sig_of: dict = {}          # sig tuple -> id
        self._sigs: list = []            # id -> interned sig tuple
        # sparse side table: slot -> (req_scalar_items, upd_scalar_items);
        # only pods with extended-resource requests have an entry
        self._scalars: dict[int, tuple] = {}
        # slot map: uid -> (slot, rv); insertion-ordered for the capacity
        # eviction (dict preserves insertion order)
        self._slot_of: dict[str, tuple[int, int]] = {}
        self._free: list[int] = list(range(cap0 - 1, -1, -1))
        ROW_CACHE_ROWS.set_function(lambda: float(len(self._slot_of)))

    def __len__(self) -> int:
        return len(self._slot_of)

    # -- maintenance (informer delivery) -------------------------------------
    def _grow(self) -> None:
        new_cap = self._cap * 2
        for f in _I64_FIELDS + _BOOL_FIELDS:
            arr = getattr(self, "_" + f)
            grown = np.zeros(new_cap, dtype=arr.dtype)
            grown[: self._cap] = arr
            setattr(self, "_" + f, grown)
        sid = np.full(new_cap, -1, dtype=np.int32)
        sid[: self._cap] = self._sig_id
        self._sig_id = sid
        self._free.extend(range(new_cap - 1, self._cap - 1, -1))
        self._cap = new_cap

    def _intern_sig(self, sig: tuple) -> int:
        sid = self._sig_of.get(sig)
        if sid is None:
            sid = self._sig_of[sig] = len(self._sigs)
            self._sigs.append(sig)
        return sid

    def insert(self, pod: Pod) -> None:
        """Encode `pod`'s row at its current (uid, resourceVersion) —
        called at informer delivery (add and update both land here; an
        existing row for the uid is overwritten in place)."""
        uid = pod.uid
        existing = self._slot_of.pop(uid, None)
        if existing is not None:
            slot = existing[0]
        else:
            if len(self._slot_of) >= self.capacity:
                # bound the table: evict the oldest insertion (it decays
                # to the miss path, never to a wrong row)
                self.invalidate_uid(next(iter(self._slot_of)))
            if not self._free:
                self._grow()
            slot = self._free.pop()
        self._write(slot, encode_row(pod, self.profile_fn))
        # (re-)append so eviction order stays oldest-write-first
        self._slot_of[uid] = (slot, pod.resource_version)

    def _write(self, slot: int, row: dict) -> None:
        for f in _I64_FIELDS + _BOOL_FIELDS:
            getattr(self, "_" + f)[slot] = row[f]
        self._sig_id[slot] = self._intern_sig(row["signature"])
        if row["req_scalar_items"] or row["upd_scalar_items"]:
            self._scalars[slot] = (row["req_scalar_items"],
                                   row["upd_scalar_items"])
        else:
            self._scalars.pop(slot, None)

    def insert_many(self, pods: list) -> None:
        for pod in pods:
            self.insert(pod)

    def invalidate_uid(self, uid: str) -> None:
        got = self._slot_of.pop(uid, None)
        if got is not None:
            slot = got[0]
            self._sig_id[slot] = -1
            self._scalars.pop(slot, None)
            self._free.append(slot)

    def invalidate(self, pod: Pod) -> None:
        """Delete-side invalidation (the informer's on_delete)."""
        self.invalidate_uid(pod.uid)

    def invalidate_many(self, pods: list) -> None:
        """Batched delete-side invalidation (round 23): one call per
        informer delete run — the freed slots land in one pass."""
        for pod in pods:
            self.invalidate_uid(pod.uid)

    # -- window-prologue reads ------------------------------------------------
    def _slot(self, pod: Pod) -> int:
        """Row slot for `pod` at its exact resourceVersion, or -1 (miss /
        stale). Books the outcome counter."""
        got = self._slot_of.get(pod.uid)
        if got is None:
            ROW_CACHE_HITS.labels("miss").inc()
            return -1
        slot, rv = got
        if rv != pod.resource_version:
            ROW_CACHE_HITS.labels("stale").inc()
            return -1
        ROW_CACHE_HITS.labels("hit").inc()
        return slot

    def signatures(self, pods: list) -> list:
        """Per-pod class signatures, interned: cache hits gather the
        shared tuple by id (equal sigs are the SAME object — the window's
        uniformity check becomes identity); misses encode fresh through
        the canonical function and intern the result, so the returned
        list is bit-identical to a per-pod `pod_class_signature` pass."""
        sigs = self._sigs
        out = []
        for pod in pods:
            slot = self._slot(pod)
            if slot >= 0:
                out.append(sigs[self._sig_id[slot]])
            else:
                out.append(sigs[self._intern_sig(pod_class_signature(pod))])
        return out

    def lookup_row(self, pod: Pod) -> dict:
        """One pod's row — cached when live at the pod's rv, else a fresh
        `encode_row` (identical values; the fallback is the contract)."""
        slot = self._slot(pod)
        if slot < 0:
            return encode_row(pod, self.profile_fn)
        row = {f: getattr(self, "_" + f)[slot].item()
               for f in _I64_FIELDS}
        for f in _BOOL_FIELDS:
            row[f] = bool(getattr(self, "_" + f)[slot])
        req_s, upd_s = self._scalars.get(slot, ((), ()))
        row["req_scalar_items"] = req_s
        row["upd_scalar_items"] = upd_s
        row["signature"] = self._sigs[self._sig_id[slot]]
        return row

    def gather(self, pods: list, fields: tuple = _BOOL_FIELDS) -> Optional[dict]:
        """Columnar gather for a window's pods: ONE np.take per requested
        field. Returns None when any pod misses (the caller falls back to
        its per-pod path — correctness never depends on the cache)."""
        slots = np.empty(len(pods), dtype=np.int64)
        slot_of = self._slot_of
        for i, pod in enumerate(pods):
            got = slot_of.get(pod.uid)
            if got is None or got[1] != pod.resource_version:
                ROW_CACHE_HITS.labels(
                    "miss" if got is None else "stale").inc()
                return None
            slots[i] = got[0]
        ROW_CACHE_HITS.labels("hit").inc(len(pods))
        return {f: np.take(getattr(self, "_" + f), slots) for f in fields}

    def debug_state(self) -> dict:
        return {"rows": len(self._slot_of), "capacity": self.capacity,
                "signatures_interned": len(self._sigs),
                "scalar_rows": len(self._scalars)}
