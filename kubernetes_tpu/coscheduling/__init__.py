"""kubernetes_tpu.coscheduling — all-or-nothing PodGroup placement.

The gang-scheduling subsystem: the `PodGroup` API object (types), the
queue's group-adjacent ordering + gang backoff map
(queue.scheduling_queue), the shell's atomic gang segment
(scheduler.Scheduler._gang_segment), the device group-boundary
checkpoint/rewind (core.tpu_scheduler.TPUScheduler.gang_checkpoint /
gang_rewind over kernels.gang_carry_checkpoint), the serial referee
trial (oracle.gang.GangTrial — burst gang decisions must stay
bit-identical to it), and the phase/timeout controller
(controllers.podgroup.PodGroupController).
"""
from kubernetes_tpu.coscheduling.types import (   # noqa: F401
    LABEL_POD_GROUP, PHASE_PENDING, PHASE_PRESCHEDULING, PHASE_SCHEDULED,
    PHASE_UNSCHEDULABLE, PodGroup, pod_group_key, pod_group_name,
    pod_group_status_mutator,
)
