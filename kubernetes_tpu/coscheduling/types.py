"""PodGroup — the co-scheduling (gang) API object.

Mirrors the semantics of the sig-scheduling coscheduling plugin's
PodGroup CRD (scheduling.sigs.k8s.io/v1alpha1 PodGroupSpec/Status): a
named group of pods that must be placed all-or-nothing. `min_member` is
the gang floor — the scheduler commits a gang attempt only when every
gathered member found a node AND (gathered + already bound) covers it;
otherwise the whole trial is discarded and no partial binding ever
reaches the store. Pods join a group through the well-known label
`pod-group.kubernetes-tpu/name` (the CRD uses a label the same way —
membership is metadata, not spec, so the Pod schema is untouched).

Phases:
- Pending:        the group exists; fewer than min_member members seen.
- PreScheduling:  enough members exist; the scheduler is attempting (or
                  backing off between) atomic placements.
- Scheduled:      >= min_member members are bound.
- Unschedulable:  schedule_timeout_seconds elapsed without reaching
                  Scheduled (the controller's terminal verdict; a later
                  successful placement flips it back to Scheduled).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

# well-known membership label (coscheduling plugin:
# pod-group.scheduling.sigs.k8s.io/name)
LABEL_POD_GROUP = "pod-group.kubernetes-tpu/name"

PHASE_PENDING = "Pending"
PHASE_PRESCHEDULING = "PreScheduling"
PHASE_SCHEDULED = "Scheduled"
PHASE_UNSCHEDULABLE = "Unschedulable"


@dataclass
class PodGroup:
    """Pruned PodGroup: spec (min_member, schedule_timeout_seconds) +
    status (phase, member counts) — served by the apiserver like any
    kind, with a /status subresource for the controller/scheduler."""
    name: str
    namespace: str = "default"
    # spec
    min_member: int = 1
    schedule_timeout_seconds: Optional[float] = None
    # status
    phase: str = PHASE_PENDING
    members: int = 0        # member pods observed (bound + pending)
    scheduled: int = 0      # member pods currently bound
    last_transition_time: Optional[float] = None
    # bookkeeping
    creation_timestamp: float = 0.0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "PodGroup":
        import copy
        return copy.copy(self)


def pod_group_name(pod) -> Optional[str]:
    """The group a pod belongs to (its membership label), else None."""
    return pod.labels.get(LABEL_POD_GROUP) or None


def pod_group_key(pod) -> Optional[str]:
    """Store key (namespace/name) of the pod's group, else None."""
    name = pod.labels.get(LABEL_POD_GROUP)
    if not name:
        return None
    return f"{pod.namespace}/{name}"


def pod_group_status_mutator(phase: Optional[str] = None,
                             members: Optional[int] = None,
                             scheduled: Optional[int] = None,
                             now: Optional[float] = None):
    """Mutate closure for the /status subresource — shared by the
    embedded store and RemoteStore (per the CLAUDE.md sync rule: both
    transports must write identical objects). Returns None (no write)
    when nothing changes, so guaranteed_update(allow_skip=True) skips
    no-op writes exactly like pod_condition_mutator."""
    def mutate(group):
        changed = False
        if phase is not None and group.phase != phase:
            group.phase = phase
            group.last_transition_time = now
            changed = True
        if members is not None and group.members != members:
            group.members = members
            changed = True
        if scheduled is not None and group.scheduled != scheduled:
            group.scheduled = scheduled
            changed = True
        return group if changed else None
    return mutate
