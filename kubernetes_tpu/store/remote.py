"""HTTP client store — the client-go analog for the apiserver.

`RemoteStore` implements the Store surface the scheduler, controllers, and
shared informers consume (get/list/watch + the write verbs) over the
apiserver's REST + chunked-watch contract (apiserver/server.py), so
`Scheduler(RemoteStore(url))` runs a control-plane component OUT of the
apiserver's process. It mirrors the reference's client runtime:

- REST client with status->error mapping
  (client-go/rest/request.go; Conflict/AlreadyExists/NotFound/Gone).
- Reflector transport semantics (client-go/tools/cache/reflector.go:159):
  list returns (objects, resourceVersion); watch streams JSON-lines from
  that version, transparently RECONNECTING from the last seen version when
  the TCP stream drops, and raising ExpiredError (410 Gone) when the
  server's event log no longer covers the resume point — the informer then
  re-lists.
- Client-side optimistic concurrency: guaranteed_update is a
  get -> mutate -> PUT(resourceVersion) -> retry-on-409 loop, exactly how
  reference controllers wrap their writes (GuaranteedUpdate semantics over
  plain REST); the pod convenience verbs reuse it with the same mutate
  logic as the embedded store so both transports produce identical writes.
"""
from __future__ import annotations

import json
import queue
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Optional

from kubernetes_tpu import chaos, obs
from kubernetes_tpu.api import serde
from kubernetes_tpu.store.store import (
    Event, LEASES, PODS, AlreadyExistsError, BackpressureError,
    ConflictError, DisruptionBudgetError, ExpiredError, FencedError,
    NotFoundError, nominated_node_mutator, pod_condition_mutator,
)

# client-runtime metrics (rest_client_requests_total /
# reflector short-watch analogs)
WATCH_RECONNECTS = obs.counter(
    "remote_watch_reconnects_total",
    "Dropped watch streams reopened from the last seen resourceVersion, "
    "by kind.", ("kind",))
WATCH_DECODE_FAILURES = obs.counter(
    "remote_watch_decode_failures_total",
    "Watch events the client could not decode (schema drift -> watch "
    "marked expired), by kind.", ("kind",))
TRANSIENT_RETRIES = obs.counter(
    "remote_transient_retries_total",
    "Transient transport failures retried during watch re-open, by kind.",
    ("kind",))
REQUEST_RETRIES = obs.counter(
    "remote_request_retries_total",
    "Unary requests retried, by verb/outcome class: read / cas / bind / "
    "status retries follow a transient transport failure or 5xx; the "
    "distinct 'backpressure' label counts creates re-sent after a 429 "
    "admission shed, honoring the server's Retry-After with capped "
    "jittered backoff (the shed write never landed, so the retry is "
    "safe). Write classes that are not idempotent (create / delete) "
    "never retry on TRANSPORT failures.", ("verb",))


class APIStatusError(Exception):
    """Non-2xx response that maps to no store error (e.g. 422 admission
    rejection): carries the server's Status reason/message."""

    def __init__(self, code: int, reason: str, message: str):
        super().__init__(f"{code} {reason}: {message}")
        self.code = code
        self.reason = reason
        self.message = message


def _raise_for(code: int, reason: str, message: str,
               retry_after: Optional[str] = None,
               accepted: int = 0) -> None:
    if code == 404:
        raise NotFoundError(message)
    if code == 409:
        if reason == "AlreadyExists":
            raise AlreadyExistsError(message)
        if reason == "Fenced":
            # superseded partition-lease fencing token: the write was
            # rejected WHOLE — a definitive answer for a superseded
            # claim holder, never auto-retried (FencedError subclasses
            # ConflictError, so every conflict path already stops)
            raise FencedError(message)
        raise ConflictError(message)
    if code == 410:
        raise ExpiredError(message)
    if code == 429:
        # two distinct 429 contracts share the status code, split by
        # reason: "Backpressure" is the serving admission shed (the write
        # never landed — retry after the suggested backoff is SAFE),
        # anything else is the eviction subresource's budget refusal
        # (same error type as the embedded verb; never auto-retried).
        # Retry-After carries the server's suggested backoff either way.
        try:
            ra = float(retry_after) if retry_after else 10.0
        except ValueError:
            ra = 10.0
        if reason == "Backpressure":
            # `accepted` rides the status body on batched creates: the
            # first `accepted` items of the batch LANDED, only the tail
            # was shed (0 on the single-create path)
            raise BackpressureError(message, retry_after=ra,
                                    accepted=accepted)
        raise DisruptionBudgetError(message, retry_after=ra)
    raise APIStatusError(code, reason, message)


class RemoteWatch:
    """Chunked JSON-lines watch stream with reflector resume semantics.

    A background reader parses events into a queue; on a dropped stream it
    reopens from the last delivered resourceVersion. A 410 at (re)open
    surfaces as ExpiredError from next()/try_next() — the informer
    re-lists (reflector.go:159 / the server's watch contract)."""

    _RECONNECT_DELAY = 0.05

    def __init__(self, base: str, kind: str, since_rv: Optional[int],
                 timeout: float, token: Optional[str] = None,
                 selector: Optional[str] = None):
        self.kind = kind
        self.selector = selector
        self._base = base
        self._timeout = timeout
        self._token = token
        self._queue: "queue.Queue[Event]" = queue.Queue()
        self._stop = threading.Event()
        self._expired: Optional[str] = None
        self._last_rv = since_rv
        # open synchronously so an immediate 410 raises from watch() like
        # the embedded store's Store.watch does
        self._resp = self._open(since_rv)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"remote-watch-{kind}")
        self._thread.start()

    def _open(self, since_rv: Optional[int]):
        url = f"{self._base}/api/v1/{self.kind}?watch=true"
        if since_rv is not None:
            url += f"&resourceVersion={since_rv}"
        if self.selector is not None:
            # subscription-class key: server-side, watchers sharing it
            # serve from one serialize-once byte ring (reconnects carry
            # it so a resumed stream rejoins its class)
            url += "&selector=" + urllib.parse.quote(self.selector, safe="")
        headers = {}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        req = urllib.request.Request(url, method="GET", headers=headers)
        try:
            return urllib.request.urlopen(req, timeout=self._timeout)
        except urllib.error.HTTPError as e:
            body = _status_body(e)
            _raise_for(e.code, body.get("reason", ""),
                       body.get("message", str(e)))

    def _run(self) -> None:
        resp = self._resp
        while not self._stop.is_set():
            try:
                line = resp.readline()
            except (OSError, ValueError, AttributeError):
                # AttributeError: stop() closed the response under us and
                # http.client's chunked reader lost its fp mid-call
                line = b""
            if self._stop.is_set():
                break
            if line == b"":
                # stream ended: reconnect from the last seen version
                try:
                    resp.close()
                except OSError:
                    pass
                try:
                    WATCH_RECONNECTS.labels(self.kind).inc()
                    resp = self._resp = self._open(self._last_rv)
                except ExpiredError as e:
                    self._expired = str(e)
                    return
                except APIStatusError as e:
                    if e.code in (401, 403):
                        # token revoked/denied mid-watch: not transient.
                        # Surface as expiry so the informer's re-list runs
                        # and raises the auth error to its caller instead
                        # of a silent forever-retry.
                        self._expired = str(e)
                        return
                    TRANSIENT_RETRIES.labels(self.kind).inc()
                    if self._stop.wait(self._RECONNECT_DELAY):
                        return
                except (urllib.error.URLError, OSError, NotFoundError):
                    TRANSIENT_RETRIES.labels(self.kind).inc()
                    if self._stop.wait(self._RECONNECT_DELAY):
                        return
                continue
            line = line.strip()
            if not line:
                continue   # keep-alive blank line
            try:
                d = json.loads(line)
            except ValueError:
                continue
            try:
                rv = int(d.get("resourceVersion", 0))
                obj = serde.from_dict(self.kind, d["object"])
                etype = d["type"]
            except Exception as e:   # noqa: BLE001 — schema drift
                # an event the client cannot decode means the stream is no
                # longer trustworthy (server/client schema drift, not a
                # transport blip): mark the watch expired so next() raises
                # and the informer re-lists, instead of the reader thread
                # dying and next() hanging forever
                WATCH_DECODE_FAILURES.labels(self.kind).inc()
                self._expired = f"watch decode failed for {self.kind}: {e!r}"
                return
            self._last_rv = rv
            self._queue.put(Event(etype, self.kind, obj, rv))

    def _check_expired(self) -> None:
        if self._expired is not None and self._queue.empty():
            raise ExpiredError(self._expired)

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        self._check_expired()
        try:
            return self._queue.get(
                timeout=timeout if timeout and timeout > 0 else 0.001)
        except queue.Empty:
            self._check_expired()
            return None

    def try_next(self) -> Optional[Event]:
        self._check_expired()
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None

    def drain(self) -> list[Event]:
        out = []
        while True:
            ev = self.try_next()
            if ev is None:
                return out
            out.append(ev)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._resp.close()
        except OSError:
            pass


def _status_body(e: urllib.error.HTTPError) -> dict:
    try:
        return json.loads(e.read() or b"{}")
    except ValueError:
        return {}


class RemoteStore:
    """The Store read/write surface over HTTP. Watch streams reconnect;
    unary calls retry transient transport failures with bounded
    exponential backoff + jitter PER VERB CLASS (reads and CAS-guarded
    writes are retry-safe; creates/deletes are not idempotent and fail
    fast), then fail with mapped errors."""

    #: verb class -> (total attempts, base backoff seconds). The bases are
    #: deliberately small: the client's job is to ride out a connection
    #: reset or an apiserver restart blip, not to poll an outage — callers
    #: with real deadlines own the long waits.
    RETRY_POLICY = {
        "read": (4, 0.02),     # GET/LIST: always idempotent
        "cas": (3, 0.02),      # rv-preconditioned PUT: a replay that landed
                               # surfaces as 409 to the CAS loop above it
        "bind": (4, 0.02),     # binding POST: read-your-write dedupe below
        "status": (3, 0.02),   # status subresource PUT (idempotent mutator)
        "write": (1, 0.0),     # create/delete: NOT idempotent — no retry
        # Lease CAS writes (leader-election acquire/renew/claim): exactly
        # ONE attempt, never ridden through transport retries. A retried
        # renew whose first attempt landed answers 409, which the elector
        # must read as a DEFINITIVE lost lease (step down before the
        # fencing window), not something a client-side loop may paper
        # over — a lease retried into "still holding" while another
        # candidate acquired is precisely the split-brain fencing exists
        # to kill. tests/test_remote.TestRetryPolicyTable pins this row.
        "lease": (1, 0.0),
    }

    def __init__(self, base_url: str, timeout: float = 30.0,
                 token: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token   # bearer identity (tokenfile authn analog)
        # deterministic jitter stream + injectable sleep (tests stub it)
        self._rng = random.Random(0xC0FFEE)
        self._sleep = time.sleep

    # -- transport -----------------------------------------------------------
    @staticmethod
    def _is_transient(exc: BaseException) -> bool:
        """A failure worth retrying on an idempotent verb: transport-level
        (connection reset/refused, timeout — incl. the chaos plane's
        injected RemoteFault, a URLError subclass) or a server-side 5xx.
        Mapped client errors (404/409/410/422...) are REAL answers."""
        if isinstance(exc, APIStatusError):
            return exc.code in (500, 502, 503, 504)
        return isinstance(exc, (urllib.error.URLError, OSError,
                                TimeoutError))

    def _backoff(self, attempt: int, base: float) -> float:
        return base * (2 ** attempt) * (0.5 + self._rng.random() / 2)

    def _request_once(self, method: str, path: str,
                      body: Optional[dict] = None) -> Any:
        chaos.check("remote.http")
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            b = _status_body(e)
            try:
                accepted = int(b.get("accepted", 0) or 0)
            except (TypeError, ValueError):
                accepted = 0
            _raise_for(e.code, b.get("reason", ""),
                       b.get("message", str(e)),
                       retry_after=e.headers.get("Retry-After"),
                       accepted=accepted)

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 verb_class: str = "read") -> Any:
        attempts, base = self.RETRY_POLICY.get(verb_class, (1, 0.0))
        for attempt in range(attempts):
            try:
                return self._request_once(method, path, body)
            except Exception as e:   # noqa: BLE001 — filtered below
                if attempt + 1 >= attempts or not self._is_transient(e):
                    raise
                REQUEST_RETRIES.labels(verb_class).inc()
                self._sleep(self._backoff(attempt, base))

    # -- reads ---------------------------------------------------------------
    def get(self, kind: str, key: str) -> Any:
        return serde.from_dict(kind, self._request(
            "GET", f"/api/v1/{kind}/{key}"))

    def list(self, kind: str) -> tuple[list[Any], int]:
        d = self._request("GET", f"/api/v1/{kind}")
        return ([serde.from_dict(kind, o) for o in d["items"]],
                int(d["resourceVersion"]))

    def watch(self, kind: str, since_rv: Optional[int] = None,
              selector: Optional[str] = None) -> RemoteWatch:
        return RemoteWatch(self.base_url, kind, since_rv, self.timeout,
                           token=self.token, selector=selector)

    #: (total attempts, cap seconds) for 429-Backpressure retries on
    #: create: the server's Retry-After is honored but capped (a server
    #: suggesting minutes must not stall the client thread), with the
    #: same 0.5-1.0x jitter stream as the transport backoff so a shed
    #: wave of clients doesn't re-arrive in phase. Distinct from the
    #: transport RETRY_POLICY: a 429 means the write definitively did
    #: NOT land, so re-POSTing is safe even though POST isn't idempotent.
    BACKPRESSURE_RETRY = (6, 2.0)

    # -- writes --------------------------------------------------------------
    def create(self, kind: str, obj: Any, move: bool = False) -> Any:
        # `move` is the embedded store's no-clone fast path; over the wire
        # serialization copies regardless. POST is not idempotent (a retry
        # whose first attempt landed would AlreadyExists) — no auto-retry
        # on TRANSPORT failures; only the 429-Backpressure shed (which
        # proves the write never landed) re-sends, on its own policy.
        attempts, cap = self.BACKPRESSURE_RETRY
        body = serde.to_dict(obj)
        for attempt in range(attempts):
            try:
                return serde.from_dict(kind, self._request(
                    "POST", f"/api/v1/{kind}", body, verb_class="write"))
            except BackpressureError as e:
                if attempt + 1 >= attempts:
                    raise
                REQUEST_RETRIES.labels("backpressure").inc()
                self._sleep(min(e.retry_after, cap)
                            * (0.5 + self._rng.random() / 2))

    def create_many(self, kind: str, objs: list, move: bool = False) -> None:
        """Batched create: ONE collection POST ({"items": [...]}) so the
        server runs ONE admission-gate evaluation + one batched ledger
        stamp for the whole flush (the round-17 arrival-ingest contract).
        A partial shed surfaces as BackpressureError carrying `accepted`
        (how many items of the prefix landed) + the server's Retry-After;
        NO auto-retry here — partial acceptance makes a blind re-POST
        unsafe, so the caller (ArrivalGenerator) re-queues the shed tail
        on its own backoff. Callers pass fresh uniquely-named objects,
        exactly like the embedded verb."""
        del move   # serialization copies regardless, as in create()
        self._request("POST", f"/api/v1/{kind}",
                      {"items": [serde.to_dict(o) for o in objs]},
                      verb_class="write")

    def update(self, kind: str, obj: Any,
               expect_rv: Optional[int] = None) -> Any:
        d = serde.to_dict(obj)
        # the server uses the object's resourceVersion as the CAS
        # precondition; expect_rv overrides it (None = unconditional)
        d["resource_version"] = expect_rv if expect_rv is not None else 0
        if kind == LEASES and expect_rv is not None:
            # lease acquire/renew CAS: one attempt, fail fast to the
            # elector (see the RETRY_POLICY "lease" row)
            verb = "lease"
        else:
            verb = "cas" if expect_rv is not None else "write"
        return serde.from_dict(kind, self._request(
            "PUT", f"/api/v1/{kind}/{obj.key}", d, verb_class=verb))

    def update_many(self, kind: str, updates: list, fence=None,
                    token: Optional[str] = None,
                    conflicts: Optional[list] = None,
                    missing: Optional[list] = None) -> list:
        """Batched update: ONE collection PUT ({"items": [...]}) — the
        churn plane's mutation twin of create_many. `updates` takes
        objects or (obj, expect_rv) pairs; each item's rv-CAS rides its
        serialized resource_version (0 = unconditional, matching the
        serial update()'s wire contract). Per-item refusals come back in
        the body and land in the caller's `conflicts`/`missing` lists —
        never an exception; 409 reason=Fenced (whole-batch) maps to
        FencedError. NOT idempotent under partial landing: no transport
        auto-retry (write verb class), same stance as create_many.
        Returns the stored snapshots echoed by the server, exactly like
        the embedded verb."""
        del token   # the server-side verb dedupes embedded callers only
        items = []
        for u in updates:
            obj, expect_rv = u if isinstance(u, tuple) else (u, None)
            d = serde.to_dict(obj)
            d["resource_version"] = expect_rv if expect_rv is not None \
                else 0
            items.append(d)
        body: dict = {"items": items}
        if fence:
            body["fence"] = [[s, t] for s, t in fence]
        out = self._request("PUT", f"/api/v1/{kind}", body,
                            verb_class="write")
        if conflicts is not None:
            conflicts.extend(out.get("conflicts") or [])
        if missing is not None:
            missing.extend(out.get("missing") or [])
        return [serde.from_dict(kind, d) for d in out.get("items") or []]

    def delete(self, kind: str, key: str) -> Any:
        return serde.from_dict(kind, self._request(
            "DELETE", f"/api/v1/{kind}/{key}", verb_class="write"))

    def contains(self, kind: str, key: str) -> bool:
        """Existence probe (the stale-host check's verb): GET mapped to
        bool. Rides the read retry policy."""
        try:
            self._request("GET", f"/api/v1/{kind}/{key}")
            return True
        except NotFoundError:
            return False

    def evict_pod(self, pod_key: str, reason: str = "api") -> Any:
        """POST pods/{ns}/{name}/eviction — the PDB-guarded delete. An
        exhausted budget surfaces as DisruptionBudgetError (429 +
        Retry-After mapped by _raise_for). NOT idempotent (a retry whose
        first attempt landed would double-charge the budget): no
        auto-retry, matching create/delete."""
        del reason   # the server books its own reason label for HTTP evicts
        return serde.from_dict(PODS, self._request(
            "POST", f"/api/v1/{PODS}/{pod_key}/eviction", {},
            verb_class="write"))

    def evict_many(self, pod_keys: list, reason: str = "api", fence=None,
                   token: Optional[str] = None,
                   stop_on_refusal: bool = False) -> dict:
        """POST pods/evictions — the batched PDB-guarded delete. Answers
        the embedded verb's per-item outcome dict ({key: "evicted" |
        "refused" | "missing" | "skipped" | "invalid"}); a refusal is an
        OUTCOME, never a 429, so callers refund tokens item-by-item. NOT
        idempotent (evicted items charged budgets): no auto-retry,
        matching evict_pod."""
        del fence, token   # embedded-verb seams; the wire batch is one POST
        out = self._request(
            "POST", f"/api/v1/{PODS}/evictions",
            {"keys": list(pod_keys), "reason": reason,
             "stop_on_refusal": bool(stop_on_refusal)},
            verb_class="write")
        return dict(out.get("outcomes") or {})

    def bind_pod(self, pod_key: str, node_name: str, fence=None) -> Any:
        """POST pods/{ns}/{name}/binding (factory.go:710), idempotent
        under retry: a transient failure after the POST went out is
        AMBIGUOUS (the write may have landed, only the response was lost),
        so before re-POSTing the client reads the pod back — a binding
        that already landed is success, never re-POSTed, and therefore
        never double-bumps the rv or double-emits the MODIFIED event.

        `fence` rides the body as [[scope, token], ...]; the server's 409
        reason=Fenced maps to FencedError (definitive, no retry), and the
        rv-CAS already-bound refusal maps to ConflictError."""
        attempts, base = self.RETRY_POLICY["bind"]
        path = f"/api/v1/{PODS}/{pod_key}/binding"
        body: dict = {"node": node_name}
        if fence:
            pairs = [fence] if isinstance(fence, tuple) else list(fence)
            body["fence"] = [[s, int(t)] for s, t in pairs]
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            if attempt:
                # ambiguity check FIRST: did the lost attempt land?
                try:
                    current = self.get(PODS, pod_key)
                    if current.node_name == node_name:
                        return current
                except NotFoundError:
                    raise
                except Exception:   # noqa: BLE001 — probe is best-effort
                    pass
                REQUEST_RETRIES.labels("bind").inc()
                self._sleep(self._backoff(attempt - 1, base))
            try:
                return self._request_once("POST", path, body)
            except Exception as e:   # noqa: BLE001 — filtered below
                if not self._is_transient(e):
                    raise
                last = e
        raise last

    def bind_pods(self, bindings: list[tuple[str, str]],
                  fence=None, conflicts: Optional[list] = None) -> list[str]:
        """Batch contract of Store.bind_pods over the wire: one POST per
        binding (the REST surface has no batch verb, matching the
        reference), missing pods reported back instead of raised. rv-CAS
        losers (409 Conflict) go to `conflicts` when a list is passed,
        else ride the missing return — either way the caller requeues
        them. A FencedError STOPS the batch immediately and propagates:
        a superseded claim holder must not keep writing its tail."""
        missing = []
        for pod_key, node_name in bindings:
            try:
                self.bind_pod(pod_key, node_name, fence=fence)
            except NotFoundError:
                missing.append(pod_key)
            except FencedError:
                raise
            except ConflictError:
                if conflicts is not None:
                    conflicts.append(pod_key)
                else:
                    missing.append(pod_key)
        return missing

    def commit_wave(self, bindings: list[tuple[str, str]],
                    events: Optional[list] = None,
                    token: Optional[str] = None,
                    fence=None,
                    conflicts: Optional[list] = None) -> list[str]:
        """Wave contract of Store.commit_wave over the wire: binds via the
        binding subresource (404 -> missing, mapped exactly like
        bind_pods; 409 Conflict -> rv-CAS loser; 409 Fenced aborts the
        wave), then the audit records of the binds that landed via
        per-record POSTs — each isolated and fire-and-forget like the
        recorder's remote path (a rejected or undeliverable event write
        never fails the commit).

        Idempotency: the REST surface carries no wave token, so the
        embedded store's token map is replaced by per-verb dedupe — every
        bind retry read-checks before re-POSTing (bind_pod), and a retried
        record create that already landed dies on 409 AlreadyExists and is
        dropped (record keys are deterministic per event). `token` is
        accepted for surface parity with the embedded store."""
        del token   # per-verb dedupe makes the wave token redundant here
        confl: list = []
        missing = self.bind_pods(bindings, fence=fence, conflicts=confl)
        if events:
            from kubernetes_tpu.store.store import EVENTS
            gone = set(missing) | set(confl)
            drop = (APIStatusError, AlreadyExistsError, ConflictError,
                    OSError, urllib.error.URLError)
            for (pod_key, _n), rec in zip(bindings, events):
                if pod_key in gone:
                    continue
                try:
                    self.create(EVENTS, rec, move=True)
                except drop:
                    continue
        if conflicts is not None:
            conflicts.extend(confl)
            return missing
        return missing + confl

    def advance_fence(self, scope: str, token: int) -> bool:
        """POST /api/v1/fences/{scope} — the claim handoff's fence
        advance over the wire. Idempotent (the server records a maximum),
        so it rides the cas retry class; a 409 Fenced answer means the
        caller's token is itself already superseded -> False."""
        try:
            self._request("POST", f"/api/v1/fences/{scope}",
                          {"token": int(token)}, verb_class="cas")
            return True
        except FencedError:
            return False

    def fanout_wave(self) -> None:
        """Watch fan-out happens server-side (the embedded store's commit
        core); the wire client has nothing to deliver."""

    def guaranteed_update(self, kind: str, key: str,
                          mutate: Callable[[Any], Any],
                          allow_skip: bool = False) -> Any:
        """Client-side read-modify-write loop: GET, mutate, PUT with the
        read resourceVersion, retry on 409 — the reference controller
        pattern over plain REST."""
        while True:
            current = self.get(kind, key)
            rv = current.resource_version
            updated = mutate(current)
            if allow_skip and updated is None:
                return current
            try:
                return self.update(kind, updated, expect_rv=rv)
            except ConflictError:
                continue

    # pod conveniences: the SAME mutate closures as the embedded store
    # (store.nominated_node_mutator / pod_condition_mutator), so both
    # transports write identical objects by construction
    def set_nominated_node_name(self, pod_key: str, node_name: str) -> Any:
        return self.guaranteed_update(PODS, pod_key,
                                      nominated_node_mutator(node_name))

    def update_pod_condition(self, pod_key: str, condition) -> Any:
        return self.guaranteed_update(PODS, pod_key,
                                      pod_condition_mutator(condition),
                                      allow_skip=True)

    def update_pod_group_status(self, group_key: str,
                                phase: Optional[str] = None,
                                members: Optional[int] = None,
                                scheduled: Optional[int] = None,
                                now: Optional[float] = None) -> Any:
        """PodGroup /status subresource over the wire (the server applies
        the SAME pod_group_status_mutator the embedded store uses, so both
        transports produce identical writes). 404 maps to NotFoundError
        exactly like the embedded verb raising on a missing group."""
        from kubernetes_tpu.store.store import PODGROUPS
        d = self._request(
            "PUT", f"/api/v1/{PODGROUPS}/{group_key}/status",
            {"phase": phase, "members": members, "scheduled": scheduled,
             "last_transition_time": now}, verb_class="status")
        return serde.from_dict(PODGROUPS, d)
