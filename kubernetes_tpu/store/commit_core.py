"""Commit core: the store's versioned write log + watch fan-out engine.

This module is the REFEREE for `kubernetes_tpu/native/commitcore.cpp` — the
C++ CPython extension that turns the store's three hot host loops (batched
bind, batched create+event write, watch fan-out) into one native call each
per burst wave. Both implementations expose the same object protocol and
must produce BIT-IDENTICAL observable state: resourceVersion assignment
order, missing-key detection, AlreadyExists raises, per-watcher event
sequences, and overflow/resync decisions (tests/test_commit_core.py pins
them against each other op-for-op).

Design (shared by twin and native):

- The core owns the store's rv counter and the per-kind event LOG — a
  bounded ring of (etype, obj, rv) entries with an absolute sequence
  number. Objects in the log are the store's write snapshots (the same
  aliasing contract as before: read-only by convention).
- A watcher is a CURSOR into its kind's log, not a private queue: fan-out
  is O(watchers) per wave (advance the published cursor + wake sleepers),
  not O(watchers x events), and the consumer thread materializes its own
  `Event` objects at copy-out — moving that per-event cost OFF the commit
  thread (the native core also releases the GIL while a consumer blocks,
  so watch delivery overlaps the next wave's commit).
- Slow consumers are BOUNDED: a watcher whose backlog exceeds `ring_size`
  (or whose cursor falls out of the log ring) is dropped-with-resync —
  its pending events are discarded and the next poll raises ExpiredError,
  exactly the reference's 410-Gone watch-cache semantics. The store
  counts these on `watch_dropped_total{reason}`.
- Writes APPEND pending entries without delivering; `flush()` publishes
  them to watchers in log order. Serial store verbs flush before
  returning; `Store.commit_wave` defers so the wave's fan-out is one
  separate call (`Store.fanout_wave`) that can overlap the commit tail.

The rv counter and log appends are guarded by the Store's lock (every
writer holds it); the cursor/notify state has its own condition so
copy-out never touches the store lock.
"""
from __future__ import annotations

import copy
import os
import threading
import time as _time
from bisect import bisect_right
from typing import Any, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


def _clone(obj: Any) -> Any:
    """The store's write-snapshot rule: a fast clone() when the type has
    one, deepcopy otherwise (identical to store._clone; the native core
    implements the same attribute probe)."""
    c = getattr(obj, "clone", None)
    return c() if c is not None else copy.deepcopy(obj)


class _KindLog:
    __slots__ = ("entries", "rvs", "start", "flushed")

    def __init__(self):
        # (etype, obj, rv, ts) from abs seq `start`; ts is the monotonic
        # commit stamp feeding the watch_fanout_lag_seconds histogram
        # (commit -> copy-out) through the fan-out sink
        self.entries: list = []
        self.rvs: list[int] = []  # parallel rv vector (attach binary search)
        self.start = 0            # absolute seq of entries[0]
        self.flushed = 0          # absolute seq events are published up to

    @property
    def end(self) -> int:
        return self.start + len(self.entries)


class _SubClass:
    """One shared subscription class: every watcher with the same
    (kind, selector) interest indexes the same materialize-once caches.
    `evs`/`lines` are parallel slot vectors aligned to the kind log from
    absolute seq `cache_start` (realigned lazily at poll when the log
    ring evicts) — slot i caches the Event object / pre-encoded wire
    line for log entry `cache_start + i`, filled first-writer-wins by
    whichever classmate copies the entry out first. The selector is an
    OPAQUE interest key (class dedupe only, never an event filter)."""

    __slots__ = ("kind", "selector", "members", "cache_start",
                 "evs", "lines")

    def __init__(self, kind: str, selector: str, log: _KindLog):
        self.kind = kind
        self.selector = selector
        self.members = 0
        # cover the full current log window so replaying watchers
        # (attach with since_rv) index valid slots
        self.cache_start = log.start
        self.evs: list = [None] * len(log.entries)
        self.lines: list = [None] * len(log.entries)

    def align(self, log: _KindLog) -> None:
        """Realign the slot vectors to the log window [start, end)."""
        if self.cache_start < log.start:
            drop = min(len(self.evs), log.start - self.cache_start)
            del self.evs[:drop]
            del self.lines[:drop]
            self.cache_start = log.start
        need = log.end - self.cache_start - len(self.evs)
        if need > 0:
            self.evs.extend([None] * need)
            self.lines.extend([None] * need)


class _Watcher:
    __slots__ = ("kind", "cursor", "resync", "stopped", "cls")

    def __init__(self, kind: str, cursor: int,
                 cls: Optional[_SubClass] = None):
        self.kind = kind
        self.cursor = cursor      # absolute seq of the next entry to read
        self.resync = False
        self.stopped = False
        self.cls = cls            # shared subscription class (None = private)


class PyCommitCore:
    """Pure-Python twin of native/commitcore.cpp (identical semantics)."""

    is_native = False

    def __init__(self, log_size: int, ring_size: int,
                 event_cls, expired_exc, already_exists_exc):
        self._log_size = int(log_size)
        self._ring_size = int(ring_size)
        self._event_cls = event_cls
        self._expired = expired_exc
        self._already = already_exists_exc
        self._rv = 0
        self._logs: dict[str, _KindLog] = {}
        self._watchers: dict[int, _Watcher] = {}
        self._by_kind: dict[str, list[int]] = {}
        self._next_wid = 0
        self._cond = threading.Condition(threading.Lock())
        self._fanout_sink = None
        # shared subscription classes (round 20): watchers with the same
        # (kind, selector) interest share one materialize-once Event cache
        # and one serialize-once byte cache. `set_shared_classes(False)`
        # is the old-shape degenerate mode (every watcher private) used by
        # the differential parity tests.
        self._shared_classes = True
        self._classes: dict[tuple[str, str], _SubClass] = {}
        self._wire_encoder = None      # (etype, obj, rv) -> bytes
        self._stat_mat = 0             # Event materializations (cache miss)
        self._stat_shared = 0          # deliveries served from a class cache
        self._stat_enc = 0             # wire-line encodes (cache miss)
        self._stat_bytes = 0           # wire bytes served (hits + misses)
        # fencing-token table (round 18, active-active fleet): scope ->
        # the highest lease fencing token validated so far. Guarded by
        # the STORE's lock like the rv counter (every writer holds it);
        # never touched from consumer threads.
        self._fences: dict[str, int] = {}

    def set_fanout_sink(self, sink) -> None:
        """Observability hook (identical on the native core): called at
        poll copy-out with (kind, events, lags) — `lags[i]` is the seconds
        between events[i]'s commit stamp and this copy-out. The store wires
        it to the watch_fanout_lag_seconds histogram and the pod-lifecycle
        ledger's copy-out stamp. Never part of parity-observable state."""
        self._fanout_sink = sink

    def set_wire_encoder(self, encoder) -> None:
        """Serialize-once byte ring (round 20): `encoder(etype, obj, rv)`
        must return the complete wire line (bytes) for one event. Encoded
        lines are cached per subscription class, so the HTTP watch path
        pays ONE serialization per event per class regardless of how many
        watchers stream it. Observability/delivery-plane only."""
        self._wire_encoder = encoder

    def set_shared_classes(self, enabled: bool) -> None:
        """Toggle class sharing for FUTURE attaches (old-shape degenerate
        mode when False: every watcher materializes privately, exactly the
        pre-round-20 copy-out path — the differential tests pin the two
        modes bit-identical)."""
        self._shared_classes = bool(enabled)

    # -- fencing tokens (round 18; caller holds the store lock) --------------
    # A scope names one partition lease (e.g. "fleet-default-scheduler-s3");
    # tokens are the lease's resourceVersion at acquisition, so a later
    # claimant's token is strictly greater. `fence_ok` is the read-only
    # validation (a write carrying a token below the recorded maximum is
    # superseded and must be rejected WHOLE before anything lands);
    # `advance_fence` records the new maximum. The native core implements
    # the identical pair (commitcore.cpp), and the parity tests drive both
    # through the store's random-program harness.
    def fence_ok(self, scope: str, token: int) -> bool:
        return int(token) >= self._fences.get(scope, 0)

    def advance_fence(self, scope: str, token: int) -> bool:
        token = int(token)
        if token < self._fences.get(scope, 0):
            return False
        self._fences[scope] = token
        return True

    def fence_token(self, scope: str) -> int:
        return self._fences.get(scope, 0)

    def fence_table(self) -> dict:
        return dict(self._fences)

    def adopt_fences(self, table: dict) -> None:
        """Carry a demoted core's fence table over: the twin must keep
        rejecting superseded writers with no gap."""
        for scope, token in table.items():
            if int(token) > self._fences.get(scope, 0):
                self._fences[scope] = int(token)

    # -- rv ------------------------------------------------------------------
    def rv(self) -> int:
        return self._rv

    def set_rv(self, v: int) -> None:
        self._rv = int(v)

    def next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # -- log append (pending; caller holds the store lock) -------------------
    def _kind_log(self, kind: str) -> _KindLog:
        log = self._logs.get(kind)
        if log is None:
            log = self._logs[kind] = _KindLog()
        return log

    def _append(self, log: _KindLog, etype: str, obj: Any, rv: int,
                ts: Optional[float] = None) -> None:
        log.entries.append((etype, obj, rv,
                            ts if ts is not None else _time.perf_counter()))
        log.rvs.append(rv)
        if len(log.entries) > self._log_size:
            n = len(log.entries) - self._log_size
            del log.entries[:n]
            del log.rvs[:n]
            log.start += n
            # a cursor the eviction passed is detected at flush/poll time
            # (cursor < log.start -> drop-with-resync)

    def append(self, etype: str, kind: str, obj: Any, rv: int) -> None:
        """One pending log entry (the serial update/delete verbs)."""
        self._append(self._kind_log(kind), etype, obj, rv)

    # -- batched write verbs (pending; caller holds the store lock) ----------
    def bind_batch(self, bucket: dict, kind: str,
                   bindings: list[tuple[str, str]]) -> list[str]:
        """The store's batched bind body (_bind_locked semantics per
        binding): clone, set node_name, assign the next rv, replace the
        bucket entry, log MODIFIED. Returns the keys that were missing."""
        log = self._kind_log(kind)
        ts = _time.perf_counter()   # one commit stamp for the whole batch
        missing = []
        for pod_key, node_name in bindings:
            current = bucket.get(pod_key)
            if current is None:
                missing.append(pod_key)
                continue
            stored = current.clone()
            stored.node_name = node_name
            self._rv += 1
            stored.resource_version = self._rv
            bucket[pod_key] = stored
            self._append(log, MODIFIED, stored, self._rv, ts)
        return missing

    def create_batch(self, bucket: dict, kind: str, objs: list,
                     move: bool) -> list:
        """The store's batched create body (_create_locked semantics per
        object): raise AlreadyExists on a duplicate key, snapshot unless
        `move`, assign the next rv, log ADDED. Returns the stored objects."""
        log = self._kind_log(kind)
        ts = _time.perf_counter()   # one commit stamp for the whole batch
        out = []
        for obj in objs:
            key = obj.key
            if key in bucket:
                raise self._already(f"{kind}/{key}")
            stored = obj if move else _clone(obj)
            self._rv += 1
            stored.resource_version = self._rv
            bucket[key] = stored
            self._append(log, ADDED, stored, self._rv, ts)
            out.append(stored)
        return out

    def update_batch(self, bucket: dict, kind: str, objs: list) -> list:
        """The store's batched update body (round 23; update() semantics
        per object): snapshot the caller's replacement object, assign the
        next rv, replace the bucket entry, log MODIFIED — one commit
        stamp for the whole batch. NotFound / rv-CAS refusals are the
        STORE's per-item pre-scan (under the same lock), so every object
        reaching the core lands. Returns the stored snapshots."""
        log = self._kind_log(kind)
        ts = _time.perf_counter()   # one commit stamp for the whole batch
        out = []
        for obj in objs:
            stored = _clone(obj)
            self._rv += 1
            stored.resource_version = self._rv
            bucket[obj.key] = stored
            self._append(log, MODIFIED, stored, self._rv, ts)
            out.append(stored)
        return out

    def delete_batch(self, bucket: dict, kind: str, keys: list) -> list:
        """The store's batched delete body (round 23; delete() semantics
        per key): pop the bucket entry and log DELETED with a snapshot at
        the next rv — one commit stamp for the whole batch. The DELETED
        payload keeps the object's last stored rv (only the log entry
        carries the delete's own rv, exactly like the serial verb).
        Missing keys are skipped; returns the popped originals."""
        log = self._kind_log(kind)
        ts = _time.perf_counter()   # one commit stamp for the whole batch
        gone = []
        for key in keys:
            obj = bucket.pop(key, None)
            if obj is None:
                continue
            self._rv += 1
            self._append(log, DELETED, _clone(obj), self._rv, ts)
            gone.append(obj)
        return gone

    def commit_wave(self, pod_bucket: dict, pod_kind: str,
                    bindings: list[tuple[str, str]],
                    ev_bucket: dict, ev_kind: str, recs: list) -> list[str]:
        """One burst wave's whole store-write tail in one call: the batched
        bind plus the audit-record creates for the bindings that landed
        (recs[i] rides bindings[i]; a vanished pod's record is skipped,
        like the serial path that never reaches its Scheduled event).
        Event creates are move=True (recorder ownership transfer)."""
        missing = self.bind_batch(pod_bucket, pod_kind, bindings)
        if recs:
            if missing:
                miss = set(missing)
                recs = [r for (k, _n), r in zip(bindings, recs)
                        if k not in miss]
            self.create_batch(ev_bucket, ev_kind, recs, True)
        return missing

    def commit_wave_binds(self, pod_bucket: dict, pod_kind: str,
                          bindings: list[tuple[str, str]],
                          ev_bucket: dict, ev_kind: str,
                          record_cls, component: str,
                          seq0: int) -> list[str]:
        """commit_wave with the Scheduled-event payloads built INSIDE the
        core (round 17): the caller passes only (key, node) bindings plus
        the record class / component / reserved name-sequence start, and
        the core constructs one `Successfully assigned {key} to {node}`
        record per LANDED binding (binding i names its record seq0+i;
        vanished pods consume their seq but emit nothing — exactly the
        serial path that never reaches its Scheduled event). Deletes the
        last per-pod Python construction from the commit thread when the
        native core runs this; this twin is the referee."""
        from kubernetes_tpu.store.record import build_scheduled_records
        missing = self.bind_batch(pod_bucket, pod_kind, bindings)
        if bindings:
            recs = build_scheduled_records(record_cls, bindings,
                                           component, seq0)
            if missing:
                miss = set(missing)
                recs = [r for (k, _n), r in zip(bindings, recs)
                        if k not in miss]
            if recs:
                self.create_batch(ev_bucket, ev_kind, recs, True)
        return missing

    # -- fan-out -------------------------------------------------------------
    def flush(self) -> int:
        """Publish every pending entry to its kind's watchers (log order)
        and wake blocked polls. A watcher whose backlog would exceed the
        ring bound — or whose cursor the log ring already evicted — is
        dropped-with-resync. Returns the number of events dropped."""
        dropped = 0
        with self._cond:
            for kind, log in self._logs.items():
                if log.flushed >= log.end:
                    continue
                log.flushed = log.end
                for wid in self._by_kind.get(kind, ()):
                    w = self._watchers[wid]
                    if w.resync or w.stopped:
                        continue
                    backlog = log.flushed - w.cursor
                    if w.cursor < log.start or backlog > self._ring_size:
                        dropped += backlog
                        w.cursor = log.flushed
                        w.resync = True
            self._cond.notify_all()
        return dropped

    # -- watch ---------------------------------------------------------------
    def _join_class(self, kind: str, selector: Optional[str],
                    log: _KindLog) -> Optional[_SubClass]:
        """Resolve (kind, selector) to its shared class, creating it on
        first membership (attach/detach move a refcount, never a backlog).
        Returns None in degenerate mode. Caller holds `_cond`."""
        if not self._shared_classes:
            return None
        key = (kind, selector or "")
        cls = self._classes.get(key)
        if cls is None:
            cls = self._classes[key] = _SubClass(kind, key[1], log)
        cls.members += 1
        return cls

    def attach(self, kind: str, since_rv: Optional[int],
               selector: Optional[str] = None) -> int:
        """New watcher cursor. since_rv=None -> only events published after
        this point; else replay from the log, raising ExpiredError when the
        resume point predates the log window (410 Gone). `selector` is the
        watcher's interest key: identical (kind, selector) watchers dedupe
        into one shared subscription class (None joins the kind's default
        class); it never filters events."""
        log = self._kind_log(kind)
        with self._cond:
            if since_rv is None:
                cursor = log.end
            elif log.rvs and since_rv < log.rvs[0] - 1:
                raise self._expired(
                    f"{kind}: rv {since_rv} older than log window")
            else:
                cursor = log.start + bisect_right(log.rvs, since_rv)
            wid = self._next_wid
            self._next_wid += 1
            cls = self._join_class(kind, selector, log)
            self._watchers[wid] = _Watcher(kind, cursor, cls)
            self._by_kind.setdefault(kind, []).append(wid)
            return wid

    def adopt_watcher(self, wid: int, kind: str, resync: bool = True,
                      selector: Optional[str] = None) -> None:
        """Take over a watcher id from a DEMOTED core (store fault plane):
        the Watch object keeps its wid, but its cursor state died with the
        old core, so the adopted watcher starts at the log head marked
        `resync` — the next poll raises ExpiredError and the consumer
        re-lists (the standard drop-with-resync contract). Class membership
        RIDES the adoption (round 20): the adopted watcher re-joins its
        (kind, selector) subscription class so classmates keep sharing the
        materialize-once caches after failover. Twin-only: the native core
        is never the demotion TARGET."""
        log = self._kind_log(kind)
        with self._cond:
            w = _Watcher(kind, log.end, self._join_class(kind, selector, log))
            w.resync = bool(resync)
            self._watchers[wid] = w
            self._by_kind.setdefault(kind, []).append(wid)
            self._next_wid = max(self._next_wid, wid + 1)
            self._cond.notify_all()

    def detach(self, wid: int) -> None:
        with self._cond:
            w = self._watchers.pop(wid, None)
            if w is not None:
                w.stopped = True
                lst = self._by_kind.get(w.kind, [])
                if wid in lst:
                    lst.remove(wid)
                cls = w.cls
                if cls is not None:
                    cls.members -= 1
                    if cls.members <= 0:
                        self._classes.pop((cls.kind, cls.selector), None)
            self._cond.notify_all()

    def _poll_pick(self, wid: int, timeout: Optional[float], limit: int,
                   bytes_mode: bool = False):
        """The shared wait-and-pick half of poll/poll_bytes: block for the
        first published entry, detect drop-with-resync, slice the picked
        entries and advance the cursor, and snapshot the watcher's class
        cache slots — all under `_cond`. Returns None on timeout/stop."""
        deadline = None
        if timeout and timeout > 0:
            deadline = _time.monotonic() + timeout
        with self._cond:
            while True:
                w = self._watchers.get(wid)
                if w is None:
                    return None
                if w.resync:
                    raise self._expired(
                        f"{w.kind}: watch dropped (resync required)")
                log = self._logs[w.kind]
                if w.cursor < log.start:
                    # the ring evicted entries this watcher never consumed
                    w.resync = True
                    raise self._expired(
                        f"{w.kind}: rv window evicted before copy-out")
                if w.cursor < log.flushed:
                    break
                if timeout == 0:
                    return None
                wait = None
                if deadline is not None:
                    wait = deadline - _time.monotonic()
                    if wait <= 0:
                        return None
                self._cond.wait(wait)   # None = wait forever
            c0 = w.cursor
            lo = c0 - log.start
            n = min(limit, log.flushed - c0)
            picked = log.entries[lo: lo + n]
            w.cursor += n
            cls = w.cls
            cached_evs = cached_lines = None
            if cls is None:
                # old-shape private watcher: every pick materializes
                self._stat_mat += n
            else:
                cls.align(log)
                base = c0 - cls.cache_start
                cached_evs = cls.evs[base: base + n]
                cached_lines = cls.lines[base: base + n]
                hits = cached_lines if bytes_mode else cached_evs
                self._stat_shared += sum(1 for h in hits if h is not None)
        return w, picked, c0, cls, cached_evs, cached_lines

    def _install_shared(self, cls: _SubClass, made_ev: list,
                        made_ln: list, nbytes: int) -> list:
        """First-writer-wins cache fill for events/lines this poll
        materialized. Returns the (event, entry) pairs THIS call installed
        — the fan-out sink fires for exactly those, so lag is observed
        once per event per class, not once per watcher."""
        installed = []
        with self._cond:
            self._stat_mat += len(made_ev)
            self._stat_enc += len(made_ln)
            self._stat_bytes += nbytes
            for seq, e, entry in made_ev:
                ci = seq - cls.cache_start
                if 0 <= ci < len(cls.evs) and cls.evs[ci] is None:
                    cls.evs[ci] = e
                    installed.append((e, entry))
            for seq, ln in made_ln:
                ci = seq - cls.cache_start
                if 0 <= ci < len(cls.lines) and cls.lines[ci] is None:
                    cls.lines[ci] = ln
        return installed

    def _sink_fire(self, kind: str, events: list, entries: list) -> None:
        sink = self._fanout_sink
        if sink is None or not events:
            return
        # copy-out stamp: commit->copy-out lag per event, observed on
        # the CONSUMER's thread (the identical hook exists in
        # commitcore.cpp's poll)
        now = _time.perf_counter()
        try:
            sink(kind, events, [now - en[3] for en in entries])
        except Exception:
            pass   # observability must never break delivery

    def poll(self, wid: int, timeout: Optional[float],
             limit: int) -> list:
        """Copy out up to `limit` published events past the watcher's
        cursor, blocking up to `timeout` seconds (None = forever) for the
        first one. Returns [] on timeout or after stop; raises ExpiredError
        when the watcher was dropped (slow consumer / log window). With a
        shared subscription class, each entry is materialized into an Event
        ONCE per class (first classmate to copy it out) and every later
        classmate is served the cached object — per-watcher event streams
        stay value-identical to the private path."""
        res = self._poll_pick(wid, timeout, limit)
        if res is None:
            return []
        w, picked, c0, cls, cached_evs, _cached_lines = res
        ev = self._event_cls
        kind = w.kind
        if cls is None:
            events = [ev(t, kind, o, rv) for t, o, rv, _ts in picked]
            self._sink_fire(kind, events, picked)
            return events
        events = []
        made = []   # (abs seq, event, entry) materialized by this call
        for i, entry in enumerate(picked):
            e = cached_evs[i]
            if e is None:
                e = ev(entry[0], kind, entry[1], entry[2])
                made.append((c0 + i, e, entry))
            events.append(e)
        if made:
            installed = self._install_shared(cls, made, [], 0)
            if installed:
                self._sink_fire(kind, [e for e, _en in installed],
                                [en for _e, en in installed])
        return events

    def poll_bytes(self, wid: int, timeout: Optional[float],
                   limit: int) -> list:
        """poll(), but returns pre-encoded wire lines (bytes) from the
        class's serialize-once byte ring: each entry is encoded ONCE per
        class and every watcher streams the same bytes object — zero
        per-watcher encoding on the delivery thread. Requires a wire
        encoder (`set_wire_encoder`)."""
        enc = self._wire_encoder
        if enc is None:
            raise RuntimeError("wire encoder not set")
        res = self._poll_pick(wid, timeout, limit, bytes_mode=True)
        if res is None:
            return []
        w, picked, c0, cls, cached_evs, cached_lines = res
        ev = self._event_cls
        kind = w.kind
        if cls is None:
            events = [ev(t, kind, o, rv) for t, o, rv, _ts in picked]
            lines = [enc(t, o, rv) for t, o, rv, _ts in picked]
            self._sink_fire(kind, events, picked)
            with self._cond:
                self._stat_enc += len(lines)
                self._stat_bytes += sum(len(b) for b in lines)
            return lines
        lines = []
        made_ev = []   # events materialized here (sink + classmate reuse)
        made_ln = []
        for i, entry in enumerate(picked):
            ln = cached_lines[i]
            if ln is None:
                ln = enc(entry[0], entry[1], entry[2])
                made_ln.append((c0 + i, ln))
                if cached_evs[i] is None:
                    made_ev.append((c0 + i,
                                    ev(entry[0], kind, entry[1], entry[2]),
                                    entry))
            lines.append(ln)
        installed = self._install_shared(cls, made_ev, made_ln,
                                         sum(len(b) for b in lines))
        if installed:
            self._sink_fire(kind, [e for e, _en in installed],
                            [en for _e, en in installed])
        return lines

    # -- introspection (tests / bench) ---------------------------------------
    def backlog(self, wid: int) -> int:
        with self._cond:
            w = self._watchers.get(wid)
            if w is None:
                return 0
            log = self._logs[w.kind]
            return max(0, log.flushed - max(w.cursor, log.start))

    def log_window(self, kind: str) -> tuple[int, int]:
        """(first rv retained, last rv) of a kind's log ring."""
        log = self._kind_log(kind)
        if not log.rvs:
            return (0, 0)
        return (log.rvs[0], log.rvs[-1])

    def fanout_stats(self) -> dict:
        """Watch-plane snapshot (identical shape on the native core):
        cumulative materialization/shared-hit/encode/bytes counters plus
        one row per live subscription class. Observability only."""
        with self._cond:
            classes = sorted(self._classes.values(),
                             key=lambda c: (c.kind, c.selector))
            rows = [{"kind": c.kind, "selector": c.selector,
                     "members": c.members,
                     "cached_events":
                         sum(1 for e in c.evs if e is not None),
                     "cached_lines":
                         sum(1 for b in c.lines if b is not None),
                     "window": [c.cache_start,
                                c.cache_start + len(c.evs)]}
                    for c in classes]
            return {"shared_classes": self._shared_classes,
                    "materializations": self._stat_mat,
                    "shared_hits": self._stat_shared,
                    "line_encodes": self._stat_enc,
                    "bytes_served": self._stat_bytes,
                    "classes": rows}


def make_commit_core(log_size: int, ring_size: int, event_cls,
                     expired_exc, already_exists_exc, force: Optional[str] = None):
    """Native CommitCore when it builds, PyCommitCore otherwise. `force`
    (or KTPU_COMMITCORE=twin|native) pins the implementation — the parity
    tests and the bench's in-run twin referee use it."""
    choice = force or os.environ.get("KTPU_COMMITCORE", "auto")
    if choice != "twin":
        from kubernetes_tpu import native
        mod = native.load("commitcore")
        if mod is not None:
            return mod.CommitCore(log_size, ring_size, event_cls,
                                  expired_exc, already_exists_exc)
        if choice == "native":
            raise RuntimeError("KTPU_COMMITCORE=native but the commitcore "
                               "extension failed to build/load")
    return PyCommitCore(log_size, ring_size, event_cls,
                        expired_exc, already_exists_exc)
