"""Reflector + shared informer over the versioned store.

Mirrors the reference's client-side cache pipeline (SURVEY §3.4):
Reflector.ListAndWatch (client-go/tools/cache/reflector.go:159) →
DeltaFIFO → sharedIndexInformer.HandleDeltas (shared_informer.go:180) →
registered handlers. Here the transport is the in-process Store watch; the
delta queue is the Watch's event queue; handlers see the same
add/update/delete callbacks with old+new objects.

Two pump modes:
- `start()` — background thread, like the reference's informer goroutines.
- `pump(max_events)` — synchronous drain for deterministic tests and for
  the benchmark loop (keeps the hot path single-threaded).
"""
from __future__ import annotations

import random
import threading
from typing import Any, Callable, Optional

from kubernetes_tpu import obs
from kubernetes_tpu.store.store import (
    Store, Watch, Event, ADDED, MODIFIED, DELETED, ExpiredError,
)

# reflector metrics (client-go reflector_metrics.go analog)
RELISTS = obs.counter(
    "informer_relists_total",
    "List+watch re-establishments (initial sync and 410-Gone resumes), "
    "by kind.", ("kind",))
WATCH_EXPIRATIONS = obs.counter(
    "informer_watch_expirations_total",
    "Watches that outran the server's event log (410 Gone), by kind.",
    ("kind",))
RELIST_BACKOFF = obs.histogram(
    "informer_relist_backoff_seconds",
    "Backoff slept before a re-list during a consecutive-ExpiredError "
    "streak, by kind. The first expiry of a streak re-lists immediately "
    "(zero observation); a sustained expired window climbs the jittered "
    "exponential ladder instead of hot-looping list+watch.", ("kind",),
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5))

Handler = Callable[[Any], None]
UpdateHandler = Callable[[Any, Any], None]
BatchHandler = Callable[[list], None]


class ResourceEventHandler:
    """One registered handler set, optionally filtered
    (reference: cache.FilteringResourceEventHandler).

    `on_add_many` is the batched-ingest extension (round 17): when set, a
    pump that delivered a RUN of consecutive adds hands the whole run to
    this callback in one call (per-object filter still applied) instead of
    one `on_add` per object — per-handler delivery ORDER is unchanged, so
    a handler never observes anything a per-event loop wouldn't.

    `on_update_many` / `on_delete_many` extend the same contract to the
    mutation plane (round 23): runs of consecutive MODIFIED land as one
    [(old, new), ...] call, runs of consecutive DELETED as one [obj, ...]
    call. A MODIFIED run batches ONLY when every pair is a plain update
    under the filter (both sides pass) — mixed filter categories
    (update-as-add / update-as-delete) fall back to the per-event loop so
    their interleaved order is bit-identical to the unbatched path."""

    def __init__(self,
                 on_add: Optional[Handler] = None,
                 on_update: Optional[UpdateHandler] = None,
                 on_delete: Optional[Handler] = None,
                 filter_fn: Optional[Callable[[Any], bool]] = None,
                 on_add_many: Optional[BatchHandler] = None,
                 on_update_many: Optional[BatchHandler] = None,
                 on_delete_many: Optional[BatchHandler] = None):
        self.on_add = on_add
        self.on_add_many = on_add_many
        self.on_update = on_update
        self.on_update_many = on_update_many
        self.on_delete = on_delete
        self.on_delete_many = on_delete_many
        self.filter_fn = filter_fn

    def _passes(self, obj: Any) -> bool:
        return self.filter_fn is None or self.filter_fn(obj)

    def handle_added_run(self, objs: list) -> None:
        """A run of consecutive ADDED objects, in delivery order: one
        `on_add_many` call for the filtered batch when registered, else
        the per-object `on_add` loop."""
        if self.on_add is None and self.on_add_many is None:
            return
        passing = objs if self.filter_fn is None \
            else [o for o in objs if self.filter_fn(o)]
        if not passing:
            return
        if self.on_add_many is not None and len(passing) > 1:
            self.on_add_many(passing)
        elif self.on_add is not None:
            for o in passing:
                self.on_add(o)
        else:
            self.on_add_many(passing)

    def handle_updated_run(self, pairs: list) -> None:
        """A run of consecutive MODIFIED (old, new) pairs, in delivery
        order: one `on_update_many` call when registered and EVERY pair
        is a plain update under the filter — anything else (an
        update-as-add or update-as-delete in the run) replays the exact
        per-event loop, preserving the interleaved category order."""
        if self.on_update_many is not None and len(pairs) > 1 and all(
                old is not None and self._passes(old) and self._passes(new)
                for old, new in pairs):
            self.on_update_many(pairs)
            return
        for old, new in pairs:
            self.handle(MODIFIED, old, new)

    def handle_deleted_run(self, objs: list) -> None:
        """A run of consecutive DELETED objects, in delivery order: one
        `on_delete_many` call for the filtered batch when registered,
        else the per-object `on_delete` loop."""
        if self.on_delete is None and self.on_delete_many is None:
            return
        passing = objs if self.filter_fn is None \
            else [o for o in objs if self.filter_fn(o)]
        if not passing:
            return
        if self.on_delete_many is not None and len(passing) > 1:
            self.on_delete_many(passing)
        elif self.on_delete is not None:
            for o in passing:
                self.on_delete(o)
        else:
            self.on_delete_many(passing)

    def handle(self, ev_type: str, old: Any, new: Any) -> None:
        if ev_type == ADDED:
            if self._passes(new) and self.on_add:
                self.on_add(new)
        elif ev_type == MODIFIED:
            old_ok = old is not None and self._passes(old)
            new_ok = self._passes(new)
            # reference filtering semantics: update→update / add / delete
            if old_ok and new_ok:
                if self.on_update:
                    self.on_update(old, new)
            elif new_ok:
                if self.on_add:
                    self.on_add(new)
            elif old_ok:
                if self.on_delete:
                    self.on_delete(old)
        elif ev_type == DELETED:
            if self._passes(new) and self.on_delete:
                self.on_delete(new)


class SharedInformer:
    """List+watch one kind; maintain a local cache; fan events out."""

    def __init__(self, store: Store, kind: str):
        self.store = store
        self.kind = kind
        self._handlers: list[ResourceEventHandler] = []
        self._cache: dict[str, Any] = {}
        self._watch: Optional[Watch] = None
        self._synced = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.RLock()
        # terminal background-mode failure (revoked/denied credentials):
        # recorded by _safe_relist before it stops the informer, so the
        # operator sees WHY the informer died instead of a silent stall
        self.last_error: Optional[Exception] = None
        # consecutive-ExpiredError streak driving the re-list backoff:
        # the first expiry re-lists immediately, a sustained expired
        # window (log smaller than churn) backs off exponentially with
        # jitter instead of spinning list+watch back-to-back. `_sleep` is
        # injectable so tests count/observe delays deterministically.
        self._expired_streak = 0
        self._backoff_rng = random.Random(f"relist:{kind}")
        self._sleep: Callable[[float], Any] = self._stop.wait
        self.relist_backoff_base = 0.05
        self.relist_backoff_cap = 1.0

    # -- registration -------------------------------------------------------
    def add_event_handler(self,
                          on_add: Optional[Handler] = None,
                          on_update: Optional[UpdateHandler] = None,
                          on_delete: Optional[Handler] = None,
                          filter_fn: Optional[Callable[[Any], bool]] = None,
                          on_add_many: Optional[BatchHandler] = None,
                          on_update_many: Optional[BatchHandler] = None,
                          on_delete_many: Optional[BatchHandler] = None,
                          ) -> None:
        self._handlers.append(ResourceEventHandler(
            on_add, on_update, on_delete, filter_fn,
            on_add_many=on_add_many, on_update_many=on_update_many,
            on_delete_many=on_delete_many))

    # -- lister (reference: informer.Lister()) ------------------------------
    def list(self) -> list[Any]:
        with self._lock:
            return list(self._cache.values())

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._cache.get(key)

    @property
    def has_synced(self) -> bool:
        return self._synced

    def backlog(self) -> int:
        """Events published for this informer's watch but not yet pumped
        (embedded store: the commit core's cursor backlog; remote: the
        client reader's queue). The serving backpressure gate adds this
        to the activeQ depth so a burst of creates BETWEEN informer pumps
        cannot blow past the watermark unobserved — it counts every
        undelivered event for the kind (binds included), which only
        overcounts, so the gate errs toward shedding under churn."""
        w = self._watch
        if w is None:
            return 0
        core = getattr(self.store, "_core", None)
        wid = getattr(w, "_wid", None)
        if core is not None and wid is not None:
            try:
                return int(core.backlog(wid))
            except Exception:
                return 0
        q = getattr(w, "_queue", None)   # RemoteWatch's reader queue
        return q.qsize() if q is not None else 0

    # -- relist backoff guard ------------------------------------------------
    def _note_expired(self) -> None:
        """One step of the consecutive-ExpiredError streak: sleep the
        streak's jittered exponential delay (0 on the first expiry) and
        record it. Stopping the informer interrupts the sleep."""
        streak = self._expired_streak
        self._expired_streak = streak + 1
        if streak == 0:
            return
        delay = min(self.relist_backoff_cap,
                    self.relist_backoff_base * (2 ** (streak - 1)))
        delay *= 0.5 + self._backoff_rng.random() / 2
        RELIST_BACKOFF.labels(self.kind).observe(delay)
        self._sleep(delay)

    # -- list+watch ---------------------------------------------------------
    def sync(self) -> None:
        """Initial list + open watch at the list's resourceVersion."""
        self._relist()
        self._synced = True

    def _relist(self) -> None:
        """List + re-open the watch, then reconcile the local cache with
        DeltaFIFO Replace semantics (delta_fifo.go:96): vanished keys emit
        deletes, changed keys updates, new keys adds — so a 410-Gone resume
        (reflector.go:159) never replays spurious adds or loses deletes
        that happened inside the expired window."""
        RELISTS.labels(self.kind).inc()
        if self._watch is not None:
            self._watch.stop()
        while True:
            objs, rv = self.store.list(self.kind)
            try:
                self._watch = self.store.watch(self.kind, since_rv=rv)
            except ExpiredError:
                # the log window moved past rv between list and watch
                # open: a sustained window would otherwise re-list
                # back-to-back — climb the backoff ladder instead
                self._note_expired()
                continue
            break
        new = {o.key: o for o in objs}
        with self._lock:
            old_cache = self._cache
            self._cache = new
        for key, obj in new.items():
            old = old_cache.get(key)
            if old is None:
                self._dispatch(ADDED, None, obj)
            elif old.resource_version != obj.resource_version:
                self._dispatch(MODIFIED, old, obj)
        for key, obj in old_cache.items():
            if key not in new:
                self._dispatch(DELETED, None, obj)

    #: events copied out per watch poll during pump() — ONE core poll call
    #: (GIL-released on the native core) serves a whole batch instead of
    #: one call per event (the round-17 batched-ingest prologue)
    pump_batch = 256

    def pump(self, max_events: Optional[int] = None,
             timeout: float = 0.0) -> int:
        """Synchronously apply pending watch events, copied out in
        batches (one core poll per `pump_batch` events; consecutive adds
        dispatch as one batch to handlers that registered on_add_many).
        Returns count applied."""
        if self._watch is None:
            self.sync()
        n = 0
        while max_events is None or n < max_events:
            limit = self.pump_batch if max_events is None \
                else min(self.pump_batch, max_events - n)
            try:
                evs = self._poll_batch(timeout, limit)
            except ExpiredError:
                # the watch outran the server's event log: re-list
                # (reflector 410 contract); consecutive expirations with
                # no event applied in between back off
                WATCH_EXPIRATIONS.labels(self.kind).inc()
                self._note_expired()
                self._relist()
                continue
            if not evs:
                break
            self._apply_batch(evs)
            n += len(evs)
        return n

    def _poll_batch(self, timeout: float, limit: int) -> list:
        """Copy out up to `limit` pending events: one cursor poll on the
        embedded store's Watch (the core call is GIL-released on the
        native commit core); transports without the batch poll
        (RemoteWatch's reader queue) drain per event."""
        w = self._watch
        poll = getattr(w, "_poll", None)
        if poll is not None:
            return poll(timeout if timeout else 0, limit)
        evs = []
        ev = w.next(timeout=timeout) if timeout else w.try_next()
        while ev is not None:
            evs.append(ev)
            if len(evs) >= limit:
                break
            ev = w.try_next()
        return evs

    def _apply(self, ev: Event) -> None:
        self._apply_batch([ev])

    def _apply_batch(self, evs: list) -> None:
        # a delivered event ends any consecutive-ExpiredError streak
        self._expired_streak = 0
        prepared = []   # (effective etype, old, new) in delivery order
        with self._lock:
            cache = self._cache
            for ev in evs:
                old = None
                if ev.type in (ADDED, MODIFIED):
                    old = cache.get(ev.obj.key)
                    cache[ev.obj.key] = ev.obj
                elif ev.type == DELETED:
                    old = cache.pop(ev.obj.key, None)
                # an ADDED for a key we already had behaves as update
                # (re-list replay)
                etype = ev.type
                if etype == ADDED and old is not None:
                    etype = MODIFIED
                prepared.append((etype, old, ev.obj))
        i = 0
        n = len(prepared)
        while i < n:
            # run of consecutive same-type events: one batched dispatch
            # per handler (per-handler order identical to the per-event
            # loop; singletons take the plain _dispatch path)
            etype, old, new = prepared[i]
            j = i + 1
            while j < n and prepared[j][0] == etype:
                j += 1
            if j - i == 1:
                self._dispatch(etype, old, new)
            elif etype == ADDED:
                run = [prepared[k][2] for k in range(i, j)]
                for h in self._handlers:
                    h.handle_added_run(run)
            elif etype == MODIFIED:
                pairs = [(prepared[k][1], prepared[k][2])
                         for k in range(i, j)]
                for h in self._handlers:
                    h.handle_updated_run(pairs)
            else:   # DELETED
                run = [prepared[k][2] for k in range(i, j)]
                for h in self._handlers:
                    h.handle_deleted_run(run)
            i = j

    def _dispatch(self, ev_type: str, old: Any, new: Any) -> None:
        for h in self._handlers:
            h.handle(ev_type, old, new)

    # -- background mode ----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        if self._watch is None:
            self.sync()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"informer-{self.kind}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                ev = self._watch.next(timeout=0.05)
            except ExpiredError:
                WATCH_EXPIRATIONS.labels(self.kind).inc()
                self._note_expired()
                self._safe_relist()
                continue
            if ev is not None:
                self._apply(ev)

    def _safe_relist(self) -> None:
        """Background-mode re-list: transient transport failures (a remote
        apiserver mid-restart) must not kill the informer thread — retry
        until the list+watch lands or the informer stops. The synchronous
        pump() path propagates transport errors to its caller instead.

        Authentication/authorization failures are NOT transient: a revoked
        or denied token will 401/403 forever, so retrying silently turns a
        credential problem into an invisible stall. Record the error and
        stop the informer instead (the reference reflector likewise
        surfaces Unauthorized instead of hot-looping on it)."""
        while not self._stop.is_set():
            try:
                self._relist()
                return
            except ExpiredError:
                self._note_expired()
                continue
            except Exception as e:
                code = getattr(e, "code", None)
                if code in (401, 403):
                    self.last_error = e
                    self._stop.set()
                    return
                if self._stop.wait(0.2):
                    return

    def stop(self) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None


class InformerFactory:
    """SharedInformerFactory analog: one informer per kind, shared."""

    def __init__(self, store: Store):
        self.store = store
        self._informers: dict[str, SharedInformer] = {}

    def informer(self, kind: str) -> SharedInformer:
        inf = self._informers.get(kind)
        if inf is None:
            inf = SharedInformer(self.store, kind)
            self._informers[kind] = inf
        return inf

    def sync_all(self) -> None:
        for inf in self._informers.values():
            if not inf.has_synced:
                inf.sync()

    def pump_all(self) -> int:
        return sum(inf.pump() for inf in self._informers.values())

    def start_all(self) -> None:
        for inf in self._informers.values():
            inf.start()

    def stop_all(self) -> None:
        for inf in self._informers.values():
            inf.stop()
