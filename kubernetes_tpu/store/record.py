"""Event recorder: the user-visible audit trail.

Analog of client-go/tools/record.EventRecorder + its correlator: events are
aggregated by (involved object, type, reason, message) with a count, and
written through the store so any watcher (tests, CLI, controllers) sees them
— the reference's recorder posts to the events API the same way
(reference: pkg/scheduler/scheduler.go:268,325,433 call sites).
"""
from __future__ import annotations

import threading
from collections import OrderedDict

from kubernetes_tpu.api.types import EventRecord
from kubernetes_tpu.store.store import (
    Store, EVENTS, AlreadyExistsError, ConflictError, NotFoundError,
)
from kubernetes_tpu.store.remote import APIStatusError

NORMAL = "Normal"
WARNING = "Warning"

_seq_val = 0
_seq_lock = threading.Lock()


def reserve_seq(n: int) -> int:
    """Atomically reserve a contiguous block of `n` record-name sequence
    numbers; returns the first. The commit core's batched Scheduled-event
    build (native and twin) names its records seq0+i off one reservation
    per wave, so wave records stay unique against every other emitter of
    the process-global sequence (gaps from unlanded bindings are fine —
    the sequence only guarantees uniqueness, like the per-record
    next_seq() it generalizes)."""
    global _seq_val
    with _seq_lock:
        first = _seq_val + 1
        _seq_val += n
        return first


def next_seq() -> int:
    return reserve_seq(1)


def build_scheduled_records(record_cls, bindings: list, component: str,
                            seq0: int) -> list:
    """Pure-Python twin of the native core's batched Scheduled-record
    build (commitcore.cpp commit_wave_binds): one EventRecord per binding
    (key, node), named `{name}.{seq0+i:x}`, message exactly the burst
    commit's wording. Used by PyCommitCore.commit_wave_binds and as the
    stale-native-.so fallback; field-for-field parity with the native
    build is pinned by tests/test_commit_core.py."""
    recs = []
    new = record_cls.__new__
    for i, (key, node) in enumerate(bindings):
        namespace, _, name = key.partition("/")
        rec = new(record_cls)
        rec.__dict__.update(
            name=f"{name or key}.{seq0 + i:x}",
            namespace=namespace if name else "default",
            involved_kind="Pod", involved_key=key,
            type=NORMAL, reason="Scheduled",
            message=f"Successfully assigned {key} to {node}",
            count=1, component=component, resource_version=0)
        recs.append(rec)
    return recs

# correlation cache bound (the reference correlator is an LRU with TTL,
# client-go/tools/record/events_cache.go); keys include per-pod messages, so
# an unbounded map grows one entry per pod ever scheduled
MAX_CORRELATION_ENTRIES = 4096


class EventRecorder:
    def __init__(self, store: Store, component: str = "default-scheduler",
                 max_entries: int = MAX_CORRELATION_ENTRIES):
        self.store = store
        self.component = component
        self._lock = threading.Lock()
        # correlation cache: aggregation key -> stored event key (LRU)
        self._known: OrderedDict[tuple, str] = OrderedDict()
        self._max_entries = max_entries

    def event(self, involved_kind: str, involved_key: str, etype: str,
              reason: str, message: str) -> None:
        agg = (self.component, involved_kind, involved_key, etype, reason,
               message)
        with self._lock:
            existing = self._known.get(agg)
            if existing is not None:
                self._known.move_to_end(agg)
                def bump(ev):
                    ev.count += 1
                    return ev
                try:
                    self.store.guaranteed_update(EVENTS, existing, bump)
                    return
                except NotFoundError:
                    pass   # expired/cleaned: fall through to re-create
            namespace, _, name = involved_key.partition("/")
            rec = EventRecord(
                name=f"{name or involved_key}.{next_seq():x}",
                namespace=namespace if name else "default",
                involved_kind=involved_kind, involved_key=involved_key,
                type=etype, reason=reason, message=message,
                component=self.component)
            try:
                self.store.create(EVENTS, rec, move=True)
            except (APIStatusError, AlreadyExistsError, ConflictError,
                    OSError):
                # fire-and-forget like the reference recorder: a rejected
                # or undeliverable event write (rate-limit 422, transport
                # failure, name collision) must never fail the component's
                # work loop — events are audit records, not state.
                # Programming errors (TypeError from schema drift) still
                # propagate.
                return
            self._known[agg] = rec.key
            while len(self._known) > self._max_entries:
                self._known.popitem(last=False)

    def make_pod_records(self, items) -> list:
        """Construct (without writing) one EventRecord per
        (pod, etype, reason, message) item. Burst messages are unique per
        pod (they carry the pod's key), so the correlation cache can never
        aggregate them and is skipped. The burst commit passes these
        straight into `store.commit_wave` so a wave's binds AND audit
        records land in ONE core call."""
        recs = []
        new = EventRecord.__new__
        for pod, etype, reason, message in items:
            key = pod.key
            namespace, _, name = key.partition("/")
            # dataclass __init__ costs ~3x a direct dict fill and this loop
            # runs 10k+ times inside the timed burst window
            rec = new(EventRecord)
            rec.__dict__.update(
                name=f"{name or key}.{next_seq():x}",
                namespace=namespace if name else "default",
                involved_kind="Pod", involved_key=key,
                type=etype, reason=reason, message=message,
                count=1, component=self.component, resource_version=0)
            recs.append(rec)
        return recs

    def pod_events_batch(self, items) -> None:
        """Burst-commit form: every record lands in ONE store write
        (create_many), one lock instead of one per pod."""
        recs = self.make_pod_records(items)
        if not recs:
            return
        drop = (APIStatusError, AlreadyExistsError, ConflictError, OSError)
        create_many = getattr(self.store, "create_many", None)
        if create_many is not None:
            try:
                create_many(EVENTS, recs, move=True)
            except drop:
                pass   # fire-and-forget, as above
            return
        for rec in recs:   # remote transport: per-record creates,
            try:           # each isolated like the serial pod_event path
                self.store.create(EVENTS, rec, move=True)
            except drop:
                continue

    # convenience mirrors of the reference call sites
    def pod_event(self, pod, etype: str, reason: str, message: str) -> None:
        self.event("Pod", pod.key, etype, reason, message)
