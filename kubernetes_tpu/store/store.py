"""In-memory versioned object store with list/watch — the etcd+apiserver analog.

Provides the same distributed-communication contract the reference's control
plane is built on (SURVEY §2.4): a single authoritative store assigning a
monotonically increasing resourceVersion to every write, optimistic
concurrency via resourceVersion preconditions (reference:
staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go GuaranteedUpdate),
and resumable watch streams with a bounded event log (reference:
storage/cacher/cacher.go:217 watch cache; etcd3/watcher.go:99).

Objects are the pruned dataclasses from `kubernetes_tpu.api.types`. The
store snapshots objects ON WRITE (so a caller mutating its argument after
create/update cannot corrupt stored state) and ON READ via get/list — the
stand-in for the reference's serialize/deserialize boundary. Watch events
and create/update RETURN VALUES alias that write snapshot: they are
read-only by convention — consumers that mutate (cache, queue, scheduler)
clone() first, exactly as API clients deserialize their own copy.
"""
from __future__ import annotations

import copy
import os
import threading
import time as _time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

import numpy as _np

from kubernetes_tpu import chaos, obs

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

# Well-known kinds (the reference's resource names)
PODS = "pods"
NODES = "nodes"
SERVICES = "services"
REPLICASETS = "replicasets"
PDBS = "poddisruptionbudgets"
PVS = "persistentvolumes"
PVCS = "persistentvolumeclaims"
LEASES = "leases"  # leader-election locks (resourcelock analog)
EVENTS = "events"  # user-visible audit records (record.EventRecorder analog)
PRIORITYCLASSES = "priorityclasses"  # scheduling.k8s.io (admission-resolved)
ENDPOINTS = "endpoints"  # service backends (controllers.endpoints)
RESOURCEQUOTAS = "resourcequotas"  # per-namespace caps (admission-enforced)
DEPLOYMENTS = "deployments"  # apps workload tier (controllers.deployment)
JOBS = "jobs"  # batch run-to-completion (controllers.job)
DAEMONSETS = "daemonsets"  # one-pod-per-node (controllers.daemonset)
STATEFULSETS = "statefulsets"  # ordinal identities (controllers.statefulset)
NAMESPACES = "namespaces"  # lifecycle owned by controllers.namespace
HPAS = "horizontalpodautoscalers"  # autoscaling (controllers.hpa)
CLUSTERROLES = "clusterroles"  # rbac.authorization.k8s.io policy objects
CLUSTERROLEBINDINGS = "clusterrolebindings"
PODMETRICS = "podmetrics"  # metrics.k8s.io stand-in (HPA's usage source)
CRONJOBS = "cronjobs"  # batch schedules (controllers.cronjob)
CONFIGMAPS = "configmaps"
SECRETS = "secrets"
SERVICEACCOUNTS = "serviceaccounts"
PODGROUPS = "podgroups"  # co-scheduling gangs (coscheduling.types.PodGroup)

DEFAULT_WATCH_LOG = 8192  # events retained per kind for resumable watches

# watch fan-out robustness counters (reference: the watch cache terminates
# streams that outrun it; apiserver_terminated_watchers_total analog)
WATCH_DROPPED = obs.counter(
    "watch_dropped_total",
    "Watch events dropped instead of buffered unboundedly, by reason: "
    "slow-consumer (per-watcher backlog exceeded the ring bound at "
    "fan-out) or log-window (the shared event log evicted entries the "
    "watcher never copied out). The watcher's next poll raises "
    "ExpiredError and the consumer re-lists (410 Gone).", ("reason",))
COMMIT_WAVES = obs.counter(
    "store_commit_waves_total",
    "Batched bind+event commit waves written through the commit core, by "
    "implementation (native C++ extension vs pure-Python twin).",
    ("impl",))
# µs-scale families (obs.MICRO_BUCKETS): the native commit core lands a
# wave in tens of µs and fan-out lag is sub-ms on an idle box — the
# default ms ladder would crush both into one bucket (the round-12
# per-family bucket-override satellite)
COMMIT_WAVE_SECONDS = obs.histogram(
    "store_commit_wave_seconds",
    "Wall seconds of one commit_wave core call (batched bind + audit "
    "record creates), by implementation.",
    ("impl",), buckets=obs.MICRO_BUCKETS)
WATCH_FANOUT_LAG = obs.histogram(
    "watch_fanout_lag_seconds",
    "Seconds from an event's commit (core log append) to its copy-out by "
    "a watcher — stamped inside BOTH commit cores (native commitcore.cpp "
    "and the PyCommitCore twin) via the fan-out sink.",
    ("impl",), buckets=obs.MICRO_BUCKETS)
WAVE_DEDUP = obs.counter(
    "store_commit_wave_dedup_total",
    "commit_wave calls answered from the wave-token dedupe map: a retried "
    "wave whose first attempt had landed before the ambiguous failure — "
    "the retry returned the recorded result instead of double-landing "
    "binds or double-emitting events.")
# watch-plane subscription classes (round 20): watchers sharing one
# (kind, selector) interest dedupe into a class; each event is
# materialized (and wire-encoded) ONCE per class, classmates after the
# first serve the shared object/bytes from the class cache.
WATCH_CLASSES_GAUGE = obs.gauge(
    "watch_subscription_classes",
    "Live shared subscription classes (distinct (kind, selector) watcher "
    "interests) in the commit core's fan-out plane, by kind.", ("kind",))
WATCH_COPYOUT_SHARED = obs.counter(
    "watch_copyout_shared_total",
    "Watch copy-out slots served from a subscription class's shared cache "
    "(an Event or wire line a classmate already materialized) — the "
    "fan-out work the class plane deduplicated away.")
WATCH_COPYOUT_MAT = obs.counter(
    "watch_copyout_materializations_total",
    "Watch copy-out Event materializations actually performed (once per "
    "event per class in shared mode; once per event per watcher in the "
    "degenerate per-watcher mode).")

#: watcher_lags() debug copy-out sample cap: the /debug/sched fan-out
#: health view walks at most this many live watchers (at 100k watchers a
#: full walk is itself a fan-out storm)
WATCHER_LAG_SAMPLE = 1000

EVICTIONS = obs.counter(
    "evictions_total",
    "Pods evicted through the PDB-guarded eviction verb, by reason "
    "(taint-manager = NoExecute taint eviction via the zone-paced "
    "queue, drain = kubectl drain, api = the HTTP subresource). A "
    "refused eviction (budget exhausted -> 429) does NOT count.",
    ("reason",))

#: retained dedupe tokens (one per wave; the retry window is one wave, so
#: a small multiple of any realistic pipeline depth is plenty)
WAVE_TOKEN_CAP = 1024

#: retained audit EventRecords (the reference apiserver expires events
#: after a TTL — default 1h — for exactly this reason: a serving process
#: emits one Scheduled record per pod forever, and an unbounded events
#: bucket is a heap leak whose growing gen2 GC passes land as multi-ms
#: pauses inside scheduling windows). Oldest-first eviction past the cap,
#: with a DELETED watch event so consumers stay consistent.
DEFAULT_EVENTS_CAP = 1 << 16

EVENTS_TRIMMED = obs.counter(
    "store_events_trimmed_total",
    "Audit EventRecords evicted oldest-first past the store's retention "
    "cap (the reference's event TTL analog; each eviction emits DELETED).")

FENCED_WRITES = obs.counter(
    "store_fenced_writes_total",
    "Writes rejected whole because they carried an expired or superseded "
    "partition-lease fencing token, by verb (commit_wave / bind / "
    "advance). A fenced write lands NOTHING: no binds, no events, no rv.",
    ("verb",))
BIND_CAS_CONFLICTS = obs.counter(
    "store_bind_conflicts_total",
    "Bind writes refused by the rv-CAS already-bound check (the pod was "
    "bound by another writer between decision and commit). The pod's "
    "existing binding is never overwritten — this counter plus the "
    "fleet's zero-double-bind tripwire are the two sides of the same "
    "invariant.")
# churn-plane batching proof (round 23): objects per call >> 1 means a
# churn tick's mutations take O(batches) store-lock acquisitions, not
# O(pods) — the soak asserts it on these two families.
BATCH_MUTATIONS = obs.counter(
    "store_batch_mutations_total",
    "Objects landed through the batched mutation verbs (update_many / "
    "evict_many / delete_many), by verb.", ("verb",))
BATCH_MUTATION_CALLS = obs.counter(
    "store_batch_mutation_calls_total",
    "Batched mutation verb invocations — one store-lock acquisition and "
    "one commit-core call each — by verb.", ("verb",))


class ConflictError(Exception):
    """resourceVersion precondition failed (optimistic-concurrency loss)."""


class FencedError(ConflictError):
    """A write carried an expired or superseded partition-lease fencing
    token (round 18, active-active fleet): the claim it wrote under has a
    newer holder, so the WHOLE write is rejected atomically — no partial
    wave lands, no events emit, no rv burns. Subclasses ConflictError so
    every existing never-auto-retry path treats it as a definitive answer;
    the HTTP surface maps it to 409 reason=Fenced."""

    def __init__(self, message: str, scope: str = ""):
        super().__init__(message)
        self.scope = scope


class DisruptionBudgetError(Exception):
    """Eviction refused: a matching PodDisruptionBudget has no disruptions
    left (the eviction subresource's 429 TooManyRequests — reference
    pkg/registry/core/pod/rest/eviction.go). `retry_after` is the
    suggested backoff seconds the server sends as Retry-After."""

    def __init__(self, message: str, retry_after: float = 10.0):
        super().__init__(message)
        self.retry_after = retry_after


class BackpressureError(Exception):
    """Pod create shed by the serving admission gate (activeQ depth or
    in-flight launch windows over the watermark) — the apiserver's
    429 TooManyRequests on CREATE, with Retry-After carrying the server's
    suggested backoff. Distinct from DisruptionBudgetError (the eviction
    subresource's 429): a shed create definitively did NOT land, so
    clients retry it safely after the suggested backoff; a refused
    eviction must never auto-retry."""

    def __init__(self, message: str, retry_after: float = 0.25,
                 accepted: int = 0):
        super().__init__(message)
        self.retry_after = retry_after
        # batched-create partial acceptance (create_many): the first
        # `accepted` objects of the batch LANDED; only the tail was shed.
        # Always 0 on the single-create path.
        self.accepted = accepted


class NotFoundError(Exception):
    pass


class AlreadyExistsError(Exception):
    pass


class ExpiredError(Exception):
    """Watch asked to resume from a resourceVersion older than the log window
    (the reference returns 410 Gone → client re-lists)."""


@dataclass(frozen=True)
class Event:
    type: str            # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any             # snapshot of the object at this version
    resource_version: int


class Watch:
    """One watch stream: a bounded cursor into the commit core's event log
    plus a stop handle. Copy-out happens on the CONSUMER's thread (the
    core materializes Event objects at poll, off the committing thread),
    and a consumer that falls behind the ring bound is dropped-with-resync:
    next()/try_next()/drain() raise ExpiredError and the caller re-lists,
    exactly like the reference reflector on 410 Gone."""

    def __init__(self, store: "Store", kind: str, wid: int,
                 selector: Optional[str] = None):
        self._store = store
        self.kind = kind
        self.selector = selector
        self._wid = wid
        self._stopped = False

    def _pre_poll(self) -> None:
        if self._store._fanout_deferred:
            # a chaos-deferred wave fan-out: the consumer's poll is the
            # seam's delivery point — events are delayed, never lost
            self._store.deliver_deferred()
        if chaos.take("watch.drop"):
            # injected slow-consumer drop: identical consumer contract to
            # the real overflow path (ExpiredError -> re-list)
            WATCH_DROPPED.labels("injected").inc()
            raise ExpiredError(
                f"{self.kind}: chaos-injected watch drop (resync required)")

    def _poll(self, timeout: Optional[float], limit: int) -> list[Event]:
        self._pre_poll()
        try:
            return self._store._core.poll(self._wid, timeout, limit)
        except ExpiredError as e:
            # fan-out-time drops were already counted (slow-consumer, by
            # event) in flush; an eviction the poll itself detects is the
            # log-window case (contract message shared with the native core)
            if "evicted" in str(e):
                WATCH_DROPPED.labels("log-window").inc()
            raise

    def _poll_bytes(self, timeout: Optional[float],
                    limit: int) -> list[bytes]:
        """Byte-ring poll: pre-encoded wire lines from the subscription
        class's serialize-once cache (same chaos seams and drop contract
        as the Event path)."""
        self._pre_poll()
        try:
            return self._store._core.poll_bytes(self._wid, timeout, limit)
        except ExpiredError as e:
            if "evicted" in str(e):
                WATCH_DROPPED.labels("log-window").inc()
            raise

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None on timeout / stream close. Raises
        ExpiredError when this watcher was dropped (slow consumer)."""
        evs = self._poll(timeout, 1)
        return evs[0] if evs else None

    def try_next(self) -> Optional[Event]:
        """Non-blocking next event, or None when nothing is pending."""
        evs = self._poll(0, 1)
        return evs[0] if evs else None

    def drain(self) -> list[Event]:
        return self._poll(0, 1 << 30)

    def next_bytes(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Next event as a pre-encoded wire line (requires a wire encoder
        on the store; the apiserver installs one). Shares the watcher
        cursor with next()/drain() — a stream consumes ONE representation."""
        lines = self._poll_bytes(timeout, 1)
        return lines[0] if lines else None

    def drain_bytes(self) -> list[bytes]:
        return self._poll_bytes(0, 1 << 30)

    def stop(self) -> None:
        self._stopped = True
        self._store._watch_ids.pop(self._wid, None)
        self._store._core.detach(self._wid)  # wakes any blocked next()


def nominated_node_mutator(node_name: str) -> Callable[[Any], Any]:
    """Mutate closure for SetNominatedNodeName — shared by the embedded
    store and RemoteStore so both transports write identical objects."""
    def mutate(pod):
        pod.nominated_node_name = node_name
        return pod
    return mutate


def pod_condition_mutator(condition) -> Callable[[Any], Any]:
    """Mutate closure for podutil.UpdatePodCondition (factory.go:715):
    replace the same-type condition if changed, append if absent, None for
    a no-op (with allow_skip the write is skipped entirely). Shared by the
    embedded store and RemoteStore."""
    def mutate(pod):
        conds = list(pod.conditions)
        for i, c in enumerate(conds):
            if c.type == condition.type:
                if c == condition:
                    return None   # unchanged -> no write
                conds[i] = condition
                break
        else:
            conds.append(condition)
        pod.conditions = tuple(conds)
        return pod
    return mutate


def _key_of(obj: Any) -> str:
    return obj.key


def _clone(obj: Any) -> Any:
    """Snapshot an object crossing the store boundary. Objects with a fast
    clone() use it; anything else falls back to deepcopy."""
    c = getattr(obj, "clone", None)
    return c() if c is not None else copy.deepcopy(obj)


class Store:
    """Threadsafe versioned KV with per-kind watch fan-out.

    The versioned write log and watch delivery live in the COMMIT CORE
    (native/commitcore.cpp when it builds, store/commit_core.PyCommitCore
    otherwise — bit-identical semantics either way): every write verb is
    one core call assigning resourceVersions and appending watch-log
    entries, and the burst path's `commit_wave`/`fanout_wave` pair lands a
    whole wave's binds + audit events as ONE core call each.

    `watch_queue_size` bounds each watcher's backlog (defaults to the log
    size — the shared ring is the buffer); a consumer that falls further
    behind is dropped-with-resync instead of buffering unboundedly."""

    def __init__(self, watch_log_size: int = DEFAULT_WATCH_LOG,
                 debug_integrity: Optional[bool] = None,
                 watch_queue_size: Optional[int] = None,
                 commit_core: Optional[str] = None,
                 events_cap: Optional[int] = DEFAULT_EVENTS_CAP,
                 shared_watch_classes: Optional[bool] = None):
        from kubernetes_tpu.store.commit_core import make_commit_core
        self._lock = threading.RLock()
        self._objs: dict[str, dict[str, Any]] = {}
        self._queue_size = (watch_queue_size if watch_queue_size is not None
                            else watch_log_size)
        self._core = make_commit_core(
            watch_log_size, self._queue_size,
            Event, ExpiredError, AlreadyExistsError, force=commit_core)
        self.core_impl = "native" if getattr(self._core, "is_native", False) \
            else "twin"
        # shared subscription classes (round 20): watchers with the same
        # (kind, selector) interest share one materialize-once event cache
        # and one serialize-once byte ring. False is the degenerate
        # class-per-watcher mode — the EXACT pre-class fan-out path, kept
        # as the differential referee's old shape. KTPU_WATCH_CLASSES=0
        # forces degenerate mode process-wide.
        if shared_watch_classes is None:
            shared_watch_classes = \
                os.environ.get("KTPU_WATCH_CLASSES", "1") != "0"
        self.shared_watch_classes = bool(shared_watch_classes)
        if not self.shared_watch_classes \
                and hasattr(self._core, "set_shared_classes"):
            self._core.set_shared_classes(False)
        # wire encoder for the byte ring ((etype, obj, rv) -> bytes; the
        # apiserver installs its serde line encoder). Kept on the store so
        # core demotion can re-install it on the twin.
        self._wire_encoder = None
        # last cumulative core fan-out stats synced into the obs counters
        # (the core counts monotonically; obs counters get the deltas)
        self._fanout_obs_synced = {"materializations": 0, "shared_hits": 0}
        self._gauge_kinds: set = set()
        # watcher_lag_summary()'s TTL cache ({"at": t, "summary": {...}});
        # the all-watchers backlog walk is O(watchers) core calls
        self._lag_summary_cache: Optional[dict] = None
        self._log_size = watch_log_size
        # audit-record retention (the event-TTL analog); None/0 = unbounded
        self._events_cap = events_cap
        # wave-token dedupe map (idempotent commit retry): token -> the
        # missing-keys result of the wave that landed under it. A retried
        # commit_wave after an ambiguous failure replays the RESULT, not
        # the write.
        self._wave_tokens: "OrderedDict[str, list]" = OrderedDict()
        # batched-mutation dedupe (round 23): update_many / evict_many
        # replays answer the recorded RESULT, exactly the wave contract
        self._mutation_tokens: "OrderedDict[str, Any]" = OrderedDict()
        # chaos store.fanout seam: a deferred wave delivery is flushed by
        # the next fan-out call or the next consumer poll (never lost)
        self._fanout_deferred = False
        # fencing-token fallback table: used ONLY when the loaded commit
        # core predates the fence verbs (a stale prebuilt .so) — the
        # fresh builds of both cores own the table themselves
        self._py_fences: dict[str, int] = {}
        # serving admission gate (serve.backpressure.BackpressureGate):
        # when attached, pod creates are checked against the activeQ-depth
        # / in-flight-window watermarks and shed with BackpressureError
        # (HTTP: 429 + Retry-After) — and accepted pod creates stamp the
        # lifecycle ledger's admission slot, opening the watch-to-enqueue
        # phase. None (the default) admits everything unstamped.
        self.admission_gate = None
        # live watcher ids (wid -> (kind, selector)) for the /debug/sched
        # cursor-lag view AND demotion adoption (class membership rides
        # the adoption); pruned on Watch.stop()
        self._watch_ids: dict[int, tuple] = {}
        # fan-out sink: the commit core calls this at poll copy-out (both
        # impls) with (kind, events, lags) — feeds the fan-out-lag
        # histogram and the pod ledger's copy-out stamp. hasattr-gated so a
        # stale prebuilt .so without the hook degrades to no lag samples.
        if hasattr(self._core, "set_fanout_sink"):
            self._core.set_fanout_sink(self._make_fanout_sink())
        # alias tripwire: watch events and create/update return values alias
        # the write snapshot, read-only BY CONVENTION. In debug mode every
        # write records a fingerprint of the stored object; the next write
        # to the same key (and check_integrity()) verifies it, so a consumer
        # that mutated an aliased object in place fails LOUDLY instead of
        # silently corrupting every other consumer. Enabled explicitly or
        # via KTPU_STORE_INTEGRITY=1 (the test suite turns it on).
        if debug_integrity is None:
            debug_integrity = bool(os.environ.get("KTPU_STORE_INTEGRITY"))
        self._integrity: Optional[dict] = {} if debug_integrity else None

    # -- native-core demotion (graceful degradation) -------------------------
    def _core_guard(self) -> None:
        """Called (under the store lock) before every write verb's core
        call: when the chaos plane fires the native.commitcore seam against
        a native core, demote to the twin BEFORE the call — the verb then
        lands on the twin, so no wave/write is ever dropped."""
        if self.core_impl == "native" \
                and chaos.take("native.commitcore"):
            self._demote_core()

    def _demote_core(self) -> None:
        """Swap the commit core for the pure-Python twin mid-run.

        The rv counter carries over (resourceVersion assignment continues
        without a gap) and the OBJECT buckets are untouched — they live in
        the store, not the core — so reads and subsequent writes are
        seamless. The event log and watcher cursors are core-internal
        state the faulted native core cannot be trusted to yield, so live
        watchers are dropped-with-resync: each keeps its wid in the twin
        but the next poll raises ExpiredError and the consumer re-lists,
        exactly the slow-consumer contract informers already implement.
        Caller holds the store lock."""
        from kubernetes_tpu.store.commit_core import PyCommitCore
        twin = PyCommitCore(self._log_size, self._queue_size,
                            Event, ExpiredError, AlreadyExistsError)
        twin.set_rv(self._core.rv())
        # the fence table must survive demotion with no gap: a superseded
        # writer rejected by the native core must stay rejected by the twin
        old_table = getattr(self._core, "fence_table", None)
        if old_table is not None:
            try:
                twin.adopt_fences(old_table())
            except Exception:
                twin.adopt_fences(dict(self._py_fences))
        else:
            twin.adopt_fences(dict(self._py_fences))
        # fan-out plane posture FIRST (mode gates how adoptions join
        # classes), then the adoptions themselves
        if not self.shared_watch_classes:
            twin.set_shared_classes(False)
        if self._wire_encoder is not None:
            twin.set_wire_encoder(self._wire_encoder)
        for wid, (kind, selector) in self._watch_ids.items():
            # class membership RIDES the adoption: the adopted watcher
            # rejoins its (kind, selector) subscription class in the twin
            # (resync still fires — the faulted core's cursors are gone)
            twin.adopt_watcher(wid, kind, resync=True, selector=selector)
        self._core = twin
        self.core_impl = "twin"
        if hasattr(twin, "set_fanout_sink"):
            twin.set_fanout_sink(self._make_fanout_sink())
        chaos.DEMOTIONS.labels("commitcore").inc()
        if self._watch_ids:
            WATCH_DROPPED.labels("core-demotion").inc(len(self._watch_ids))

    # -- observability -------------------------------------------------------
    def _make_fanout_sink(self):
        """Build the copy-out sink. Deliberately closes over nothing of
        `self` (the core holds the sink; a closure over the store would
        make a reference cycle through the core)."""
        from kubernetes_tpu.obs.ledger import LEDGER
        lag_child = WATCH_FANOUT_LAG.labels(self.core_impl)

        def sink(kind, events, lags):
            # one vectorized fold per poll batch — a per-event observe()
            # loop here would put O(events) Python back on the consumer
            # threads the GIL-released poll just freed
            lag_child.observe_batch(lags)
            if kind == PODS and LEDGER.has_awaiting():
                now = _time.perf_counter()
                for ev in events:
                    if ev.type == MODIFIED and ev.obj.node_name:
                        LEDGER.copyout(ev.obj.key, now)
        return sink

    def watcher_lags(self, sample: int = WATCHER_LAG_SAMPLE) -> list[dict]:
        """Per-watcher published-but-unconsumed cursor backlog (the
        /debug/sched fan-out health view). SAMPLED: at 100k watchers a
        full walk is itself a fan-out storm, so the debug copy-out stops
        at `sample` watchers (class-level health lives in
        watch_plane_state(), which is O(classes))."""
        out = []
        with self._lock:
            ids = list(self._watch_ids.items())
        for wid, (kind, _sel) in ids[:sample]:
            try:
                out.append({"wid": wid, "kind": kind,
                            "backlog": int(self._core.backlog(wid))})
            except Exception:
                continue
        return out

    def watcher_lag_summary(self, ttl: float = 2.0) -> dict:
        """Backlog summary over ALL watchers in one pass — count, max,
        p99, total — the true-tail complement to the sampled
        watcher_lags() list (which stops at 1k entries and, at 100k
        watchers, would report the FIRST thousand's health as the
        plane's). One `backlog(wid)` call per watcher; results are
        cached for `ttl` seconds because the soak scraper reads this at
        2 Hz via a callback gauge and 100k core calls per sample would
        be a self-inflicted fan-out storm (ttl=0 forces a fresh walk)."""
        now = _time.perf_counter()
        with self._lock:
            cached = self._lag_summary_cache
            if cached is not None and ttl > 0 \
                    and now - cached["at"] < ttl:
                return dict(cached["summary"])
            ids = list(self._watch_ids)
        backlogs = []
        for wid in ids:
            try:
                backlogs.append(int(self._core.backlog(wid)))
            except Exception:
                continue
        if backlogs:
            arr = _np.asarray(backlogs, dtype=_np.int64)
            summary = {"count": int(arr.size),
                       "max": int(arr.max()),
                       "p99": int(_np.percentile(arr, 99)),
                       "total": int(arr.sum())}
        else:
            summary = {"count": 0, "max": 0, "p99": 0, "total": 0}
        with self._lock:
            self._lag_summary_cache = {"at": now, "summary": summary}
        return dict(summary)

    def set_wire_encoder(self, fn) -> None:
        """Install the byte ring's wire encoder ((etype, obj, rv) ->
        bytes; the apiserver passes its serde line encoder). Kept on the
        store so core demotion re-installs it on the twin."""
        self._wire_encoder = fn
        if hasattr(self._core, "set_wire_encoder"):
            self._core.set_wire_encoder(fn)

    def watch_plane_state(self) -> dict:
        """Subscription-class fan-out snapshot (classes, members, ring
        occupancy, bytes served) from the commit core, and the obs
        delta-sync point: the core counts materializations/shared hits
        monotonically; this folds the deltas into the process counters
        and refreshes the per-kind class gauge."""
        fn = getattr(self._core, "fanout_stats", None)
        if fn is None:    # a stale prebuilt .so without the class plane
            return {"shared_classes": 0, "classes": []}
        stats = fn()
        with self._lock:
            synced = self._fanout_obs_synced
            d_mat = stats["materializations"] - synced["materializations"]
            d_sh = stats["shared_hits"] - synced["shared_hits"]
            synced["materializations"] = stats["materializations"]
            synced["shared_hits"] = stats["shared_hits"]
        if d_mat > 0:
            WATCH_COPYOUT_MAT.inc(d_mat)
        if d_sh > 0:
            WATCH_COPYOUT_SHARED.inc(d_sh)
        per_kind: dict[str, int] = {}
        for row in stats["classes"]:
            per_kind[row["kind"]] = per_kind.get(row["kind"], 0) + 1
        for kind, n in per_kind.items():
            WATCH_CLASSES_GAUGE.labels(kind).set(n)
        for kind in self._gauge_kinds - set(per_kind):
            WATCH_CLASSES_GAUGE.labels(kind).set(0)   # all classes gone
        self._gauge_kinds = set(per_kind)
        return stats

    def debug_state(self) -> dict:
        with self._lock:
            n_objs = {k: len(v) for k, v in self._objs.items()}
            rv = self._core.rv()
            n_watchers = len(self._watch_ids)
        return {"resource_version": rv,
                "commit_core": self.core_impl,
                "objects": n_objs,
                "watchers_total": n_watchers,
                "watchers": self.watcher_lags(),
                "watcher_lag_summary": self.watcher_lag_summary(),
                "watch_plane": self.watch_plane_state()}

    # -- alias tripwire ------------------------------------------------------
    @staticmethod
    def _fingerprint(obj: Any) -> int:
        return hash(repr(obj))

    def _record_entry(self, kind: str, key: str, obj: Any) -> None:
        if self._integrity is not None:
            self._integrity[(kind, key)] = self._fingerprint(obj)

    def _check_entry(self, kind: str, key: str, obj: Any) -> None:
        if self._integrity is None:
            return
        fp = self._integrity.get((kind, key))
        if fp is not None and fp != self._fingerprint(obj):
            raise RuntimeError(
                f"store integrity violation: {kind}/{key} was mutated in "
                "place through an aliased reference (watch event or "
                "create/update return value) — consumers must clone() "
                "before mutating")

    def check_integrity(self) -> None:
        """Verify every live bucket entry still matches the fingerprint
        recorded at its write (debug mode only; no-op otherwise)."""
        with self._lock:
            if self._integrity is None:
                return
            for kind, bucket in self._objs.items():
                for key, obj in bucket.items():
                    self._check_entry(kind, key, obj)

    # -- reads --------------------------------------------------------------
    def get(self, kind: str, key: str) -> Any:
        with self._lock:
            obj = self._objs.get(kind, {}).get(key)
            if obj is None:
                raise NotFoundError(f"{kind}/{key}")
            return _clone(obj)

    def list(self, kind: str) -> tuple[list[Any], int]:
        """Objects plus the store resourceVersion the list is consistent at."""
        with self._lock:
            objs = [_clone(o) for o in self._objs.get(kind, {}).values()]
            return objs, self._core.rv()

    def resource_version(self) -> int:
        with self._lock:
            return self._core.rv()

    def contains(self, kind: str, key: str) -> bool:
        """Existence probe without the clone a get() pays — the burst
        commit's stale-host check runs this once per unique host per
        wave."""
        with self._lock:
            return key in self._objs.get(kind, {})

    def count(self, kind: str) -> int:
        """O(1) object count — the burst launch's stale scan compares it
        against the enumeration length to catch a node death whose rows
        received no decisions (the removal still shifts rotation and
        tie-breaking, so the launch must be refused either way)."""
        with self._lock:
            return len(self._objs.get(kind, {}))

    # -- fencing tokens (round 18, active-active fleet) ----------------------
    # A scope names one partition lease; tokens are the lease's
    # resourceVersion at acquisition (strictly greater for every later
    # claimant). Validation/advance live in the commit core (native AND
    # twin — identical fence_ok/advance_fence pair); the store-side dict
    # is only the stale-prebuilt-.so fallback.
    def _fence_ok_locked(self, scope: str, token: int) -> bool:
        fn = getattr(self._core, "fence_ok", None)
        if fn is not None:
            return bool(fn(scope, int(token)))
        return int(token) >= self._py_fences.get(scope, 0)

    def _fence_advance_locked(self, scope: str, token: int) -> bool:
        fn = getattr(self._core, "advance_fence", None)
        if fn is not None:
            return bool(fn(scope, int(token)))
        if int(token) < self._py_fences.get(scope, 0):
            return False
        self._py_fences[scope] = int(token)
        return True

    @staticmethod
    def _fence_pairs(fence) -> list:
        """Normalize a fence argument: one (scope, token) pair or a list
        of pairs (a wave may span several claimed shards)."""
        if not fence:
            return []
        if isinstance(fence, tuple) and len(fence) == 2 \
                and isinstance(fence[0], str):
            return [fence]
        return list(fence)

    def _check_fences_locked(self, fence, verb: str) -> None:
        """Validate EVERY fence pair read-only first, then advance — so a
        rejection is atomic (no scope advanced, nothing written) and a
        mixed wave can never partially move the table. Raises FencedError
        naming the superseded scope."""
        pairs = self._fence_pairs(fence)
        for scope, token in pairs:
            if not self._fence_ok_locked(scope, token):
                FENCED_WRITES.labels(verb).inc()
                raise FencedError(
                    f"{verb}: fencing token {token} for {scope!r} is "
                    f"superseded (current "
                    f"{self.fence_token_locked(scope)})", scope=scope)
        for scope, token in pairs:
            self._fence_advance_locked(scope, token)

    def fence_token_locked(self, scope: str) -> int:
        fn = getattr(self._core, "fence_token", None)
        if fn is not None:
            return int(fn(scope))
        return self._py_fences.get(scope, 0)

    def advance_fence(self, scope: str, token: int) -> bool:
        """The claim protocol's handoff verb: a new partition-lease holder
        advances the fence BEFORE replaying its partition, so any late
        write from the superseded holder is rejected even if the usurper
        has not written yet. Returns False (no state change) when `token`
        is itself already superseded — the caller lost a newer race and
        must drop its claim."""
        with self._lock:
            ok = self._fence_advance_locked(scope, int(token))
        if not ok:
            FENCED_WRITES.labels("advance").inc()
        return ok

    def fence_token(self, scope: str) -> int:
        with self._lock:
            return self.fence_token_locked(scope)

    def fence_table(self) -> dict:
        """scope -> token snapshot (the fleet replay harness re-applies it
        at the recorded points; /debug material otherwise)."""
        with self._lock:
            fn = getattr(self._core, "fence_table", None)
            if fn is not None:
                return dict(fn())
            return dict(self._py_fences)

    # -- writes -------------------------------------------------------------
    # Every verb's per-object body lives in the commit core (shared by the
    # serial verbs and the burst wave): one snapshot serves the bucket, the
    # event log, and the return value — the store NEVER mutates a stored
    # object in place, and consumers receive store objects read-only;
    # anything that mutates must clone() first, which every caller (cache,
    # queue, scheduler) already does.
    def _flush(self) -> None:
        """Publish pending log entries to watchers, booking drops."""
        dropped = self._core.flush()
        if dropped:
            WATCH_DROPPED.labels("slow-consumer").inc(dropped)

    def _trim_events_locked(self) -> None:
        """Evict the oldest audit records past the retention cap (event
        TTL analog; caller holds the lock and flushes after). The evicted
        object moves into the DELETED log entry — it left the bucket, so
        no clone is needed (the usual read-only aliasing convention)."""
        cap = self._events_cap
        if not cap:
            return
        bucket = self._objs.get(EVENTS)
        if bucket is None or len(bucket) <= cap:
            return
        core = self._core
        trimmed = 0
        while len(bucket) > cap:
            key = next(iter(bucket))
            obj = bucket.pop(key)
            if self._integrity is not None:
                self._integrity.pop((EVENTS, key), None)
            core.append(DELETED, EVENTS, obj, core.next_rv())
            trimmed += 1
        EVENTS_TRIMMED.inc(trimmed)

    def create(self, kind: str, obj: Any, move: bool = False) -> Any:
        """`move=True` transfers ownership: the caller promises never to
        touch `obj` again, skipping the write snapshot (the event recorder's
        fire-and-forget records use this)."""
        gate = self.admission_gate
        if gate is not None and kind == PODS:
            # serving backpressure: shed BEFORE anything is written (a
            # 429'd create definitively did not land), and evict any
            # ledger record the shed attempt would otherwise poison
            gate.admit(obj)
        with self._lock:
            self._core_guard()
            try:
                stored = self._core.create_batch(
                    self._objs.setdefault(kind, {}), kind, [obj], move)[0]
                if kind == EVENTS:
                    self._trim_events_locked()
            finally:
                self._flush()
            self._record_entry(kind, _key_of(stored), stored)
        if gate is not None and kind == PODS:
            # admission accepted: open the pod's lifecycle record at the
            # accepted create, BEFORE the informer delivers it to
            # queue.add (the watch-to-enqueue phase's left boundary)
            from kubernetes_tpu.obs.ledger import LEDGER
            LEDGER.stamp_admission(stored.key)
        return stored

    def update(self, kind: str, obj: Any, expect_rv: Optional[int] = None) -> Any:
        with self._lock:
            bucket = self._objs.setdefault(kind, {})
            key = _key_of(obj)
            current = bucket.get(key)
            if current is None:
                raise NotFoundError(f"{kind}/{key}")
            if expect_rv is not None and current.resource_version != expect_rv:
                raise ConflictError(
                    f"{kind}/{key}: rv {current.resource_version} != expected {expect_rv}")
            self._check_entry(kind, key, current)
            self._core_guard()
            stored = _clone(obj)
            rv = self._core.next_rv()
            stored.resource_version = rv
            bucket[key] = stored
            self._record_entry(kind, key, stored)
            self._core.append(MODIFIED, kind, stored, rv)  # see create()
            self._flush()
            return stored

    def guaranteed_update(self, kind: str, key: str,
                          mutate: Callable[[Any], Any],
                          allow_skip: bool = False) -> Any:
        """Read-modify-write retry loop (reference: GuaranteedUpdate).
        With allow_skip, a mutate returning None means "no change" and the
        current object is returned without a write."""
        while True:
            current = self.get(kind, key)
            rv = current.resource_version
            updated = mutate(current)
            if allow_skip and updated is None:
                return current
            try:
                return self.update(kind, updated, expect_rv=rv)
            except ConflictError:
                continue

    # -- batched mutation bodies (round 23; caller holds the lock) -----------
    def _update_batch_locked(self, bucket: dict, kind: str,
                             objs: list) -> list:
        """One core call lands a whole batch of replacement objects (the
        per-object body identical to update()); a stale prebuilt .so
        without the verb degrades to per-entry appends."""
        ub = getattr(self._core, "update_batch", None)
        if ub is not None:
            stored = ub(bucket, kind, objs)
        else:
            core = self._core
            stored = []
            for obj in objs:
                snap = _clone(obj)
                rv = core.next_rv()
                snap.resource_version = rv
                bucket[_key_of(obj)] = snap
                core.append(MODIFIED, kind, snap, rv)
                stored.append(snap)
        if self._integrity is not None:
            for o in stored:
                self._record_entry(kind, _key_of(o), o)
        return stored

    def _delete_batch_locked(self, bucket: dict, kind: str,
                             keys: list) -> list:
        """One core call pops a whole batch of keys (delete() semantics
        per key; missing keys skip); stale-.so fallback appends per entry."""
        db = getattr(self._core, "delete_batch", None)
        if db is not None:
            return db(bucket, kind, keys)
        core = self._core
        gone = []
        for key in keys:
            obj = bucket.pop(key, None)
            if obj is None:
                continue
            core.append(DELETED, kind, _clone(obj), core.next_rv())
            gone.append(obj)
        return gone

    def _mutation_token_hit(self, token: Optional[str]):
        if token is None:
            return None
        hit = self._mutation_tokens.get(token)
        if hit is not None:
            WAVE_DEDUP.inc()
        return hit

    def _mutation_token_record(self, token: Optional[str], result) -> None:
        if token is None:
            return
        self._mutation_tokens[token] = result
        while len(self._mutation_tokens) > WAVE_TOKEN_CAP:
            self._mutation_tokens.popitem(last=False)

    def update_many(self, kind: str, updates: list, fence=None,
                    token: Optional[str] = None,
                    conflicts: Optional[list] = None,
                    missing: Optional[list] = None) -> list:
        """Batched update under ONE lock and ONE commit-core call (the
        churn plane's mutation verb, round 23 — the round-17 ingest
        batching mirrored onto the write path). `updates` is a list of
        replacement objects or (obj, expect_rv) pairs; a bare object
        updates unconditionally (expect_rv None), exactly like update().

        Per-item semantics are update()'s, reported per item instead of
        raised: a vanished key lands in `missing`, an rv-CAS loser in
        `conflicts` (both optional out-lists; refused items are skipped,
        never partially applied). Returns the stored snapshots of the
        items that landed, in batch order.

        `fence` carries the writer's partition-lease token(s) and is
        validated BEFORE any write — a superseded token rejects the whole
        batch atomically (FencedError), the commit_wave contract. `token`
        is the caller's idempotency key: a batch that already landed under
        it returns its recorded result without touching the core."""
        pairs = [(u[0], u[1]) if isinstance(u, tuple) else (u, None)
                 for u in updates]
        with self._lock:
            hit = self._mutation_token_hit(token)
            if hit is not None:
                stored, confl, miss = hit
                if conflicts is not None:
                    conflicts.extend(confl)
                if missing is not None:
                    missing.extend(miss)
                return list(stored)
            # fence validation FIRST — before the chaos seam and every
            # core write (the commit_wave ordering contract)
            if fence is not None:
                self._check_fences_locked(fence, "update_many")
            chaos.check("store.update_many")
            self._core_guard()
            bucket = self._objs.setdefault(kind, {})
            confl: list = []
            miss: list = []
            live: list = []
            for obj, expect_rv in pairs:
                key = _key_of(obj)
                current = bucket.get(key)
                if current is None:
                    miss.append(key)
                    continue
                if expect_rv is not None \
                        and current.resource_version != expect_rv:
                    confl.append(key)
                    continue
                self._check_entry(kind, key, current)
                live.append(obj)
            stored = self._update_batch_locked(bucket, kind, live) \
                if live else []
            self._flush()
            self._mutation_token_record(
                token, (list(stored), list(confl), list(miss)))
        BATCH_MUTATION_CALLS.labels("update_many").inc()
        if stored:
            BATCH_MUTATIONS.labels("update_many").inc(len(stored))
        if conflicts is not None:
            conflicts.extend(confl)
        if missing is not None:
            missing.extend(miss)
        return stored

    def delete(self, kind: str, key: str) -> Any:
        with self._lock:
            bucket = self._objs.get(kind, {})
            obj = bucket.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{kind}/{key}")
            self._check_entry(kind, key, obj)
            if self._integrity is not None:
                self._integrity.pop((kind, key), None)
            self._core_guard()
            rv = self._core.next_rv()
            self._core.append(DELETED, kind, _clone(obj), rv)
            self._flush()
        if kind == PODS:
            # lifecycle-ledger finalize-on-delete: a pod deleted while
            # still holding an in-flight slot (pending, or bound and
            # awaiting its bind event's copy-out stamp) must not retain
            # it forever — the completion reaper / PodGC would otherwise
            # leak one record per deletion until the capacity bound
            from kubernetes_tpu.obs.ledger import LEDGER
            LEDGER.finalize_delete(key)
        return obj

    def delete_many(self, kind: str, keys: list) -> list:
        """Batched delete under ONE lock and one flush (the completion
        reaper's verb — per-pod deletes put one lock+flush per reaped pod
        on the serving loop's critical path). Missing keys are skipped;
        returns the deleted objects. Per-key semantics otherwise identical
        to delete()."""
        with self._lock:
            bucket = self._objs.get(kind, {})
            self._core_guard()
            present = []
            for key in keys:
                obj = bucket.get(key)
                if obj is None:
                    continue
                self._check_entry(kind, key, obj)
                if self._integrity is not None:
                    self._integrity.pop((kind, key), None)
                present.append(key)
            # ONE core call pops + logs the whole batch (round 23; one
            # log-ring splice instead of one per key on the native core)
            gone = self._delete_batch_locked(bucket, kind, present) \
                if present else []
            self._flush()
        BATCH_MUTATION_CALLS.labels("delete_many").inc()
        if gone:
            BATCH_MUTATIONS.labels("delete_many").inc(len(gone))
        if kind == PODS and gone:
            from kubernetes_tpu.obs.ledger import LEDGER
            for obj in gone:
                LEDGER.finalize_delete(obj.key)
        return gone

    # -- pod conveniences (the scheduler's write surface) --------------------
    def bind_pod(self, pod_key: str, node_name: str,
                 fence=None) -> Any:
        """POST pods/<p>/binding analog (reference: factory.go:710).

        Single-lock fast path of guaranteed_update(set nodeName): one
        clone, one lock, one event. Round 18 makes the verb an rv-CAS
        bind (the reference rejects a Binding for a pod whose nodeName is
        already set): a pod already bound to a DIFFERENT node raises
        ConflictError and its binding is never overwritten — two racing
        schedulers see exactly one success and one 409 — while a re-bind
        to the SAME node is an idempotent no-op (a retried bind whose
        first attempt landed must look like success). `fence` optionally
        carries the writer's partition-lease fencing token(s); a
        superseded token raises FencedError before anything lands."""
        with self._lock:
            if fence is not None:
                self._check_fences_locked(fence, "bind")
            self._core_guard()
            bucket = self._objs.setdefault(PODS, {})
            current = bucket.get(pod_key)
            if current is None:
                raise NotFoundError(f"{PODS}/{pod_key}")
            # the alias tripwire runs BEFORE the CAS read: a consumer
            # mutation through an aliased reference must fail loudly as
            # corruption, not masquerade as an already-bound conflict
            self._check_entry(PODS, pod_key, current)
            if current.node_name:
                if current.node_name == node_name:
                    return current   # idempotent re-bind: already landed
                BIND_CAS_CONFLICTS.inc()
                raise ConflictError(
                    f"{PODS}/{pod_key}: already bound to "
                    f"{current.node_name} (rv-CAS refused bind to "
                    f"{node_name})")
            self._bind_batch_locked(bucket, [(pod_key, node_name)], [])
            self._flush()
            from kubernetes_tpu.obs.ledger import LEDGER
            LEDGER.commit_many((pod_key,))
            return bucket[pod_key]

    def _bind_batch_locked(self, bucket, bindings: list[tuple[str, str]],
                           conflicts: list) -> list[str]:
        """Batched binding body shared by bind_pod/bind_pods/commit_wave;
        caller holds the lock and flushes. Returns the missing keys and
        appends rv-CAS losers to `conflicts`: a pod already bound to a
        different node is NEVER overwritten (the fleet's double-bind
        impossibility rests on this one scan), and a same-node re-bind is
        a silent no-op (neither missing nor conflicted — the binding
        already landed). The integrity tripwire brackets the core call
        (debug mode only)."""
        if self._integrity is not None:
            # alias tripwire BEFORE the CAS scan: a mutated aliased pod
            # must surface as corruption, not as an already-bound loser
            for pod_key, _n in bindings:
                current = bucket.get(pod_key)
                if current is not None:
                    self._check_entry(PODS, pod_key, current)
        live = []
        for pod_key, node_name in bindings:
            current = bucket.get(pod_key)
            if current is not None and current.node_name:
                if current.node_name != node_name:
                    BIND_CAS_CONFLICTS.inc()
                    conflicts.append(pod_key)
                continue
            live.append((pod_key, node_name))
        if not live:
            return []
        missing = self._core.bind_batch(bucket, PODS, live)
        if self._integrity is not None:
            gone = set(missing)
            for pod_key, _n in live:
                if pod_key not in gone:
                    self._record_entry(PODS, pod_key, bucket[pod_key])
        return missing

    def bind_pods(self, bindings: list[tuple[str, str]],
                  fence=None, conflicts: Optional[list] = None) -> list[str]:
        """Batch form of bind_pod for the burst prefix commit: ONE lock
        acquisition and ONE core call for the whole burst instead of one
        per pod (per-binding semantics identical to bind_pod, including
        the rv-CAS already-bound check). Returns the keys that were
        missing (deleted between decision and commit); rv-CAS losers go
        to `conflicts` when the caller passes a list — else they ride the
        missing return (either way the caller requeues them, never
        overwrites). `fence` validates the writer's partition-lease
        tokens atomically before anything lands."""
        confl: list = []
        with self._lock:
            if fence is not None:
                self._check_fences_locked(fence, "bind")
            self._core_guard()
            bucket = self._objs.setdefault(PODS, {})
            missing = self._bind_batch_locked(bucket, bindings, confl)
        self._flush()
        from kubernetes_tpu.obs.ledger import LEDGER
        gone = set(missing) | set(confl)
        LEDGER.commit_many([k for k, _n in bindings if k not in gone])
        if conflicts is not None:
            conflicts.extend(confl)
            return missing
        return missing + confl

    def create_many(self, kind: str, objs: list,
                    move: bool = False) -> list:
        """Batch create under one lock and one core call (event records
        from a burst commit, and the serving lane's batched arrival
        ingest); per-object semantics identical to create(). Raises on
        the first duplicate — callers pass fresh uniquely-named objects.

        Pod batches ride the serving admission surface exactly like
        create(), but with ONE gate evaluation and ONE batched ledger
        admission stamp per call: the gate admits a PREFIX (its depth
        watermark grows monotonically across a batch — see
        BackpressureGate.admit_many), the admitted prefix lands in one
        core call, and a shed tail raises ONE BackpressureError carrying
        `accepted` (how many landed) + the suggested Retry-After. Returns
        the stored objects (admitted prefix)."""
        gate = self.admission_gate
        retry_after = None
        shed = 0
        if gate is not None and kind == PODS and objs:
            admit_many = getattr(gate, "admit_many", None)
            if admit_many is not None:
                n_admit, retry_after = admit_many(objs)
            else:
                # a gate without the batch verb keeps per-pod admits;
                # the first shed ends the batch (prefix semantics)
                n_admit = 0
                try:
                    for o in objs:
                        gate.admit(o)
                        n_admit += 1
                except BackpressureError as e:
                    retry_after = e.retry_after
            shed = len(objs) - n_admit
            objs = objs[:n_admit]
        stored: list = []
        if objs:
            with self._lock:
                self._core_guard()
                try:
                    stored = self._core.create_batch(
                        self._objs.setdefault(kind, {}), kind, objs, move)
                    if kind == EVENTS:
                        self._trim_events_locked()
                finally:
                    self._flush()
                if self._integrity is not None:
                    for o in stored:
                        self._record_entry(kind, _key_of(o), o)
            if gate is not None and kind == PODS:
                # one batched admission stamp for the accepted prefix —
                # the per-pod path's stamp_admission, amortized
                from kubernetes_tpu.obs.ledger import LEDGER
                LEDGER.stamp_admission_many([o.key for o in stored])
        if shed:
            raise BackpressureError(
                f"{kind}: batched create shed {shed}/{shed + len(stored)} "
                f"past the admission watermark",
                retry_after=(retry_after if retry_after is not None
                             else 0.25),
                accepted=len(stored))
        return stored

    def commit_wave(self, bindings: list[tuple[str, str]],
                    events: Optional[list] = None,
                    token: Optional[str] = None,
                    event_spec: Optional[dict] = None,
                    fence=None,
                    conflicts: Optional[list] = None) -> list[str]:
        """One burst wave's whole store-write tail as ONE core call: the
        batched bind (bind_pods semantics) plus the audit-record creates
        for the bindings that landed (`events[i]` rides `bindings[i]`;
        records are created move=True, like the recorder's batch path).
        Fan-out is deliberately NOT triggered here — the scheduler calls
        `fanout_wave()` as its one separate per-wave delivery call, which
        may overlap the remaining host commit work.

        `token` is the caller's idempotency key (one fresh token per wave,
        REUSED across retries of that wave): a wave that already landed
        under the same token returns its recorded missing-keys result
        without touching the core — a retried bind after an AMBIGUOUS
        failure (the wave landed but the caller saw an exception) can
        neither double-land nor double-emit its events.

        `fence` (round 18) carries the writing scheduler's partition-lease
        fencing token(s): an expired or superseded token rejects the WHOLE
        wave atomically (FencedError; no bind, no event, no rv — on the
        native core and the twin alike, since validation precedes every
        core write). Bindings whose pod is ALREADY bound to a different
        node are rv-CAS conflicts: skipped (never overwritten), reported
        via `conflicts` when a list is passed, else merged into the
        missing return; their audit records are skipped exactly like a
        vanished pod's. Same-node re-binds are idempotent no-ops.

        `event_spec` (round 17, mutually exclusive with `events`) asks
        the commit core to BUILD the Scheduled audit payloads itself:
        `{"component": name}` makes the core construct one
        `Successfully assigned {key} to {node}` record per landed binding
        (record names ride a reserved block of the recorder's global
        sequence), deleting the per-pod Python record construction from
        the commit thread — natively in commitcore.cpp, with
        PyCommitCore.commit_wave_binds as the twin, and a Python-side
        build as the stale-.so fallback. Retries of the SAME token must
        pass the same spec; the dedupe map answers them either way."""
        import time as _time
        if event_spec is not None:
            from kubernetes_tpu.api.types import EventRecord
            from kubernetes_tpu.store.record import (build_scheduled_records,
                                                     reserve_seq)
            seq0 = reserve_seq(max(1, len(bindings)))
            component = event_spec.get("component", "")
        with self._lock:
            if token is not None:
                hit = self._wave_tokens.get(token)
                if hit is not None:
                    WAVE_DEDUP.inc()
                    missing, confl = list(hit[0]), list(hit[1])
                    if conflicts is not None:
                        conflicts.extend(confl)
                        return missing
                    return missing + confl
            # fence validation FIRST (before the chaos seam and every
            # core write): a superseded claim's retry must stay rejected
            # whole, never half-retried into the core
            if fence is not None:
                self._check_fences_locked(fence, "commit_wave")
            # injected pre-land failure: nothing written yet — the caller
            # retries the whole wave under the same token
            chaos.check("store.commit_wave")
            self._core_guard()
            pods = self._objs.setdefault(PODS, {})
            evs = self._objs.setdefault(EVENTS, {})
            if self._integrity is not None:
                # alias tripwire BEFORE the CAS scan (see bind_pod)
                for pod_key, _n in bindings:
                    current = pods.get(pod_key)
                    if current is not None:
                        self._check_entry(PODS, pod_key, current)
            # rv-CAS pre-scan (round 18): already-bound pods never reach
            # the core — a different-node decision is a conflict, a
            # same-node one an idempotent no-op; `live` keeps wave order
            confl = []
            live = []
            live_idx = []
            for i, (pod_key, node_name) in enumerate(bindings):
                current = pods.get(pod_key)
                if current is not None and current.node_name:
                    if current.node_name != node_name:
                        BIND_CAS_CONFLICTS.inc()
                        confl.append(pod_key)
                    continue
                live.append((pod_key, node_name))
                live_idx.append(i)
            t_core = _time.perf_counter()
            if event_spec is not None:
                cwb = getattr(self._core, "commit_wave_binds", None)
                if cwb is not None:
                    # ONE core call builds the Scheduled payloads AND
                    # lands binds + events (native: zero per-pod Python
                    # on the commit thread)
                    missing = cwb(pods, PODS, live, evs, EVENTS,
                                  EventRecord, component, seq0)
                else:
                    # stale prebuilt .so without the verb: build the
                    # records host-side (identical fields) and ride the
                    # classic wave call
                    recs = build_scheduled_records(
                        EventRecord, live, component, seq0)
                    missing = self._core.commit_wave(
                        pods, PODS, live, evs, EVENTS, recs)
            else:
                recs = events or []
                if recs and len(live) != len(bindings):
                    # events[i] rides bindings[i]: conflicted / no-op
                    # bindings drop their records like vanished pods
                    recs = [recs[i] for i in live_idx]
                missing = self._core.commit_wave(pods, PODS, live,
                                                 evs, EVENTS, recs)
            self._trim_events_locked()   # audit retention (event TTL)
            t_landed = _time.perf_counter()
            if token is not None:
                self._wave_tokens[token] = (list(missing), list(confl))
                while len(self._wave_tokens) > WAVE_TOKEN_CAP:
                    self._wave_tokens.popitem(last=False)
            # injected AMBIGUOUS failure: the wave LANDED (core write done,
            # token recorded) but the caller's "response" is lost below
            ambiguous = chaos.take("store.commit_wave.ambiguous")
            COMMIT_WAVES.labels(self.core_impl).inc()
            COMMIT_WAVE_SECONDS.labels(self.core_impl).observe(
                t_landed - t_core)
            if self._integrity is not None:
                gone = set(missing)
                for pod_key, _n in live:
                    if pod_key not in gone:
                        self._record_entry(PODS, pod_key, pods[pod_key])
                for rec in events or []:
                    stored = evs.get(rec.key)
                    if stored is not None:
                        self._record_entry(EVENTS, rec.key, stored)
        # ledger: the commit_wave landing IS the per-pod commit stamp
        from kubernetes_tpu.obs.ledger import LEDGER
        gone = set(missing) | set(confl)
        LEDGER.commit_many([k for k, _n in bindings if k not in gone],
                           t=t_landed)
        if ambiguous:
            raise chaos.StoreFault(
                "store.commit_wave.ambiguous",
                "chaos: commit_wave response lost after the wave landed")
        if conflicts is not None:
            conflicts.extend(confl)
            return missing
        return missing + confl

    def fanout_wave(self) -> None:
        """Deliver a committed wave's pending watch events: ONE core call
        advancing every watcher's published cursor (O(watchers), not
        O(watchers x events) — consumers copy out on their own threads).
        A chaos-deferred delivery is flushed by the NEXT fan-out call or
        the next consumer poll — delayed, never lost."""
        if chaos.take("store.fanout"):
            self._fanout_deferred = True
            return
        self._fanout_deferred = False
        self._flush()

    def deliver_deferred(self) -> None:
        """Flush a chaos-deferred wave fan-out (called from a consumer's
        poll — the seam's guaranteed delivery point)."""
        with self._lock:
            self._fanout_deferred = False
            self._flush()

    def evict_pod(self, pod_key: str, reason: str = "api") -> Any:
        """POST pods/{ns}/{name}/eviction analog (reference:
        pkg/registry/core/pod/rest/eviction.go): delete the pod ONLY if
        every matching PodDisruptionBudget has disruptions left, and
        charge each matching budget's `disruptions_allowed` in the same
        critical section — two evictors racing a budget of 1 see exactly
        one success and one DisruptionBudgetError (the HTTP surface maps
        it to 429 + Retry-After). The disruption controller's recompute
        reconciles the charged status from pod state afterwards, exactly
        like the reference's trySync."""
        with self._lock:
            pod = self._objs.get(PODS, {}).get(pod_key)
            if pod is None:
                raise NotFoundError(f"{PODS}/{pod_key}")
            blockers = [
                b for b in self._objs.get(PDBS, {}).values()
                if b.namespace == pod.namespace and b.selector is not None
                and b.selector.matches(pod.labels)]
            exhausted = next(
                (b for b in blockers if b.disruptions_allowed <= 0), None)
            if exhausted is not None:
                # the reference eviction handler's exact message wording
                raise DisruptionBudgetError(
                    f"Cannot evict pod as it would violate the pod's "
                    f"disruption budget. ({exhausted.key} exhausted "
                    f"for {pod_key})")
            for b in blockers:
                charged = _clone(b)
                charged.disruptions_allowed -= 1
                self.update(PDBS, charged)   # reentrant: emits MODIFIED
            gone = self.delete(PODS, pod_key)
        EVICTIONS.labels(reason).inc()
        return gone

    def evict_many(self, pod_keys: list, reason: str = "api", fence=None,
                   token: Optional[str] = None,
                   stop_on_refusal: bool = False) -> dict:
        """Batched PDB-charging eviction (round 23): the whole batch runs
        in ONE critical section with per-item outcomes — returns
        {pod_key: "evicted" | "refused" | "missing" | "skipped"}. Budget
        charges are visible WITHIN the batch (a budget of 1 facing two
        pods answers one evicted + one refused, exactly like two serial
        racers), and the writes land as one batched MODIFIED per touched
        budget (carrying the cumulative charge) plus one batched DELETED
        pass for the evicted pods — two commit-core calls per batch
        instead of O(pods) serial verbs. A refused item charges nothing
        and deletes nothing.

        `stop_on_refusal` preserves the zone evictor's head-of-line
        pacing: the first refusal ends processing and every later item
        reports "skipped" (not attempted — its token is refundable).
        `fence` validates before any write (whole-batch FencedError);
        `token` dedupes a retried batch onto its recorded outcomes."""
        with self._lock:
            hit = self._mutation_token_hit(token)
            if hit is not None:
                return dict(hit)
            if fence is not None:
                self._check_fences_locked(fence, "evict_many")
            chaos.check("store.evict_many")
            self._core_guard()
            pods = self._objs.get(PODS, {})
            pdb_bucket = self._objs.setdefault(PDBS, {})
            outcomes: dict = {}
            charged: dict = {}   # pdb key -> working clone (batch-visible)
            to_delete: list = []
            stopped = False
            for pod_key in pod_keys:
                if stopped:
                    outcomes[pod_key] = "skipped"
                    continue
                pod = pods.get(pod_key)
                if pod is None:
                    outcomes[pod_key] = "missing"
                    continue
                self._check_entry(PODS, pod_key, pod)
                blockers = [
                    charged.get(b.key, b)
                    for b in pdb_bucket.values()
                    if b.namespace == pod.namespace
                    and b.selector is not None
                    and b.selector.matches(pod.labels)]
                if any(b.disruptions_allowed <= 0 for b in blockers):
                    outcomes[pod_key] = "refused"
                    if stop_on_refusal:
                        stopped = True
                    continue
                for b in blockers:
                    c = charged.get(b.key)
                    if c is None:
                        c = charged[b.key] = _clone(b)
                    c.disruptions_allowed -= 1
                outcomes[pod_key] = "evicted"
                to_delete.append(pod_key)
            if charged:
                self._update_batch_locked(pdb_bucket, PDBS,
                                          list(charged.values()))
            if to_delete:
                if self._integrity is not None:
                    for pod_key in to_delete:
                        self._integrity.pop((PODS, pod_key), None)
                self._delete_batch_locked(pods, PODS, to_delete)
            self._flush()
            self._mutation_token_record(token, dict(outcomes))
        BATCH_MUTATION_CALLS.labels("evict_many").inc()
        if to_delete:
            BATCH_MUTATIONS.labels("evict_many").inc(len(to_delete))
            EVICTIONS.labels(reason).inc(len(to_delete))
            from kubernetes_tpu.obs.ledger import LEDGER
            for pod_key in to_delete:
                LEDGER.finalize_delete(pod_key)
        return outcomes

    def set_nominated_node_name(self, pod_key: str, node_name: str) -> Any:
        return self.guaranteed_update(PODS, pod_key,
                                      nominated_node_mutator(node_name))

    def update_pod_group_status(self, group_key: str,
                                phase: Optional[str] = None,
                                members: Optional[int] = None,
                                scheduled: Optional[int] = None,
                                now: Optional[float] = None) -> Any:
        """PodGroup /status subresource analog: phase + member counts only
        (spec fields untouched); no-op writes are skipped. The mutate
        closure is shared with RemoteStore so both transports write
        identical objects (the CLAUDE.md sync rule)."""
        from kubernetes_tpu.coscheduling.types import pod_group_status_mutator
        return self.guaranteed_update(
            PODGROUPS, group_key,
            pod_group_status_mutator(phase=phase, members=members,
                                     scheduled=scheduled, now=now),
            allow_skip=True)

    def update_pod_condition(self, pod_key: str, condition) -> Any:
        """UpdateStatus analog for one condition (reference: factory.go:715
        podConditionUpdater + podutil.UpdatePodCondition): replace the
        condition of the same type if it changed, append if absent; no-op
        write is skipped entirely."""
        return self.guaranteed_update(PODS, pod_key,
                                      pod_condition_mutator(condition),
                                      allow_skip=True)

    # -- watch --------------------------------------------------------------
    def watch(self, kind: str, since_rv: Optional[int] = None,
              selector: Optional[str] = None) -> Watch:
        """Stream events for `kind` after `since_rv` (None → only new events).

        `selector` is an OPAQUE interest key, not a filter: watchers that
        pass the same (kind, selector) dedupe into one subscription class
        and share materialize-once Event objects and serialize-once wire
        bytes; every watcher still sees the kind's FULL event stream.
        None joins the kind's default class.

        Raises ExpiredError when since_rv has fallen out of the event log —
        callers re-list, exactly like the reference's Reflector on 410 Gone.
        (The core can't prove no gap when the oldest retained event may not
        be the first after since_rv.)
        """
        with self._lock:
            try:
                wid = self._core.attach(kind, since_rv, selector)
            except TypeError:
                # stale prebuilt .so predating subscription classes
                wid = self._core.attach(kind, since_rv)
            self._watch_ids[wid] = (kind, selector)
            return Watch(self, kind, wid, selector=selector)

    # -- bulk load (benchmark harness) --------------------------------------
    def load(self, kind: str, objs: Iterable[Any]) -> None:
        for o in objs:
            self.create(kind, o)
