"""In-memory versioned object store with list/watch — the etcd+apiserver analog.

Provides the same distributed-communication contract the reference's control
plane is built on (SURVEY §2.4): a single authoritative store assigning a
monotonically increasing resourceVersion to every write, optimistic
concurrency via resourceVersion preconditions (reference:
staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go GuaranteedUpdate),
and resumable watch streams with a bounded event log (reference:
storage/cacher/cacher.go:217 watch cache; etcd3/watcher.go:99).

Objects are the pruned dataclasses from `kubernetes_tpu.api.types`. The
store snapshots objects ON WRITE (so a caller mutating its argument after
create/update cannot corrupt stored state) and ON READ via get/list — the
stand-in for the reference's serialize/deserialize boundary. Watch events
and create/update RETURN VALUES alias that write snapshot: they are
read-only by convention — consumers that mutate (cache, queue, scheduler)
clone() first, exactly as API clients deserialize their own copy.
"""
from __future__ import annotations

import copy
import os
import queue as _queue
import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

# Well-known kinds (the reference's resource names)
PODS = "pods"
NODES = "nodes"
SERVICES = "services"
REPLICASETS = "replicasets"
PDBS = "poddisruptionbudgets"
PVS = "persistentvolumes"
PVCS = "persistentvolumeclaims"
LEASES = "leases"  # leader-election locks (resourcelock analog)
EVENTS = "events"  # user-visible audit records (record.EventRecorder analog)
PRIORITYCLASSES = "priorityclasses"  # scheduling.k8s.io (admission-resolved)
ENDPOINTS = "endpoints"  # service backends (controllers.endpoints)
RESOURCEQUOTAS = "resourcequotas"  # per-namespace caps (admission-enforced)
DEPLOYMENTS = "deployments"  # apps workload tier (controllers.deployment)
JOBS = "jobs"  # batch run-to-completion (controllers.job)
DAEMONSETS = "daemonsets"  # one-pod-per-node (controllers.daemonset)
STATEFULSETS = "statefulsets"  # ordinal identities (controllers.statefulset)
NAMESPACES = "namespaces"  # lifecycle owned by controllers.namespace
HPAS = "horizontalpodautoscalers"  # autoscaling (controllers.hpa)
CLUSTERROLES = "clusterroles"  # rbac.authorization.k8s.io policy objects
CLUSTERROLEBINDINGS = "clusterrolebindings"
PODMETRICS = "podmetrics"  # metrics.k8s.io stand-in (HPA's usage source)
CRONJOBS = "cronjobs"  # batch schedules (controllers.cronjob)
CONFIGMAPS = "configmaps"
SECRETS = "secrets"
SERVICEACCOUNTS = "serviceaccounts"
PODGROUPS = "podgroups"  # co-scheduling gangs (coscheduling.types.PodGroup)

DEFAULT_WATCH_LOG = 8192  # events retained per kind for resumable watches


class ConflictError(Exception):
    """resourceVersion precondition failed (optimistic-concurrency loss)."""


class NotFoundError(Exception):
    pass


class AlreadyExistsError(Exception):
    pass


class ExpiredError(Exception):
    """Watch asked to resume from a resourceVersion older than the log window
    (the reference returns 410 Gone → client re-lists)."""


@dataclass(frozen=True)
class Event:
    type: str            # ADDED | MODIFIED | DELETED
    kind: str
    obj: Any             # snapshot of the object at this version
    resource_version: int


class Watch:
    """One watch stream: a bounded queue of Events plus a stop handle."""

    def __init__(self, store: "Store", kind: str):
        self._store = store
        self.kind = kind
        self._q: _queue.Queue[Optional[Event]] = _queue.Queue()
        self._stopped = False

    def _deliver(self, event: Event) -> None:
        if not self._stopped:
            self._q.put(event)

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None on timeout / stream close."""
        try:
            return self._q.get(timeout=timeout)
        except _queue.Empty:
            return None

    def try_next(self) -> Optional[Event]:
        """Non-blocking next event, or None when the queue is empty."""
        try:
            return self._q.get_nowait()
        except _queue.Empty:
            return None

    def drain(self) -> list[Event]:
        out = []
        while True:
            try:
                ev = self._q.get_nowait()
            except _queue.Empty:
                return out
            if ev is not None:
                out.append(ev)

    def stop(self) -> None:
        self._stopped = True
        self._store._remove_watch(self)
        self._q.put(None)  # wake any blocked next()


def nominated_node_mutator(node_name: str) -> Callable[[Any], Any]:
    """Mutate closure for SetNominatedNodeName — shared by the embedded
    store and RemoteStore so both transports write identical objects."""
    def mutate(pod):
        pod.nominated_node_name = node_name
        return pod
    return mutate


def pod_condition_mutator(condition) -> Callable[[Any], Any]:
    """Mutate closure for podutil.UpdatePodCondition (factory.go:715):
    replace the same-type condition if changed, append if absent, None for
    a no-op (with allow_skip the write is skipped entirely). Shared by the
    embedded store and RemoteStore."""
    def mutate(pod):
        conds = list(pod.conditions)
        for i, c in enumerate(conds):
            if c.type == condition.type:
                if c == condition:
                    return None   # unchanged -> no write
                conds[i] = condition
                break
        else:
            conds.append(condition)
        pod.conditions = tuple(conds)
        return pod
    return mutate


def _key_of(obj: Any) -> str:
    return obj.key


def _clone(obj: Any) -> Any:
    """Snapshot an object crossing the store boundary. Objects with a fast
    clone() use it; anything else falls back to deepcopy."""
    c = getattr(obj, "clone", None)
    return c() if c is not None else copy.deepcopy(obj)


class Store:
    """Threadsafe versioned KV with per-kind watch fan-out."""

    def __init__(self, watch_log_size: int = DEFAULT_WATCH_LOG,
                 debug_integrity: Optional[bool] = None):
        self._lock = threading.RLock()
        self._rv = 0
        self._objs: dict[str, dict[str, Any]] = {}
        self._watchers: dict[str, list[Watch]] = {}
        # per-kind ring of recent events for watch resume
        self._log: dict[str, list[Event]] = {}
        self._log_size = watch_log_size
        # alias tripwire: watch events and create/update return values alias
        # the write snapshot, read-only BY CONVENTION. In debug mode every
        # write records a fingerprint of the stored object; the next write
        # to the same key (and check_integrity()) verifies it, so a consumer
        # that mutated an aliased object in place fails LOUDLY instead of
        # silently corrupting every other consumer. Enabled explicitly or
        # via KTPU_STORE_INTEGRITY=1 (the test suite turns it on).
        if debug_integrity is None:
            debug_integrity = bool(os.environ.get("KTPU_STORE_INTEGRITY"))
        self._integrity: Optional[dict] = {} if debug_integrity else None

    # -- alias tripwire ------------------------------------------------------
    @staticmethod
    def _fingerprint(obj: Any) -> int:
        return hash(repr(obj))

    def _record_entry(self, kind: str, key: str, obj: Any) -> None:
        if self._integrity is not None:
            self._integrity[(kind, key)] = self._fingerprint(obj)

    def _check_entry(self, kind: str, key: str, obj: Any) -> None:
        if self._integrity is None:
            return
        fp = self._integrity.get((kind, key))
        if fp is not None and fp != self._fingerprint(obj):
            raise RuntimeError(
                f"store integrity violation: {kind}/{key} was mutated in "
                "place through an aliased reference (watch event or "
                "create/update return value) — consumers must clone() "
                "before mutating")

    def check_integrity(self) -> None:
        """Verify every live bucket entry still matches the fingerprint
        recorded at its write (debug mode only; no-op otherwise)."""
        with self._lock:
            if self._integrity is None:
                return
            for kind, bucket in self._objs.items():
                for key, obj in bucket.items():
                    self._check_entry(kind, key, obj)

    # -- reads --------------------------------------------------------------
    def get(self, kind: str, key: str) -> Any:
        with self._lock:
            obj = self._objs.get(kind, {}).get(key)
            if obj is None:
                raise NotFoundError(f"{kind}/{key}")
            return _clone(obj)

    def list(self, kind: str) -> tuple[list[Any], int]:
        """Objects plus the store resourceVersion the list is consistent at."""
        with self._lock:
            objs = [_clone(o) for o in self._objs.get(kind, {}).values()]
            return objs, self._rv

    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    # -- writes -------------------------------------------------------------
    def _create_locked(self, kind: str, obj: Any, move: bool) -> Any:
        """Single-entry create body; caller holds the lock. One snapshot
        serves the bucket, the event log, and the return value: the store
        NEVER mutates a stored object in place (every write replaces the
        bucket entry), and consumers receive store objects read-only —
        anything that mutates must clone() first, which every caller
        (cache, queue, scheduler) already does."""
        bucket = self._objs.setdefault(kind, {})
        key = _key_of(obj)
        if key in bucket:
            raise AlreadyExistsError(f"{kind}/{key}")
        stored = obj if move else _clone(obj)
        self._rv += 1
        stored.resource_version = self._rv
        bucket[key] = stored
        self._record_entry(kind, key, stored)
        self._emit(Event(ADDED, kind, stored, self._rv))
        return stored

    def create(self, kind: str, obj: Any, move: bool = False) -> Any:
        """`move=True` transfers ownership: the caller promises never to
        touch `obj` again, skipping the write snapshot (the event recorder's
        fire-and-forget records use this)."""
        with self._lock:
            return self._create_locked(kind, obj, move)

    def update(self, kind: str, obj: Any, expect_rv: Optional[int] = None) -> Any:
        with self._lock:
            bucket = self._objs.setdefault(kind, {})
            key = _key_of(obj)
            current = bucket.get(key)
            if current is None:
                raise NotFoundError(f"{kind}/{key}")
            if expect_rv is not None and current.resource_version != expect_rv:
                raise ConflictError(
                    f"{kind}/{key}: rv {current.resource_version} != expected {expect_rv}")
            self._check_entry(kind, key, current)
            stored = _clone(obj)
            self._rv += 1
            stored.resource_version = self._rv
            bucket[key] = stored
            self._record_entry(kind, key, stored)
            self._emit(Event(MODIFIED, kind, stored, self._rv))  # see create()
            return stored

    def guaranteed_update(self, kind: str, key: str,
                          mutate: Callable[[Any], Any],
                          allow_skip: bool = False) -> Any:
        """Read-modify-write retry loop (reference: GuaranteedUpdate).
        With allow_skip, a mutate returning None means "no change" and the
        current object is returned without a write."""
        while True:
            current = self.get(kind, key)
            rv = current.resource_version
            updated = mutate(current)
            if allow_skip and updated is None:
                return current
            try:
                return self.update(kind, updated, expect_rv=rv)
            except ConflictError:
                continue

    def delete(self, kind: str, key: str) -> Any:
        with self._lock:
            bucket = self._objs.get(kind, {})
            obj = bucket.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{kind}/{key}")
            self._check_entry(kind, key, obj)
            if self._integrity is not None:
                self._integrity.pop((kind, key), None)
            self._rv += 1
            self._emit(Event(DELETED, kind, _clone(obj), self._rv))
            return obj

    # -- pod conveniences (the scheduler's write surface) --------------------
    def bind_pod(self, pod_key: str, node_name: str) -> Any:
        """POST pods/<p>/binding analog (reference: factory.go:710).

        Single-lock fast path of guaranteed_update(set nodeName): the
        binding subresource replaces one spec field unconditionally (the
        reference's Bind POST carries no resourceVersion precondition), so
        no CAS retry loop — one clone, one lock, one event."""
        with self._lock:
            bucket = self._objs.setdefault(PODS, {})
            if not self._bind_locked(bucket, pod_key, node_name):
                raise NotFoundError(f"{PODS}/{pod_key}")
            return bucket[pod_key]

    def _bind_locked(self, bucket, pod_key: str, node_name: str) -> bool:
        """Single-binding body shared by bind_pod/bind_pods; caller holds
        the lock. Returns False when the pod is gone."""
        current = bucket.get(pod_key)
        if current is None:
            return False
        self._check_entry(PODS, pod_key, current)
        stored = _clone(current)
        stored.node_name = node_name
        self._rv += 1
        stored.resource_version = self._rv
        bucket[pod_key] = stored
        self._record_entry(PODS, pod_key, stored)
        self._emit(Event(MODIFIED, PODS, stored, self._rv))
        return True

    def bind_pods(self, bindings: list[tuple[str, str]]) -> list[str]:
        """Batch form of bind_pod for the burst prefix commit: ONE lock
        acquisition for the whole burst instead of one per pod (the
        per-binding semantics are _bind_locked's, identical to bind_pod).
        Returns the keys that were missing (deleted between decision and
        commit); the caller handles those like failed binds."""
        missing = []
        with self._lock:
            bucket = self._objs.setdefault(PODS, {})
            for pod_key, node_name in bindings:
                if not self._bind_locked(bucket, pod_key, node_name):
                    missing.append(pod_key)
        return missing

    def create_many(self, kind: str, objs: list, move: bool = False) -> None:
        """Batch create under one lock (event records from a burst commit);
        per-object semantics are _create_locked's, identical to create().
        Raises on the first duplicate — callers pass fresh uniquely-named
        objects."""
        with self._lock:
            for obj in objs:
                self._create_locked(kind, obj, move)

    def set_nominated_node_name(self, pod_key: str, node_name: str) -> Any:
        return self.guaranteed_update(PODS, pod_key,
                                      nominated_node_mutator(node_name))

    def update_pod_group_status(self, group_key: str,
                                phase: Optional[str] = None,
                                members: Optional[int] = None,
                                scheduled: Optional[int] = None,
                                now: Optional[float] = None) -> Any:
        """PodGroup /status subresource analog: phase + member counts only
        (spec fields untouched); no-op writes are skipped. The mutate
        closure is shared with RemoteStore so both transports write
        identical objects (the CLAUDE.md sync rule)."""
        from kubernetes_tpu.coscheduling.types import pod_group_status_mutator
        return self.guaranteed_update(
            PODGROUPS, group_key,
            pod_group_status_mutator(phase=phase, members=members,
                                     scheduled=scheduled, now=now),
            allow_skip=True)

    def update_pod_condition(self, pod_key: str, condition) -> Any:
        """UpdateStatus analog for one condition (reference: factory.go:715
        podConditionUpdater + podutil.UpdatePodCondition): replace the
        condition of the same type if it changed, append if absent; no-op
        write is skipped entirely."""
        return self.guaranteed_update(PODS, pod_key,
                                      pod_condition_mutator(condition),
                                      allow_skip=True)

    # -- watch --------------------------------------------------------------
    def watch(self, kind: str, since_rv: Optional[int] = None) -> Watch:
        """Stream events for `kind` after `since_rv` (None → only new events).

        Raises ExpiredError when since_rv has fallen out of the event log —
        callers re-list, exactly like the reference's Reflector on 410 Gone.
        """
        with self._lock:
            w = Watch(self, kind)
            if since_rv is not None:
                log = self._log.get(kind, [])
                if log and since_rv < log[0].resource_version - 1:
                    # Can't prove no gap: the oldest retained event may not
                    # be the first after since_rv.
                    raise ExpiredError(
                        f"{kind}: rv {since_rv} older than log window")
                for ev in log:
                    if ev.resource_version > since_rv:
                        w._deliver(ev)
            self._watchers.setdefault(kind, []).append(w)
            return w

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            lst = self._watchers.get(w.kind, [])
            if w in lst:
                lst.remove(w)

    def _emit(self, event: Event) -> None:
        log = self._log.setdefault(event.kind, [])
        log.append(event)
        if len(log) > self._log_size:
            del log[: len(log) - self._log_size]
        for w in self._watchers.get(event.kind, []):
            w._deliver(event)

    # -- bulk load (benchmark harness) --------------------------------------
    def load(self, kind: str, objs: Iterable[Any]) -> None:
        for o in objs:
            self.create(kind, o)
