"""Semantic oracle: volume predicates + the volume binder bridge.

Mirrors the reference's volume-aware scheduling:
- NoDiskConflict (predicates.go:288): direct-volume double-attach conflicts.
- MaxPDVolumeCountChecker (predicates.go:452): per-plugin attach limits
  counting unique volumes on the node plus the pod's (unbound/missing PVCs
  count pessimistically as unique).
- VolumeZoneChecker (predicates.go:625): bound PVs with zone/region labels
  restrict the node's failure domain.
- VolumeBindingChecker (predicates.go:1581 via CheckVolumeBinding): bound
  PVCs' PVs must fit the node; unbound PVCs need a matching available PV.
- VolumeBinder (pkg/scheduler/volumebinder bridging
  controller/volume/scheduling): assume/bind PVC→PV around pod binding.

Failure reason strings follow predicates/error.go: NoDiskConflict,
MaxVolumeCount, NoVolumeZoneConflict, VolumeBindingNoMatch,
VolumeNodeAffinityConflict.
"""
from __future__ import annotations

from typing import Callable, Optional

from kubernetes_tpu.api.types import (
    Pod, Node, VolumeSource, PersistentVolume, PersistentVolumeClaim,
    PLUGIN_EBS, PLUGIN_GCE_PD, PLUGIN_AZURE_DISK, PLUGIN_CINDER, PLUGIN_CSI,
    DEFAULT_VOLUME_LIMITS,
    LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION,
)
from kubernetes_tpu.cache.node_info import NodeInfo

ERR_DISK_CONFLICT = "NoDiskConflict"
ERR_MAX_VOLUME_COUNT = "MaxVolumeCount"
ERR_VOLUME_ZONE_CONFLICT = "NoVolumeZoneConflict"
ERR_VOLUME_BIND_CONFLICT = "VolumeBindingNoMatch"
ERR_VOLUME_NODE_CONFLICT = "VolumeNodeAffinityConflict"

# plugins where two read-only attachments of the same volume may share a node
_RO_SHARABLE = {PLUGIN_GCE_PD}


class VolumeListers:
    """PVC/PV lookup bundle the predicates consume."""

    def __init__(self,
                 pvcs_fn: Callable[[], list[PersistentVolumeClaim]] = lambda: [],
                 pvs_fn: Callable[[], list[PersistentVolume]] = lambda: []):
        self.pvcs_fn = pvcs_fn
        self.pvs_fn = pvs_fn

    def pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        for c in self.pvcs_fn():
            if c.namespace == namespace and c.name == name:
                return c
        return None

    def pv(self, name: str) -> Optional[PersistentVolume]:
        for v in self.pvs_fn():
            if v.name == name:
                return v
        return None


def _volume_conflict(v: VolumeSource, existing: VolumeSource) -> bool:
    """Reference: isVolumeConflict — same backing volume on the same node;
    GCE PD tolerates all-read-only sharing."""
    if not v.plugin or not v.volume_id:
        return False
    if v.plugin != existing.plugin or v.volume_id != existing.volume_id:
        return False
    if v.plugin in _RO_SHARABLE and v.read_only and existing.read_only:
        return False
    return True


def no_disk_conflict(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    """Reference: predicates.go:288."""
    for v in pod.volumes:
        for ep in node_info.pods:
            for ev in ep.volumes:
                if _volume_conflict(v, ev):
                    return False, [ERR_DISK_CONFLICT]
    return True, []


class MaxVolumeCountChecker:
    """One per plugin family (predicates.go:452)."""

    def __init__(self, plugin: str, listers: VolumeListers,
                 max_volumes: Optional[int] = None):
        self.plugin = plugin
        self.listers = listers
        self.max_volumes = max_volumes

    def _limit(self, node: Optional[Node]) -> int:
        if self.max_volumes is not None:
            return self.max_volumes
        if node is not None:
            # CSI-era per-node limits live in allocatable
            # ("attachable-volumes-<plugin>")
            limit = node.allocatable.get(f"attachable-volumes-{self.plugin}")
            if limit is not None:
                return limit
        return DEFAULT_VOLUME_LIMITS.get(self.plugin, 1 << 30)

    def _filter(self, pod: Pod, into: set) -> None:
        for v in pod.volumes:
            if v.plugin == self.plugin and v.volume_id:
                into.add(v.volume_id)
            elif v.pvc:
                pvc = self.listers.pvc(pod.namespace, v.pvc)
                if pvc is None or not pvc.volume_name:
                    # missing/unbound PVC counts pessimistically as unique
                    # (predicates.go:440-448)
                    into.add(f"pvc-{pod.namespace}/{v.pvc}")
                    continue
                pv = self.listers.pv(pvc.volume_name)
                if pv is None:
                    into.add(f"pv-{pvc.volume_name}")
                elif pv.plugin == self.plugin:
                    into.add(pv.volume_id or pv.name)

    def check(self, pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
        if not pod.volumes:
            return True, []
        new: set = set()
        self._filter(pod, new)
        if not new:
            return True, []
        existing: set = set()
        for ep in node_info.pods:
            self._filter(ep, existing)
        limit = self._limit(node_info.node)
        num_existing = len(existing)
        num_new = len(new - existing)
        from kubernetes_tpu.utils import features
        if features.enabled("BalanceAttachedNodeVolumes"):
            # transient per-cycle counts the balanced-allocation volume
            # variance reads (reference: predicates.go:517-521)
            node_info.transient_allocatable_volumes = limit - num_existing
            node_info.transient_requested_volumes = num_new
        if num_existing + num_new > limit:
            return False, [ERR_MAX_VOLUME_COUNT]
        return True, []


def _zone_match(pv_value: str, node_value: Optional[str]) -> bool:
    """PV zone labels may hold a __-separated set (volumeutil.LabelZonesToSet)."""
    if node_value is None:
        return False
    return node_value in pv_value.split("__")


def make_volume_zone_predicate(listers: VolumeListers):
    def volume_zone(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
        """Reference: predicates.go:625 VolumeZoneChecker.predicate."""
        if not pod.volumes or node_info.node is None:
            return True, []
        node = node_info.node
        for v in pod.volumes:
            if not v.pvc:
                continue
            pvc = listers.pvc(pod.namespace, v.pvc)
            if pvc is None or not pvc.volume_name:
                continue
            pv = listers.pv(pvc.volume_name)
            if pv is None:
                continue
            for label in (LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION):
                want = pv.labels.get(label)
                if want and not _zone_match(want, node.labels.get(label)):
                    return False, [ERR_VOLUME_ZONE_CONFLICT]
        return True, []
    return volume_zone


class VolumeBinder:
    """pkg/scheduler/volumebinder analog: find/assume/bind PVC→PV.

    - find_pod_volumes: CheckVolumeBinding's work — bound PVCs' PVs must be
      node-compatible; unbound PVCs need a matching unclaimed PV.
    - assume: reserve the chosen PVs in memory (cleared by forget).
    - bind: write claim_ref / volume_name through the store.
    """

    def __init__(self, listers: VolumeListers, store=None):
        self.listers = listers
        self.store = store
        self._assumed: dict[str, str] = {}   # pv name -> pvc key

    def _pv_fits_node(self, pv: PersistentVolume, node: Node) -> bool:
        for label in (LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION):
            want = pv.labels.get(label)
            if want and not _zone_match(want, node.labels.get(label)):
                return False
        return True

    def _find_match(self, pvc: PersistentVolumeClaim, node: Node
                    ) -> Optional[PersistentVolume]:
        best = None
        for pv in self.listers.pvs_fn():
            if pv.claim_ref or pv.name in self._assumed:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if pv.capacity < pvc.request:
                continue
            if not self._pv_fits_node(pv, node):
                continue
            if best is None or pv.capacity < best.capacity:
                best = pv   # smallest fitting PV, like the volume binder
        return best

    def find_pod_volumes(self, pod: Pod, node: Node
                         ) -> tuple[bool, bool, list[str]]:
        """(all_bound_satisfied, all_unbound_satisfiable, reasons)."""
        reasons: list[str] = []
        bound_ok = True
        unbound_ok = True
        for v in pod.volumes:
            if not v.pvc:
                continue
            pvc = self.listers.pvc(pod.namespace, v.pvc)
            if pvc is None:
                unbound_ok = False
                reasons.append(ERR_VOLUME_BIND_CONFLICT)
                continue
            if pvc.volume_name:
                pv = self.listers.pv(pvc.volume_name)
                if pv is None or not self._pv_fits_node(pv, node):
                    bound_ok = False
                    reasons.append(ERR_VOLUME_NODE_CONFLICT)
            else:
                if self._find_match(pvc, node) is None:
                    unbound_ok = False
                    reasons.append(ERR_VOLUME_BIND_CONFLICT)
        return bound_ok, unbound_ok, reasons

    def make_predicate(self):
        def check_volume_binding(pod: Pod, node_info: NodeInfo
                                 ) -> tuple[bool, list[str]]:
            if not pod.volumes or node_info.node is None:
                return True, []
            bound_ok, unbound_ok, reasons = self.find_pod_volumes(
                pod, node_info.node)
            if bound_ok and unbound_ok:
                return True, []
            return False, reasons
        return check_volume_binding

    # -- assume / bind -------------------------------------------------------
    def assume_pod_volumes(self, pod: Pod, node: Node) -> list[tuple[str, str]]:
        """Reserve matches for the pod's unbound PVCs; returns
        [(pvc_key, pv_name)] reservations."""
        reservations = []
        for v in pod.volumes:
            if not v.pvc:
                continue
            pvc = self.listers.pvc(pod.namespace, v.pvc)
            if pvc is None or pvc.volume_name:
                continue
            pv = self._find_match(pvc, node)
            if pv is not None:
                self._assumed[pv.name] = pvc.key
                reservations.append((pvc.key, pv.name))
        return reservations

    def forget_pod_volumes(self, reservations: list[tuple[str, str]]) -> None:
        for _pvc_key, pv_name in reservations:
            self._assumed.pop(pv_name, None)

    def bind_pod_volumes(self, reservations: list[tuple[str, str]]) -> None:
        """Write the bindings through the store, then drop reservations."""
        from kubernetes_tpu.store.store import PVS, PVCS
        for pvc_key, pv_name in reservations:
            if self.store is not None:
                def set_claim(pv, _pvc_key=pvc_key):
                    pv.claim_ref = _pvc_key
                    return pv

                def set_volume(pvc, _pv_name=pv_name):
                    pvc.volume_name = _pv_name
                    return pvc
                self.store.guaranteed_update(PVS, pv_name, set_claim)
                self.store.guaranteed_update(PVCS, pvc_key, set_volume)
            self._assumed.pop(pv_name, None)


def make_volume_predicates(listers: VolumeListers,
                           binder: Optional[VolumeBinder] = None
                           ) -> dict[str, Callable]:
    """The volume slots of the default predicate set."""
    binder = binder or VolumeBinder(listers)
    return {
        "NoDiskConflict": no_disk_conflict,
        "MaxEBSVolumeCount": MaxVolumeCountChecker(PLUGIN_EBS, listers).check,
        "MaxGCEPDVolumeCount": MaxVolumeCountChecker(PLUGIN_GCE_PD, listers).check,
        "MaxAzureDiskVolumeCount": MaxVolumeCountChecker(PLUGIN_AZURE_DISK, listers).check,
        "MaxCinderVolumeCount": MaxVolumeCountChecker(PLUGIN_CINDER, listers).check,
        "MaxCSIVolumeCountPred": MaxVolumeCountChecker(PLUGIN_CSI, listers).check,
        "NoVolumeZoneConflict": make_volume_zone_predicate(listers),
        "CheckVolumeBinding": binder.make_predicate(),
    }
