"""Semantic oracle: preemption — exact reference behavior.

Mirrors pkg/scheduler/core/generic_scheduler.go:
- Preempt (:310): eligibility → candidate nodes → victim selection per node
  → 6-criteria node pick → lower-priority nomination cleanup.
- selectVictimsOnNode (:1054): remove all lower-priority pods, check fit,
  then the order-dependent reprieve loop (PDB-violating pods first, each
  sorted by descending importance).
- pickOneNodeForPreemption (:837): minPDBViolations → minHighestVictim →
  minSumPriorities → fewestVictims → latestStartTime → first.
- podFitsOnNode's two-pass nominated-pod handling (:598,627): a node with
  higher/equal-priority nominated pods must fit both with and without them.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.types import Pod, Node, PodDisruptionBudget
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.oracle import predicates as preds


@dataclass
class Victims:
    """Reference: api/types.go:263."""
    pods: list[Pod] = field(default_factory=list)
    num_pdb_violations: int = 0


def importance_key(p: Pod):
    """Sort key for descending importance (reference:
    pkg/scheduler/util.MoreImportantPod — higher priority first, ties broken
    by earlier start time). The single source for victim ordering."""
    start = p.start_time if p.start_time is not None else float("inf")
    return (-p.priority, start)


def more_important_pod(a: Pod, b: Pod) -> bool:
    return importance_key(a) < importance_key(b)


def pod_eligible_to_preempt_others(pod: Pod,
                                   node_infos: dict[str, NodeInfo]) -> bool:
    """Reference: :1165 — a pod that already nominated a node is ineligible
    while a lower-priority pod on that node is terminating."""
    if pod.nominated_node_name:
        ni = node_infos.get(pod.nominated_node_name)
        if ni is not None:
            for p in ni.pods:
                if p.deleted and p.priority < pod.priority:
                    return False
    return True


def nodes_where_preemption_might_help(
        node_infos: dict[str, NodeInfo],
        all_node_names: list[str],
        failed_predicates: dict[str, list[str]]) -> list[str]:
    """Reference: :1142 — drop nodes whose failure includes an unresolvable
    reason (preempting pods can't fix a selector/taint mismatch)."""
    out = []
    for name in all_node_names:
        # a node absent from the failure map (e.g. extender-trimmed) counts
        # as resolvable — the reference includes it (:1145-1151)
        reasons = failed_predicates.get(name) or []
        if any(r in preds.UNRESOLVABLE_FAILURES for r in reasons):
            continue
        out.append(name)
    return out


def no_possible_victims(pod: Pod, node_infos: dict[str, NodeInfo],
                        candidates: list[str]) -> bool:
    """Fast-path predicate shared by the oracle Preemptor and the device
    path (core/tpu_scheduler.preempt) so the two cannot drift: when no
    candidate hosts any lower-priority pod, victim removal is a no-op on
    every node — a candidate could only succeed if the pod already fit
    unchanged, impossible against the snapshot that produced its FitError.
    The reference discovers this by walking every candidate through
    selectVictimsOnNode (generic_scheduler.go:1054); skipping the walk
    avoids an O(candidates x predicate-set) scan per failed pod in
    same-priority saturation workloads."""
    return not any(p.priority < pod.priority
                   for name in candidates
                   for p in node_infos[name].pods)


def pods_violating_pdbs(pods: list[Pod],
                        pdbs: list[PodDisruptionBudget]) -> list[Pod]:
    """Reference: :1032 filterPodsWithPDBViolation — a pod violates when a
    matching PDB has no disruptions left."""
    violating = []
    for pod in pods:
        for pdb in pdbs:
            if pdb.namespace != pod.namespace or pdb.selector is None:
                continue
            if pdb.selector.matches(pod.labels) and pdb.disruptions_allowed <= 0:
                violating.append(pod)
                break
    return violating


def pods_violating_pdbs_mask(table, pdbs: list[PodDisruptionBudget]) -> "np.ndarray":
    """[P] bool twin of pods_violating_pdbs over a columnar pod table
    (ops.node_state.PodTable, duck-typed like the predicates matchers): one
    selector mask per PDB instead of a Python loop per (pod, pdb) pair.
    Must stay bit-identical to a row-by-row scalar evaluation — the victim
    table's reprieve ordering sorts on these flags, so a divergence here is
    a preemption-decision divergence (pinned by the PDB mask parity
    fuzzes)."""
    import numpy as np
    from kubernetes_tpu.oracle.predicates import selector_match_mask
    viol = np.zeros(len(table.pods), dtype=bool)
    for pdb in pdbs:
        if pdb.selector is None or pdb.disruptions_allowed > 0:
            continue
        nsid = table.ns_vocab.get(pdb.namespace)
        if nsid is None:
            continue
        viol |= (table.ns_id == nsid) & selector_match_mask(pdb.selector,
                                                            table)
    return viol


def select_victims_on_node(pod: Pod, node_info: NodeInfo,
                           fits_fn: Callable[[Pod, NodeInfo], bool],
                           pdbs: list[PodDisruptionBudget],
                           checker=None) -> Optional[Victims]:
    """Reference: :1054. `fits_fn` runs the predicate suite against a
    *mutated copy* of the node (the caller passes podFitsOnNode bound to the
    predicate set). `checker` is the inter-pod-affinity metadata handle:
    every mutation mirrors into it incrementally (meta.RemovePod/AddPod,
    :1068-1078) instead of invalidating the cluster scan per fit check.
    Returns None when preemption can't help on this node."""
    ni = node_info.clone()
    node = ni.node
    # remove all lower-priority pods
    potential = [p for p in ni.pods if p.priority < pod.priority]
    for p in list(potential):
        ni.remove_pod(p)
        if checker is not None:
            checker.remove_pod(pod, p, node)
    if not fits_fn(pod, ni):
        return None
    # reprieve loop: PDB-violating victims get re-added first (so we prefer
    # keeping them), each group in descending importance
    violating = pods_violating_pdbs(potential, pdbs)
    violating_set = {p.uid for p in violating}
    non_violating = [p for p in potential if p.uid not in violating_set]
    violating.sort(key=importance_key)
    non_violating.sort(key=importance_key)
    victims = Victims()

    def reprieve(p: Pod) -> bool:
        ni.add_pod(p)
        if checker is not None:
            checker.add_pod(pod, p, node)
        if fits_fn(pod, ni):
            return True
        ni.remove_pod(p)
        if checker is not None:
            checker.remove_pod(pod, p, node)
        return False

    for p in violating:
        if not reprieve(p):
            victims.pods.append(p)
            victims.num_pdb_violations += 1
    for p in non_violating:
        if not reprieve(p):
            victims.pods.append(p)
    return victims


def pick_one_node_for_preemption(
        nodes_to_victims: dict[str, Victims]) -> Optional[str]:
    """Reference: :837 — six tie-break criteria, order preserved from the
    candidate map's iteration order (here: insertion order)."""
    if not nodes_to_victims:
        return None
    # a node with no victims wins immediately
    for name, v in nodes_to_victims.items():
        if not v.pods:
            return name
    candidates = list(nodes_to_victims)

    # 1. fewest PDB violations
    min_pdb = min(nodes_to_victims[n].num_pdb_violations for n in candidates)
    candidates = [n for n in candidates
                  if nodes_to_victims[n].num_pdb_violations == min_pdb]
    if len(candidates) == 1:
        return candidates[0]

    # 2. lowest first-victim priority. The victims list is ordered
    # (PDB-violating victims in descending importance, then the rest), and
    # the reference reads Pods[0] — NOT the true maximum (:876).
    def first_priority(n):
        return nodes_to_victims[n].pods[0].priority
    min_high = min(first_priority(n) for n in candidates)
    candidates = [n for n in candidates if first_priority(n) == min_high]
    if len(candidates) == 1:
        return candidates[0]

    # 3. smallest sum of victim priorities, each offset by 2^31 so victim
    # COUNT dominates for negative priorities (:899-903)
    def sum_priorities(n):
        return sum(p.priority + (1 << 31) for p in nodes_to_victims[n].pods)
    min_sum = min(sum_priorities(n) for n in candidates)
    candidates = [n for n in candidates if sum_priorities(n) == min_sum]
    if len(candidates) == 1:
        return candidates[0]

    # 4. fewest victims
    min_count = min(len(nodes_to_victims[n].pods) for n in candidates)
    candidates = [n for n in candidates
                  if len(nodes_to_victims[n].pods) == min_count]
    if len(candidates) == 1:
        return candidates[0]

    # 5. latest (earliest start time among the truly-highest-priority
    # victims) — util.GetEarliestPodStartTime; a nil start time reads as
    # "now" in the reference, i.e. latest (+inf here)
    def earliest_start_of_highest(n):
        pods = nodes_to_victims[n].pods
        high = max(p.priority for p in pods)
        return min(p.start_time if p.start_time is not None else float("inf")
                   for p in pods if p.priority == high)
    best = candidates[0]
    best_t = earliest_start_of_highest(best)
    for n in candidates[1:]:
        t = earliest_start_of_highest(n)
        if t > best_t:
            best_t = t
            best = n
    return best


@dataclass
class PreemptionResult:
    node: Optional[Node]
    victims: list[Pod]
    nominated_to_clear: list[Pod]


class Preemptor:
    """genericScheduler.Preempt (:310) against a snapshot."""

    def __init__(self,
                 pdbs_fn: Callable[[], list[PodDisruptionBudget]] = lambda: [],
                 extenders: Optional[list] = None):
        self.pdbs_fn = pdbs_fn
        self.extenders = extenders or []

    def preempt(self, pod: Pod, node_infos: dict[str, NodeInfo],
                all_node_names: list[str],
                fit_error,
                nominated_pods_fn: Callable[[str], list[Pod]] = lambda n: [],
                predicate_set_fn: Optional[Callable] = None) -> PreemptionResult:
        if not pod_eligible_to_preempt_others(pod, node_infos):
            return PreemptionResult(None, [], [])
        candidates = nodes_where_preemption_might_help(
            node_infos, all_node_names, fit_error.failed_predicates)
        if not candidates:
            # preemption can't help anywhere: the pod's own stale nomination
            # must be cleared (reference: generic_scheduler.go:330-333 returns
            # []*v1.Pod{pod} as nominatedPodsToClear)
            return PreemptionResult(None, [], [pod])
        pdbs = self.pdbs_fn()
        if no_possible_victims(pod, node_infos, candidates):
            return PreemptionResult(None, [], [])

        nodes_to_victims: dict[str, Victims] = {}
        for name in candidates:
            ni = node_infos[name]
            # The predicate set sees the snapshot with the candidate's
            # mutated clone standing in for the original: inter-pod affinity
            # must observe removed/reprieved victims, so its metadata cache
            # is invalidated around every mutation (the reference's
            # meta.RemovePod/AddPod, :1068-1078).
            scratch = dict(node_infos)
            funcs = (predicate_set_fn(scratch) if predicate_set_fn
                     else preds.default_predicate_set(scratch))
            checker = funcs.get("_ipa_checker")

            def fits_with_scratch(p: Pod, mutated: NodeInfo, _name=name,
                                  _scratch=scratch, _funcs=funcs,
                                  _checker=checker) -> bool:
                _scratch[_name] = mutated
                try:
                    # the reference passes the scheduling queue into
                    # selectVictimsOnNode (:985), so victim fitting runs the
                    # nominated-ghost two-pass too — otherwise two preemptors
                    # can nominate the same node with zero victims, live-locking.
                    # The affinity metadata tracks victim mutations
                    # incrementally (select_victims_on_node's checker hooks),
                    # so no invalidation here.
                    ok, _ = pod_fits_on_node_with_nominated(
                        p, mutated, _funcs, nominated_pods_fn,
                        node_infos=_scratch)
                    return ok
                finally:
                    _scratch[_name] = node_infos[_name]
            v = select_victims_on_node(pod, ni, fits_with_scratch, pdbs,
                                       checker=checker)
            if v is not None:
                nodes_to_victims[name] = v
        # extender preemption veto/trim (generic_scheduler.go:347)
        for ext in self.extenders:
            if not getattr(ext.config, "preempt_verb", ""):
                continue
            surviving = ext.process_preemption(
                pod, {n: v.pods for n, v in nodes_to_victims.items()})
            nodes_to_victims = {
                n: Victims(pods=surviving[n],
                           num_pdb_violations=nodes_to_victims[n].num_pdb_violations)
                for n in surviving}
        chosen = pick_one_node_for_preemption(nodes_to_victims)
        if chosen is None:
            return PreemptionResult(None, [], [])
        # lower-priority nominated pods on the chosen node lose their spot
        # (reference: :1185 getLowerPriorityNominatedPods)
        nominated_to_clear = [
            p for p in nominated_pods_fn(chosen) if p.priority < pod.priority]
        node = node_infos[chosen].node
        return PreemptionResult(node, nodes_to_victims[chosen].pods,
                                nominated_to_clear)


# ---------------------------------------------------------------------------
# Nominated-pod-aware fitting (reference: podFitsOnNode :598 two-pass)
# ---------------------------------------------------------------------------
def pod_fits_on_node_with_nominated(
        pod: Pod, node_info: NodeInfo,
        predicate_funcs: dict[str, Callable],
        nominated_pods_fn: Callable[[str], list[Pod]],
        always_check_all: bool = False,
        node_infos: Optional[dict[str, NodeInfo]] = None) -> tuple[bool, list[str]]:
    """Two-pass check: pass 1 with higher/equal-priority nominated pods
    added to the node, pass 2 without; the pod must fit both.

    When `node_infos` is the snapshot the predicate set was built over, the
    ghost-augmented clone is swapped into it for pass 1 so inter-pod
    affinity sees the ghosts (the reference's meta.AddPod, :627)."""
    node_name = node_info.node.name if node_info.node else ""
    nominated = [p for p in nominated_pods_fn(node_name)
                 if p.priority >= pod.priority and p.uid != pod.uid]
    if not nominated:
        return preds.pod_fits_on_node(pod, node_info, predicate_funcs,
                                      always_check_all)
    checker = predicate_funcs.get("_ipa_checker")
    # pass 1: with nominated pods (the affinity metadata takes the ghosts
    # as incremental AddPod deltas, removed again for pass 2 — meta.AddPod
    # semantics, :627)
    ni = node_info.clone()
    ghosts = []
    for p in nominated:
        ghost = copy.copy(p)
        ghost.node_name = node_name
        ni.add_pod(ghost)
        ghosts.append(ghost)
        if checker is not None:
            checker.add_pod(pod, ghost, ni.node)
    swapped = node_infos is not None and node_name in node_infos
    if swapped:
        original = node_infos[node_name]
        node_infos[node_name] = ni
    try:
        fit, reasons = preds.pod_fits_on_node(pod, ni, predicate_funcs,
                                              always_check_all)
    finally:
        if swapped:
            node_infos[node_name] = original
        if checker is not None:
            for ghost in ghosts:
                checker.remove_pod(pod, ghost, ni.node)
    if not fit:
        return fit, reasons
    # pass 2: without
    return preds.pod_fits_on_node(pod, node_info, predicate_funcs,
                                  always_check_all)
