"""Semantic oracle: Filter predicates, exact reference behavior.

Pure-Python transliteration of the *semantics* (not code) of
pkg/scheduler/algorithm/predicates/predicates.go — the parity referee every
JAX kernel is tested against. Each predicate returns (fit, [reason...]).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from kubernetes_tpu.api.types import (
    Pod, Node, Taint,
    get_resource_request, get_container_ports,
    node_selector_terms_match,
    NO_SCHEDULE, NO_EXECUTE,
    TAINT_NODE_UNSCHEDULABLE, find_intolerable_taint,
    RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_PODS, RESOURCE_EPHEMERAL_STORAGE,
    IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT,
)
from kubernetes_tpu.cache.node_info import NodeInfo

# Failure reasons (reference: predicates/error.go)
ERR_NODE_SELECTOR_NOT_MATCH = "NodeSelectorNotMatch"
ERR_POD_NOT_MATCH_HOST_NAME = "PodNotMatchHostName"
ERR_POD_NOT_FITS_HOST_PORTS = "PodNotFitsHostPorts"
ERR_TAINTS_TOLERATIONS_NOT_MATCH = "TaintsTolerationsNotMatch"
ERR_NODE_UNSCHEDULABLE = "NodeUnschedulable"
ERR_NODE_UNKNOWN_CONDITION = "NodeUnknownCondition"
ERR_NODE_NOT_READY = "NodeNotReady"
ERR_NODE_NETWORK_UNAVAILABLE = "NodeNetworkUnavailable"
ERR_NODE_UNDER_MEMORY_PRESSURE = "NodeUnderMemoryPressure"
ERR_NODE_UNDER_DISK_PRESSURE = "NodeUnderDiskPressure"
ERR_NODE_UNDER_PID_PRESSURE = "NodeUnderPIDPressure"
ERR_POD_AFFINITY_NOT_MATCH = "PodAffinityNotMatch"
ERR_POD_AFFINITY_RULES_NOT_MATCH = "PodAffinityRulesNotMatch"
ERR_POD_ANTI_AFFINITY_RULES_NOT_MATCH = "PodAntiAffinityRulesNotMatch"
ERR_EXISTING_PODS_ANTI_AFFINITY_RULES_NOT_MATCH = "ExistingPodsAntiAffinityRulesNotMatch"
ERR_NODE_LABEL_PRESENCE_VIOLATED = "NodeLabelPresenceViolated"
ERR_SERVICE_AFFINITY_VIOLATED = "CheckServiceAffinity"


def insufficient_resource(resource: str) -> str:
    return f"InsufficientResource:{resource}"


# Predicate evaluation order (reference: predicates.go:143-149)
PREDICATE_ORDERING = [
    "CheckNodeCondition", "CheckNodeUnschedulable",
    "GeneralPredicates", "HostName", "PodFitsHostPorts",
    "MatchNodeSelector", "PodFitsResources", "NoDiskConflict",
    "PodToleratesNodeTaints", "PodToleratesNodeNoExecuteTaints",
    "CheckNodeLabelPresence", "CheckServiceAffinity",
    "MaxEBSVolumeCount", "MaxGCEPDVolumeCount", "MaxCSIVolumeCountPred",
    "MaxAzureDiskVolumeCount", "MaxCinderVolumeCount",
    "CheckVolumeBinding", "NoVolumeZoneConflict",
    "CheckNodeMemoryPressure", "CheckNodePIDPressure", "CheckNodeDiskPressure",
    "MatchInterPodAffinity",
]

# Failure reasons that preemption cannot resolve (reference: generic_scheduler.go:65-84)
UNRESOLVABLE_FAILURES = {
    ERR_NODE_SELECTOR_NOT_MATCH,
    ERR_POD_AFFINITY_RULES_NOT_MATCH,
    ERR_POD_NOT_MATCH_HOST_NAME,
    ERR_TAINTS_TOLERATIONS_NOT_MATCH,
    "NodeLabelPresenceViolated",
    ERR_NODE_NOT_READY,
    ERR_NODE_NETWORK_UNAVAILABLE,
    ERR_NODE_UNSCHEDULABLE,
    ERR_NODE_UNKNOWN_CONDITION,
    ERR_NODE_UNDER_MEMORY_PRESSURE,
    ERR_NODE_UNDER_DISK_PRESSURE,
    ERR_NODE_UNDER_PID_PRESSURE,
    # volume placement can't be fixed by evicting pods
    # (generic_scheduler.go:81-83)
    "NoVolumeZoneConflict",
    "VolumeNodeAffinityConflict",
    "VolumeBindingNoMatch",
}


# ---------------------------------------------------------------------------
# Individual predicates
# ---------------------------------------------------------------------------
def pod_fits_resources(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    """Reference: predicates.go:764 PodFitsResources."""
    fails: list[str] = []
    allowed = node_info.allocatable.allowed_pod_number
    if len(node_info.pods) + 1 > allowed:
        fails.append(insufficient_resource(RESOURCE_PODS))

    req = get_resource_request(pod)
    if req.milli_cpu == 0 and req.memory == 0 and req.ephemeral_storage == 0 and not req.scalar:
        return len(fails) == 0, fails

    alloc = node_info.allocatable
    used = node_info.requested
    if alloc.milli_cpu < req.milli_cpu + used.milli_cpu:
        fails.append(insufficient_resource(RESOURCE_CPU))
    if alloc.memory < req.memory + used.memory:
        fails.append(insufficient_resource(RESOURCE_MEMORY))
    if alloc.ephemeral_storage < req.ephemeral_storage + used.ephemeral_storage:
        fails.append(insufficient_resource(RESOURCE_EPHEMERAL_STORAGE))
    for name, q in req.scalar.items():
        if alloc.scalar.get(name, 0) < q + used.scalar.get(name, 0):
            fails.append(insufficient_resource(name))
    return len(fails) == 0, fails


def pod_matches_node_selector_and_affinity(pod: Pod, node: Node) -> bool:
    """Reference: predicates.go:854 podMatchesNodeSelectorAndAffinityTerms."""
    if pod.node_selector:
        for k, v in pod.node_selector.items():
            if node.labels.get(k) != v:
                return False
    affinity = pod.affinity
    if affinity is not None and affinity.node_affinity is not None:
        na = affinity.node_affinity
        if na.required is None:
            return True
        return node_selector_terms_match(na.required, node.labels)
    return True


def pod_match_node_selector(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    if node_info.node is None:
        return False, [ERR_NODE_UNKNOWN_CONDITION]
    if pod_matches_node_selector_and_affinity(pod, node_info.node):
        return True, []
    return False, [ERR_NODE_SELECTOR_NOT_MATCH]


def pod_fits_host(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    if not pod.node_name:
        return True, []
    if node_info.node is None:
        return False, [ERR_NODE_UNKNOWN_CONDITION]
    if pod.node_name == node_info.node.name:
        return True, []
    return False, [ERR_POD_NOT_MATCH_HOST_NAME]


def pod_fits_host_ports(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    want = get_container_ports(pod)
    if not want:
        return True, []
    for p in want:
        if node_info.used_ports.check_conflict(p.host_ip, p.protocol, p.host_port):
            return False, [ERR_POD_NOT_FITS_HOST_PORTS]
    return True, []


def general_predicates(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    """Reference: predicates.go:1112 — resources + host + ports + selector,
    accumulating all failures (no short-circuit inside GeneralPredicates)."""
    fails: list[str] = []
    for pred in (pod_fits_resources, pod_fits_host, pod_fits_host_ports, pod_match_node_selector):
        fit, reasons = pred(pod, node_info)
        if not fit:
            fails.extend(reasons)
    return len(fails) == 0, fails


def check_node_unschedulable(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    """Reference: predicates.go:1511."""
    if node_info.node is None:
        return False, [ERR_NODE_UNKNOWN_CONDITION]
    tolerates = any(
        t.tolerates(Taint(key=TAINT_NODE_UNSCHEDULABLE, effect=NO_SCHEDULE))
        for t in pod.tolerations
    )
    if node_info.node.unschedulable and not tolerates:
        return False, [ERR_NODE_UNSCHEDULABLE]
    return True, []


def pod_tolerates_node_taints(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    """Reference: predicates.go:1531 — NoSchedule + NoExecute taints."""
    if node_info.node is None:
        return False, [ERR_NODE_UNKNOWN_CONDITION]
    bad = find_intolerable_taint(
        node_info.taints, pod.tolerations,
        lambda t: t.effect in (NO_SCHEDULE, NO_EXECUTE))
    if bad is None:
        return True, []
    return False, [ERR_TAINTS_TOLERATIONS_NOT_MATCH]


def pod_tolerates_node_no_execute_taints(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    bad = find_intolerable_taint(node_info.taints, pod.tolerations,
                                 lambda t: t.effect == NO_EXECUTE)
    if bad is None:
        return True, []
    return False, [ERR_TAINTS_TOLERATIONS_NOT_MATCH]


def _condition(node: Optional[Node], ctype: str) -> str:
    if node is None:
        return "Unknown"
    for c in node.conditions:
        if c.type == ctype:
            return c.status
    return "Unknown"


def check_node_condition(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    """Reference: predicates.go:1610 — Ready must be True, NetworkUnavailable
    must be False; node.Spec.Unschedulable also fails here."""
    if node_info.node is None:
        return False, [ERR_NODE_UNKNOWN_CONDITION]
    reasons = []
    for c in node_info.node.conditions:
        if c.type == "Ready" and c.status != "True":
            reasons.append(ERR_NODE_NOT_READY)
        elif c.type == "NetworkUnavailable" and c.status != "False":
            reasons.append(ERR_NODE_NETWORK_UNAVAILABLE)
    if node_info.node.unschedulable:
        reasons.append(ERR_NODE_UNSCHEDULABLE)
    return len(reasons) == 0, reasons


def is_pod_best_effort(pod: Pod) -> bool:
    """QoS BestEffort — no container has any request (limits are out of our
    pruned model; requests-only matches the scheduler-relevant behavior)."""
    for c in list(pod.containers) + list(pod.init_containers):
        if c.requests:
            return False
    return True


def check_node_memory_pressure(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    if not is_pod_best_effort(pod):
        return True, []
    if _condition(node_info.node, "MemoryPressure") == "True":
        return False, [ERR_NODE_UNDER_MEMORY_PRESSURE]
    return True, []


def check_node_disk_pressure(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    if _condition(node_info.node, "DiskPressure") == "True":
        return False, [ERR_NODE_UNDER_DISK_PRESSURE]
    return True, []


def check_node_pid_pressure(pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
    if _condition(node_info.node, "PIDPressure") == "True":
        return False, [ERR_NODE_UNDER_PID_PRESSURE]
    return True, []


# ---------------------------------------------------------------------------
# Inter-pod affinity (reference: predicates.go:1196-1500)
# ---------------------------------------------------------------------------
def term_namespaces(defining_pod: Pod, term) -> tuple[str, ...]:
    """Reference: priorities/util.GetNamespacesFromPodAffinityTerm."""
    return term.namespaces if term.namespaces else (defining_pod.namespace,)


def pod_matches_term_props(target: Pod, defining_pod: Pod, term) -> bool:
    """Namespace + label selector match (PodMatchesTermsNamespaceAndSelector)."""
    if target.namespace not in term_namespaces(defining_pod, term):
        return False
    if term.label_selector is None:
        return False
    return term.label_selector.matches(target.labels)


def nodes_same_topology(a: Optional[Node], b: Optional[Node], key: str) -> bool:
    """Reference: priorities/util.NodesHaveSameTopologyKey."""
    if a is None or b is None or not key:
        return False
    return key in a.labels and key in b.labels and a.labels[key] == b.labels[key]


# ---------------------------------------------------------------------------
# Vectorized selector matching over a columnar pod table
# ---------------------------------------------------------------------------
# The table (ops.node_state.PodTable, duck-typed here to keep the oracle
# import-free of the device stack) dictionary-encodes every snapshot pod's
# namespace and label pairs:
#   ns_id[P] i32; key_ids/val_ids[P, L] i32 (-1 padding);
#   ns_vocab/key_vocab/val_vocab: str -> id; val_ints[V] f64 (parsed integer
#   value of each vocab entry, NaN when unparseable — Gt/Lt support).
# These are the SHARED vectorized twins of _selector_matches /
# LabelSelector.matches / pod_matches_term_props: one boolean mask over the
# existing-pod axis instead of a Python call per pod. Every mask must stay
# bit-identical to a row-by-row scalar evaluation — the encoder parity
# fuzzes enforce it.


def _pair_mask(table, k: str, v: str) -> np.ndarray:
    """[P] bool: pod labels contain the exact (k, v) pair."""
    kid = table.key_vocab.get(k)
    vid = table.val_vocab.get(v)
    if kid is None or vid is None:
        return np.zeros(len(table.pods), dtype=bool)
    return ((table.key_ids == kid) & (table.val_ids == vid)).any(axis=1)


def _requirement_mask(table, req) -> np.ndarray:
    """Vectorized twin of Requirement.matches over the pod axis."""
    n = len(table.pods)
    kid = table.key_vocab.get(req.key)
    if req.op == IN:
        if kid is None:
            return np.zeros(n, dtype=bool)
        vids = [table.val_vocab[v] for v in req.values
                if v in table.val_vocab]
        if not vids:
            return np.zeros(n, dtype=bool)
        return ((table.key_ids == kid)
                & np.isin(table.val_ids, vids)).any(axis=1)
    if req.op == NOT_IN:
        # scalar twin: matches when the key is absent OR the value differs
        if kid is None:
            return np.ones(n, dtype=bool)
        vids = [table.val_vocab[v] for v in req.values
                if v in table.val_vocab]
        if not vids:
            return np.ones(n, dtype=bool)
        return ~((table.key_ids == kid)
                 & np.isin(table.val_ids, vids)).any(axis=1)
    if req.op == EXISTS:
        if kid is None:
            return np.zeros(n, dtype=bool)
        return (table.key_ids == kid).any(axis=1)
    if req.op == DOES_NOT_EXIST:
        if kid is None:
            return np.ones(n, dtype=bool)
        return ~(table.key_ids == kid).any(axis=1)
    if req.op in (GT, LT):
        # both sides must parse as integers (Requirement.matches)
        if kid is None:
            return np.zeros(n, dtype=bool)
        try:
            rv = int(req.values[0])
        except (ValueError, IndexError):
            return np.zeros(n, dtype=bool)
        has = table.key_ids == kid
        # label keys are unique per pod, so at most one lane carries the key
        vsel = np.where(has, table.val_ids, -1).max(axis=1)
        vals = np.full(n, np.nan)
        ok = vsel >= 0
        vals[ok] = table.val_ints[vsel[ok]]
        with np.errstate(invalid="ignore"):
            return vals > rv if req.op == GT else vals < rv
    raise ValueError(f"unknown selector op {req.op!r}")


def selector_match_mask(selector, table) -> np.ndarray:
    """[P] bool twin of priorities._selector_matches: dict selectors match
    by exact pairs; LabelSelector adds match_expressions."""
    n = len(table.pods)
    m = np.ones(n, dtype=bool)
    if isinstance(selector, dict):
        for k, v in selector.items():
            m &= _pair_mask(table, k, v)
        return m
    for k, v in selector.match_labels:
        m &= _pair_mask(table, k, v)
    for req in selector.match_expressions:
        m &= _requirement_mask(table, req)
    return m


def pod_matches_term_props_mask(defining_pod: Pod, term, table) -> np.ndarray:
    """[P] bool twin of pod_matches_term_props(target, defining_pod, term)
    evaluated for every table row as `target` at once."""
    n = len(table.pods)
    if term.label_selector is None:
        return np.zeros(n, dtype=bool)
    ns_ids = [table.ns_vocab[x] for x in term_namespaces(defining_pod, term)
              if x in table.ns_vocab]
    if not ns_ids:
        return np.zeros(n, dtype=bool)
    m = np.isin(table.ns_id, ns_ids)
    return m & selector_match_mask(term.label_selector, table)


def pod_matches_any_term_mask(defining_pod: Pod, terms, table) -> np.ndarray:
    """[P] bool: table rows matching ANY of `defining_pod`'s terms — the
    vectorized twin of `any(pod_matches_term_props(p, defining_pod, t) for
    t in terms)` per row. The preemption inertness gate uses this to find
    potential victims whose removal would change the incoming pod's
    (anti-)affinity masks."""
    m = np.zeros(len(table.pods), dtype=bool)
    for term in terms:
        m |= pod_matches_term_props_mask(defining_pod, term, table)
    return m


class InterPodAffinityChecker:
    """MatchInterPodAffinity over a full snapshot {node name -> NodeInfo}.

    Like the reference's predicate metadata (predicates/metadata.go:71), the
    cluster-wide scans run once per incoming pod, producing topology-pair
    COUNTS; the per-node check is then O(terms) label lookups. This is also
    the shape the device kernel consumes: per-term topology-value sets
    become dictionary-encoded masks over the node axis.

    Counts (not sets) make the metadata INCREMENTAL: preemption's reprieve
    loop and the nominated-ghost two-pass mutate one pod at a time and call
    add_pod/remove_pod — the reference's meta.AddPod/RemovePod
    (metadata.go:210/:239) — instead of recomputing the cluster scan per
    fit check.
    """

    def __init__(self, node_infos: dict[str, NodeInfo]):
        self.node_infos = node_infos
        self._meta_uid: Optional[str] = None
        self._meta = None
        # optional columnar acceleration (set_table_source): the metadata's
        # whole-cluster term scans then run as one mask over the pod axis
        self._table_fn = None
        self._topo_fn = None

    def set_table_source(self, table_fn, topo_fn) -> None:
        """Enable vectorized metadata scans: `table_fn()` returns the
        columnar pod table, `topo_fn(key)` the per-node dictionary-encoded
        label values (ids[N] i32 over the table's node axis, value->id
        vocab). Results are bit-identical to the scalar scan."""
        self._table_fn = table_fn
        self._topo_fn = topo_fn

    def invalidate(self) -> None:
        """Drop the per-pod metadata cache (whole-snapshot change, or a
        mutation the caller can't express as add_pod/remove_pod)."""
        self._meta_uid = None
        self._meta = None

    # -- incremental updates (metadata.go:210 RemovePod / :239 AddPod) -------
    def _apply_delta(self, target: Pod, other: Pod,
                     node: Optional[Node], sign: int) -> None:
        if self._meta is None or self._meta_uid != target.uid \
                or node is None or other.uid == target.uid:
            return
        violating, aff_terms, anti_terms = self._meta
        oa = other.affinity
        if oa is not None and oa.pod_anti_affinity is not None:
            for term in oa.pod_anti_affinity.required:
                if term.topology_key in node.labels and \
                        pod_matches_term_props(target, other, term):
                    k = (term.topology_key, node.labels[term.topology_key])
                    violating[k] = violating.get(k, 0) + sign
                    if violating[k] <= 0:
                        del violating[k]
        for term, values, total in aff_terms + anti_terms:
            if pod_matches_term_props(other, target, term):
                total[0] += sign
                if term.topology_key in node.labels:
                    v = node.labels[term.topology_key]
                    values[v] = values.get(v, 0) + sign
                    if values[v] <= 0:
                        del values[v]

    def add_pod(self, target: Pod, other: Pod, node: Optional[Node]) -> None:
        self._apply_delta(target, other, node, 1)

    def remove_pod(self, target: Pod, other: Pod,
                   node: Optional[Node]) -> None:
        self._apply_delta(target, other, node, -1)

    def _node_of(self, pod: Pod) -> Optional[Node]:
        ni = self.node_infos.get(pod.node_name)
        return ni.node if ni else None

    def _metadata(self, pod: Pod):
        if self._meta_uid == pod.uid:
            return self._meta
        # (a) Existing pods' required anti-affinity: count of entries per
        # (topologyKey, value) the incoming pod would violate.
        violating: dict[tuple[str, str], int] = {}
        for ni in self.node_infos.values():
            for existing in ni.pods_with_affinity:
                ea = existing.affinity
                if ea is None or ea.pod_anti_affinity is None:
                    continue
                e_node = self._node_of(existing)
                if e_node is None:
                    continue
                for term in ea.pod_anti_affinity.required:
                    if term.topology_key in e_node.labels and \
                            pod_matches_term_props(pod, existing, term):
                        k = (term.topology_key,
                             e_node.labels[term.topology_key])
                        violating[k] = violating.get(k, 0) + 1

        # (b) The pod's own required terms: per term, matching-pod counts by
        # topology value plus the total match count ([mutable] so deltas
        # apply in place).
        def term_values(term) -> tuple[dict[str, int], list[int]]:
            if self._table_fn is not None:
                return self._term_values_vec(pod, term)
            values: dict[str, int] = {}
            total = [0]
            for ni in self.node_infos.values():
                for existing in ni.pods:
                    if pod_matches_term_props(existing, pod, term):
                        total[0] += 1
                        e_node = self._node_of(existing)
                        if e_node is not None and term.topology_key in e_node.labels:
                            v = e_node.labels[term.topology_key]
                            values[v] = values.get(v, 0) + 1
            return values, total

        a = pod.affinity
        aff_terms = []
        anti_terms = []
        if a is not None and a.pod_affinity is not None:
            for term in a.pod_affinity.required:
                aff_terms.append((term, *term_values(term)))
        if a is not None and a.pod_anti_affinity is not None:
            for term in a.pod_anti_affinity.required:
                anti_terms.append((term, *term_values(term)))
        self._meta = (violating, aff_terms, anti_terms)
        self._meta_uid = pod.uid
        return self._meta

    def _term_values_vec(self, pod: Pod, term) -> tuple[dict[str, int], list[int]]:
        """Columnar twin of the scalar term_values scan: one mask over the
        pod axis, counts grouped by the matching pods' node label values."""
        table = self._table_fn()
        m = pod_matches_term_props_mask(pod, term, table)
        total = [int(np.count_nonzero(m))]
        values: dict[str, int] = {}
        if total[0]:
            ids, vocab = self._topo_fn(term.topology_key)
            rows = table.name_row[m]
            rows = rows[rows >= 0]          # node_name outside the snapshot
            if rows.size:
                vids = ids[rows]
                vids = vids[vids >= 0]      # node object/label absent
                if vids.size:
                    cnt = np.bincount(vids, minlength=len(vocab))
                    for v, vid in vocab.items():
                        c = int(cnt[vid])
                        if c:
                            values[v] = c
        return values, total

    def check(self, pod: Pod, node_info: NodeInfo) -> tuple[bool, list[str]]:
        node = node_info.node
        labels = node.labels if node is not None else {}
        violating, aff_terms, anti_terms = self._metadata(pod)
        # 1. Existing pods' required anti-affinity must not be violated.
        for key, value in violating:
            if labels.get(key) == value:
                return False, [ERR_POD_AFFINITY_NOT_MATCH,
                               ERR_EXISTING_PODS_ANTI_AFFINITY_RULES_NOT_MATCH]
        # 2. The pod's own required affinity/anti-affinity.
        for term, values, total in aff_terms:
            if labels.get(term.topology_key) not in values:
                # First-pod-in-cluster rule (reference: predicates.go:1454-1464):
                # if no pod anywhere matches the term, the term is waived when
                # the pod matches its own term (it would otherwise never schedule).
                if total[0] == 0 and pod_matches_term_props(pod, pod, term):
                    continue
                return False, [ERR_POD_AFFINITY_NOT_MATCH,
                               ERR_POD_AFFINITY_RULES_NOT_MATCH]
        for term, values, _total in anti_terms:
            if labels.get(term.topology_key) in values:
                return False, [ERR_POD_AFFINITY_NOT_MATCH,
                               ERR_POD_ANTI_AFFINITY_RULES_NOT_MATCH]
        return True, []


# ---------------------------------------------------------------------------
# Policy-configured predicates (factory.go:204 RegisterCustomFitPredicate)
# ---------------------------------------------------------------------------
def make_node_label_presence(labels: list[str], presence: bool) -> Callable:
    """Reference: predicates.go:943 CheckNodeLabelPresence — all the listed
    labels must exist on the node (presence=True) or none may
    (presence=False), regardless of value."""
    labels = list(labels)

    def check_node_label_presence(pod: Pod, node_info: NodeInfo
                                  ) -> tuple[bool, list[str]]:
        node = node_info.node
        if node is None:
            return False, []
        for label in labels:
            exists = label in node.labels
            if (exists and not presence) or (not exists and presence):
                return False, [ERR_NODE_LABEL_PRESENCE_VIOLATED]
        return True, []

    return check_node_label_presence


def make_service_affinity(labels: list[str],
                          node_infos: dict[str, NodeInfo],
                          services_fn: Callable) -> Callable:
    """Reference: predicates.go:1030 checkServiceAffinity — pods of the same
    service co-locate on nodes agreeing on the listed label values. Missing
    constraints are reverse-engineered: if the pod's nodeSelector doesn't pin
    a listed label and some already-scheduled pod of the same service exists,
    that pod's NODE supplies the missing values (metadata producer
    predicates.go:970: services selecting the pod + same-namespace pods
    matching the pod's own labels)."""
    labels = list(labels)

    def check_service_affinity(pod: Pod, node_info: NodeInfo
                               ) -> tuple[bool, list[str]]:
        node = node_info.node
        if node is None:
            return False, []
        # metadata: services selecting this pod; same-namespace pods whose
        # labels are a superset of this pod's labels
        services = [s for s in services_fn()
                    if s.namespace == pod.namespace and s.selector
                    and all(pod.labels.get(k) == v
                            for k, v in s.selector.items())]
        matching = [p for ni in node_infos.values() for p in ni.pods
                    if p.namespace == pod.namespace
                    and all(p.labels.get(k) == v
                            for k, v in pod.labels.items())]
        # FilterOutPods (node_info.go:656): keep pods not on this node (and
        # this-node pods present in the NodeInfo, which ours always are)
        this = node.name
        filtered = [p for p in matching
                    if p.node_name != this or any(q is p for q in node_info.pods)]
        affinity_labels = {l: pod.node_selector[l] for l in labels
                           if l in pod.node_selector}
        if len(labels) > len(affinity_labels) and services and filtered:
            first_ni = node_infos.get(filtered[0].node_name)
            if first_ni is not None and first_ni.node is not None:
                src = first_ni.node.labels
                for l in labels:
                    if l not in affinity_labels and l in src:
                        affinity_labels[l] = src[l]
        if all(node.labels.get(k) == v for k, v in affinity_labels.items()):
            return True, []
        return False, [ERR_SERVICE_AFFINITY_VIOLATED]

    return check_service_affinity


# ---------------------------------------------------------------------------
# Driver: run predicates in reference order with short-circuit
# ---------------------------------------------------------------------------
def default_predicate_set(node_infos: dict[str, NodeInfo],
                          taint_nodes_by_condition: bool = True,
                          volume_listers=None,
                          volume_binder=None) -> dict[str, Callable]:
    """The DefaultProvider predicate set (reference: defaults.go:40), keyed by
    name; evaluated in PREDICATE_ORDERING.

    TaintNodesByCondition is Beta/default-on in this snapshot
    (kube_features.go:468), so the effective default set drops the
    condition/pressure predicates and adds the mandatory
    PodToleratesNodeTaints + CheckNodeUnschedulable (defaults.go:60-90).
    Pass taint_nodes_by_condition=False for the pre-gate behavior.

    Volume-topology predicates (NoVolumeZoneConflict, Max*VolumeCount,
    NoDiskConflict, CheckVolumeBinding) are registered as always-fit until
    the volume model lands."""
    ipa = InterPodAffinityChecker(node_infos)
    always_fit = lambda pod, ni: (True, [])
    preds = {
        # handle for callers that mutate snapshot state mid-pod; not a
        # predicate (pod_fits_on_node iterates PREDICATE_ORDERING only)
        "_ipa_checker": ipa,
        "GeneralPredicates": general_predicates,
        "PodToleratesNodeTaints": pod_tolerates_node_taints,
        "MatchInterPodAffinity": ipa.check,
    }
    if volume_listers is not None:
        from kubernetes_tpu.oracle.volumes import make_volume_predicates
        preds.update(make_volume_predicates(volume_listers, volume_binder))
    else:
        for name in ("NoDiskConflict", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
                     "MaxAzureDiskVolumeCount", "MaxCinderVolumeCount",
                     "MaxCSIVolumeCountPred",
                     "CheckVolumeBinding", "NoVolumeZoneConflict"):
            preds[name] = always_fit
    if taint_nodes_by_condition:
        preds["CheckNodeUnschedulable"] = check_node_unschedulable
    else:
        preds["CheckNodeCondition"] = check_node_condition
        preds["CheckNodeMemoryPressure"] = check_node_memory_pressure
        preds["CheckNodeDiskPressure"] = check_node_disk_pressure
        preds["CheckNodePIDPressure"] = check_node_pid_pressure
    return preds


def pod_fits_on_node(pod: Pod, node_info: NodeInfo,
                     predicate_funcs: dict[str, Callable],
                     always_check_all: bool = False) -> tuple[bool, list[str]]:
    """One pass of podFitsOnNode (reference: generic_scheduler.go:598) without
    nominated-pod handling (the caller layers that on)."""
    failed: list[str] = []
    for key in PREDICATE_ORDERING:
        pred = predicate_funcs.get(key)
        if pred is None:
            continue
        fit, reasons = pred(pod, node_info)
        if not fit:
            failed.extend(reasons)
            if not always_check_all:
                break
    return len(failed) == 0, failed
