"""Semantic oracle: Score priorities, exact reference integer/float behavior.

Pure-Python transliteration of the semantics of
pkg/scheduler/algorithm/priorities/ — Map/Reduce over nodes, integer scores
0-10 (MaxPriority), weighted sum done by the caller. Float blends
(BalancedAllocation, SelectorSpread zone weighting, InterPodAffinity
min-max normalize) use IEEE double exactly as the Go code does.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.api.types import (
    Pod, Node, Service, ReplicaSet, get_pod_nonzero_requests, get_zone_key,
    PREFER_NO_SCHEDULE, tolerations_tolerate_taint,
)
from kubernetes_tpu.cache.node_info import NodeInfo, normalized_image_name
from kubernetes_tpu.oracle.predicates import (
    pod_matches_term_props, nodes_same_topology,
)

MAX_PRIORITY = 10  # reference: pkg/scheduler/api/types.go:35

# ---------------------------------------------------------------------------
# Resource-allocation scorers (reference: resource_allocation.go:39 —
# all of them consume the pod's *nonzero* request + node NonZeroRequest)
# ---------------------------------------------------------------------------


def _pod_plus_node_nonzero(pod: Pod, ni: NodeInfo) -> tuple[int, int]:
    cpu, mem = get_pod_nonzero_requests(pod)
    return cpu + ni.nonzero_cpu, mem + ni.nonzero_mem


def least_requested_score(requested: int, capacity: int) -> int:
    """Reference: least_requested.go:44 — (cap-req)*10/cap, int64 truncation."""
    if capacity == 0 or requested > capacity:
        return 0
    return ((capacity - requested) * MAX_PRIORITY) // capacity


def most_requested_score(requested: int, capacity: int) -> int:
    """Reference: most_requested.go:46."""
    if capacity == 0 or requested > capacity:
        return 0
    return (requested * MAX_PRIORITY) // capacity


def least_requested_map(pod: Pod, ni: NodeInfo) -> int:
    cpu, mem = _pod_plus_node_nonzero(pod, ni)
    return (least_requested_score(cpu, ni.allocatable.milli_cpu)
            + least_requested_score(mem, ni.allocatable.memory)) // 2


def most_requested_map(pod: Pod, ni: NodeInfo) -> int:
    cpu, mem = _pod_plus_node_nonzero(pod, ni)
    return (most_requested_score(cpu, ni.allocatable.milli_cpu)
            + most_requested_score(mem, ni.allocatable.memory)) // 2


def balanced_allocation_map(pod: Pod, ni: NodeInfo) -> int:
    """Reference: balanced_resource_allocation.go:41 — float64 fractions,
    int64 truncation of (1-|cpuF-memF|)*10. Under the
    BalanceAttachedNodeVolumes gate with per-cycle transient volume counts
    (written by the Max*VolumeCount predicates), the three-fraction variance
    form applies instead (balanced_resource_allocation.go:44-58)."""
    cpu, mem = _pod_plus_node_nonzero(pod, ni)
    cpu_frac = _fraction(cpu, ni.allocatable.milli_cpu)
    mem_frac = _fraction(mem, ni.allocatable.memory)
    from kubernetes_tpu.utils import features
    if features.enabled("BalanceAttachedNodeVolumes") \
            and ni.transient_allocatable_volumes is not None \
            and ni.transient_allocatable_volumes > 0:
        vol_frac = (ni.transient_requested_volumes
                    / ni.transient_allocatable_volumes)
        if cpu_frac >= 1 or mem_frac >= 1 or vol_frac >= 1:
            return 0
        mean = (cpu_frac + mem_frac + vol_frac) / 3.0
        variance = ((cpu_frac - mean) ** 2 + (mem_frac - mean) ** 2
                    + (vol_frac - mean) ** 2) / 3.0
        return int((1 - variance) * float(MAX_PRIORITY))
    if cpu_frac >= 1 or mem_frac >= 1:
        return 0
    diff = abs(cpu_frac - mem_frac)
    return int((1 - diff) * float(MAX_PRIORITY))


def resource_limits_map(pod: Pod, ni: NodeInfo) -> int:
    """Reference: resource_limits.go:36 ResourceLimitsPriorityMap — score 1
    when the node's allocatable satisfies the pod's cpu OR memory limit
    (tie-break nudge toward nodes that can honor limits), else 0."""
    from kubernetes_tpu.api.types import get_resource_limits
    limits = get_resource_limits(pod)
    alloc = ni.allocatable

    def compute(limit: int, allocatable: int) -> int:
        return 1 if limit != 0 and allocatable != 0 and limit <= allocatable \
            else 0

    return 1 if (compute(limits.milli_cpu, alloc.milli_cpu) == 1
                 or compute(limits.memory, alloc.memory) == 1) else 0


def _fraction(req: int, cap: int) -> float:
    if cap == 0:
        return 1.0
    return req / cap


# Requested-to-capacity-ratio broken-linear (reference: requested_to_capacity_ratio.go)
DEFAULT_RTCR_SHAPE: tuple[tuple[int, int], ...] = ((0, 10), (100, 0))


def _trunc_div(a: int, b: int) -> int:
    """Go int64 division truncates toward zero; Python // floors."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def broken_linear(shape: tuple[tuple[int, int], ...], p: int) -> int:
    """Reference: buildBrokenLinearFunction :128 — integer segment
    interpolation with Go's truncate-toward-zero division."""
    for i, (u, s) in enumerate(shape):
        if p <= u:
            if i == 0:
                return shape[0][1]
            u0, s0 = shape[i - 1]
            return s0 + _trunc_div((s - s0) * (p - u0), u - u0)
    return shape[-1][1]


def make_rtcr_map(shape: tuple[tuple[int, int], ...] = DEFAULT_RTCR_SHAPE
                  ) -> Callable[[Pod, NodeInfo], int]:
    def resource_score(requested: int, capacity: int) -> int:
        if capacity == 0 or requested > capacity:
            return broken_linear(shape, 100)
        return broken_linear(shape, 100 - (capacity - requested) * 100 // capacity)

    def rtcr_map(pod: Pod, ni: NodeInfo) -> int:
        cpu, mem = _pod_plus_node_nonzero(pod, ni)
        return (resource_score(cpu, ni.allocatable.milli_cpu)
                + resource_score(mem, ni.allocatable.memory)) // 2

    return rtcr_map


# ---------------------------------------------------------------------------
# Node affinity (reference: node_affinity.go:34 + NormalizeReduce(10, false))
# ---------------------------------------------------------------------------
def node_affinity_map(pod: Pod, ni: NodeInfo) -> int:
    affinity = pod.affinity
    count = 0
    if affinity is not None and affinity.node_affinity is not None:
        for term in affinity.node_affinity.preferred:
            if term.weight == 0:
                continue
            if term.preference.match_expressions and term.preference.matches(ni.node.labels):
                count += term.weight
    return count


def normalize_reduce(max_priority: int, reverse: bool,
                     scores: list[int]) -> list[int]:
    """Reference: reduce.go:28 NormalizeReduce."""
    max_count = max(scores) if scores else 0
    if max_count == 0:
        return [max_priority] * len(scores) if reverse else list(scores)
    out = []
    for s in scores:
        s = max_priority * s // max_count
        if reverse:
            s = max_priority - s
        out.append(s)
    return out


# ---------------------------------------------------------------------------
# Taint/toleration (reference: taint_toleration.go + NormalizeReduce(10, true))
# ---------------------------------------------------------------------------
def taint_toleration_map(pod: Pod, ni: NodeInfo) -> int:
    tolerations = [t for t in pod.tolerations
                   if not t.effect or t.effect == PREFER_NO_SCHEDULE]
    count = 0
    for taint in ni.taints:
        if taint.effect != PREFER_NO_SCHEDULE:
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            count += 1
    return count


# ---------------------------------------------------------------------------
# Image locality (reference: image_locality.go)
# ---------------------------------------------------------------------------
MB = 1024 * 1024
IMAGE_MIN_THRESHOLD = 23 * MB
IMAGE_MAX_THRESHOLD = 1000 * MB


def image_locality_map(pod: Pod, ni: NodeInfo, total_num_nodes: int) -> int:
    total = 0
    for c in pod.containers:
        state = ni.image_states.get(normalized_image_name(c.image))
        if state is not None:
            spread = state.num_nodes / total_num_nodes
            total += int(state.size_bytes * spread)
    s = min(max(total, IMAGE_MIN_THRESHOLD), IMAGE_MAX_THRESHOLD)
    return MAX_PRIORITY * (s - IMAGE_MIN_THRESHOLD) // (IMAGE_MAX_THRESHOLD - IMAGE_MIN_THRESHOLD)


# ---------------------------------------------------------------------------
# NodePreferAvoidPods (reference: node_prefer_avoid_pods.go, weight 10000)
# ---------------------------------------------------------------------------
def node_prefer_avoid_pods_map(pod: Pod, ni: NodeInfo) -> int:
    owner = pod.owner_ref  # (kind, name, uid) controller ref
    if owner is None or owner[0] not in ("ReplicationController", "ReplicaSet"):
        return MAX_PRIORITY
    return 0 if owner[2] in ni.node.prefer_avoid_pod_uids else MAX_PRIORITY


# ---------------------------------------------------------------------------
# Selector spreading (reference: selector_spreading.go)
# ---------------------------------------------------------------------------
ZONE_WEIGHTING = 2.0 / 3.0


def get_selectors(pod: Pod, services: list[Service],
                  replicasets: list[ReplicaSet]) -> list:
    """Selectors of services / RC / RS / STS that select this pod
    (reference: selector_spreading.go getSelectors)."""
    selectors = []
    for svc in services:
        if svc.namespace != pod.namespace or not svc.selector:
            continue
        if all(pod.labels.get(k) == v for k, v in svc.selector.items()):
            selectors.append(dict(svc.selector))
    for rs in replicasets:
        if rs.namespace != pod.namespace or rs.selector is None:
            continue
        if rs.selector.matches(pod.labels):
            selectors.append(rs.selector)
    return selectors


def _selector_matches(selector, labels: dict[str, str]) -> bool:
    if isinstance(selector, dict):
        return all(labels.get(k) == v for k, v in selector.items())
    return selector.matches(labels)


def selector_spread_map(pod: Pod, ni: NodeInfo, selectors: list) -> int:
    """Count of existing same-namespace pods on the node matching ALL selectors."""
    if not ni.pods or not selectors:
        return 0
    count = 0
    for existing in ni.pods:
        if existing.namespace != pod.namespace or existing.deleted:
            continue
        if all(_selector_matches(sel, existing.labels) for sel in selectors):
            count += 1
    return count


def selector_spread_reduce(node_infos: dict[str, NodeInfo],
                           hosts: list[str], counts: list[int]) -> list[int]:
    """Reference: CalculateSpreadPriorityReduce — node+zone blend 1/3:2/3."""
    max_by_node = max(counts) if counts else 0
    counts_by_zone: dict[str, int] = {}
    for host, c in zip(hosts, counts):
        zone = get_zone_key(node_infos[host].node)
        if zone:
            counts_by_zone[zone] = counts_by_zone.get(zone, 0) + c
    max_by_zone = max(counts_by_zone.values()) if counts_by_zone else 0
    have_zones = len(counts_by_zone) != 0

    out = []
    for host, c in zip(hosts, counts):
        f_score = float(MAX_PRIORITY)
        if max_by_node > 0:
            f_score = float(MAX_PRIORITY) * ((max_by_node - c) / max_by_node)
        if have_zones:
            zone = get_zone_key(node_infos[host].node)
            if zone:
                zone_score = float(MAX_PRIORITY)
                if max_by_zone > 0:
                    zone_score = float(MAX_PRIORITY) * ((max_by_zone - counts_by_zone[zone]) / max_by_zone)
                f_score = (f_score * (1.0 - ZONE_WEIGHTING)) + (ZONE_WEIGHTING * zone_score)
        out.append(int(f_score))
    return out


# ---------------------------------------------------------------------------
# Inter-pod affinity priority (reference: interpod_affinity.go:116)
# ---------------------------------------------------------------------------
DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1  # apis/config HardPodAffinitySymmetricWeight default


def interpod_affinity_priority(pod: Pod, node_infos: dict[str, NodeInfo],
                               nodes: list[Node],
                               hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT
                               ) -> list[int]:
    """Function-style priority over the filtered `nodes` list; min-max
    normalized to 0-10 with 0 included in the min/max fold."""
    a = pod.affinity
    has_aff = a is not None and a.pod_affinity is not None
    has_anti = a is not None and a.pod_anti_affinity is not None

    counts: dict[str, int] = {}
    tracked: set[str] = set()
    for name, ni in node_infos.items():
        if has_aff or has_anti or ni.pods_with_affinity:
            counts[name] = 0
            tracked.add(name)

    def node_of(p: Pod) -> Optional[Node]:
        ni = node_infos.get(p.node_name)
        return ni.node if ni else None

    def process_term(term, defining: Pod, to_check: Pod, fixed_node: Node, weight: int):
        if fixed_node is None:
            return
        if pod_matches_term_props(to_check, defining, term):
            for name in tracked:
                n = node_infos[name].node
                if n is not None and nodes_same_topology(n, fixed_node, term.topology_key):
                    counts[name] += weight

    def process_pod(existing: Pod):
        existing_node = node_of(existing)
        ea = existing.affinity
        e_has_aff = ea is not None and ea.pod_affinity is not None
        e_has_anti = ea is not None and ea.pod_anti_affinity is not None
        if has_aff:
            for wt in a.pod_affinity.preferred:
                process_term(wt.term, pod, existing, existing_node, wt.weight)
        if has_anti:
            for wt in a.pod_anti_affinity.preferred:
                process_term(wt.term, pod, existing, existing_node, -wt.weight)
        if e_has_aff:
            if hard_pod_affinity_weight > 0:
                for term in ea.pod_affinity.required:
                    process_term(term, existing, pod, existing_node, hard_pod_affinity_weight)
            for wt in ea.pod_affinity.preferred:
                process_term(wt.term, existing, pod, existing_node, wt.weight)
        if e_has_anti:
            for wt in ea.pod_anti_affinity.preferred:
                process_term(wt.term, existing, pod, existing_node, -wt.weight)

    for ni in node_infos.values():
        if ni.node is None:
            continue
        pods = ni.pods if (has_aff or has_anti) else ni.pods_with_affinity
        for existing in pods:
            process_pod(existing)

    max_count = min_count = 0
    for node in nodes:
        if node.name in counts:
            max_count = max(max_count, counts[node.name])
            min_count = min(min_count, counts[node.name])

    diff = max_count - min_count
    out = []
    for node in nodes:
        f_score = 0.0
        if diff > 0 and node.name in counts:
            f_score = float(MAX_PRIORITY) * ((counts[node.name] - min_count) / diff)
        out.append(int(f_score))
    return out


def equal_priority_map(pod: Pod, ni: NodeInfo) -> int:
    return 1


# ---------------------------------------------------------------------------
# Gang locality (round 19 — rank-aware gang set-scoring, the serial half
# of the device kernels' per-segment zone-count carry)
# ---------------------------------------------------------------------------
def gang_locality_map(zone_counts: dict, ni: NodeInfo) -> int:
    """Score a candidate node by how many members of the CURRENT gang
    trial already landed in its zone, clipped at MAX_PRIORITY — the group
    objective that prefers packing a tightly-coupled gang into few
    zones/ICI domains. `zone_counts` is the trial's live {zone_key:
    members placed} map (reset per gang, updated after every member's
    assume); nodes without a zone score 0. Must stay bit-identical to the
    kernel's gang term in ops.kernels._fit_scores: min(count, 10),
    weighted by the member profile's gang weight at the caller."""
    zone = get_zone_key(ni.node) if ni.node is not None else ""
    if not zone:
        return 0
    return min(int(zone_counts.get(zone, 0)), MAX_PRIORITY)
