"""Serial gang trial — the oracle referee for all-or-nothing placement.

The device path trial-places a whole PodGroup through the burst wave
machinery and commits only a complete gang; THIS is the sequential
semantics it must match bit-for-bit (the same contract the burst kernels
hold against the serial scheduleOne loop, extended to group atomicity):

    for each member, in queue order:
        refresh the snapshot (earlier members' assumes are visible)
        consume one NodeTree enumeration
        schedule(member) against the live state
        assume the placement in the cache
    all members placed  -> the trial's assumes stand; the caller binds
    any member fails    -> EVERY assume is rolled back, the algorithm's
                           last_index / lastNodeIndex rewind, and the
                           NodeTree cursor restores — observable state is
                           exactly as if the gang was never attempted

`GangTrial` owns the rollback bookkeeping so the scheduler shell (and the
parity fuzzes) cannot half-rewind. It is transport- and algorithm-agnostic:
the oracle shell runs it as its primary gang path, and the TPU shell runs
it whenever the burst kernels refuse a gang's feature mix (decisions are
identical either way — that is the point).
"""
from __future__ import annotations

from typing import Callable, Optional

from kubernetes_tpu.oracle.generic_scheduler import FitError


class GangTrial:
    """One atomic trial of a gang against the live cache."""

    def __init__(self, cache, algorithm):
        self.cache = cache
        self.algorithm = algorithm

    def run(self, pods: list, schedule_fn: Callable,
            refresh_snapshot_fn: Callable[[], None],
            on_placed: Optional[Callable[[str], None]] = None,
            ) -> Optional[list[str]]:
        """Trial-place `pods` serially. Returns the per-member host list
        with every member's assume left IN the cache (the caller commits
        by binding), or None after a full rollback when any member failed.

        `schedule_fn(pod, names)` is the shell's algorithm dispatch;
        `refresh_snapshot_fn()` refreshes the shell's snapshot so member
        k sees members 0..k-1 as assumed load. `on_placed(host)`
        (optional) fires after each member's assume — the rank-aware gang
        set-scoring hook: the shell folds the placed member's zone into
        the trial's zone-count tracker so member k+1's GangLocalityPriority
        sees members 0..k, exactly like the fused kernel's per-segment
        carry (a rollback discards the whole tracker with the trial)."""
        tree = self.cache.node_tree
        tree_chk = tree.checkpoint()
        li = self.algorithm.last_index
        lni = self.algorithm.last_node_index
        assumed: list = []
        hosts: list[str] = []
        # exposed so the shell can roll a SUCCESSFUL trial back too (a
        # node death detected between trial and commit re-trials the gang
        # rather than binding a partial one)
        self.last_assumed = assumed
        self.last_chk = (tree_chk, li, lni)
        try:
            for pod in pods:
                refresh_snapshot_fn()
                names = tree.list_names()
                result = schedule_fn(pod, names)
                trial = pod.clone()
                trial.node_name = result.suggested_host
                self.cache.assume_pod(trial)
                assumed.append(trial)
                hosts.append(result.suggested_host)
                if on_placed is not None:
                    on_placed(result.suggested_host)
        except FitError:
            self.rollback(assumed, tree_chk, li, lni)
            return None
        return hosts

    def rollback(self, assumed: list, tree_chk, li: int, lni: int) -> None:
        """Forget every trial assume and rewind the walk counters + the
        rotation cursor to the pre-gang checkpoint (reverse order, so the
        cache transitions through the same states the trial created)."""
        for trial in reversed(assumed):
            self.cache.forget_pod(trial)
        self.algorithm.last_index = li
        self.algorithm.last_node_index = lni
        self.cache.node_tree.restore(tree_chk)
