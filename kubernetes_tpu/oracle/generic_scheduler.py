"""Semantic oracle: the generic scheduling algorithm, deterministic-sequential.

Reference: pkg/scheduler/core/generic_scheduler.go — findNodesThatFit
(:457, with the resumable lastIndex rotation and the adaptive
percentageOfNodesToScore truncation :434), PrioritizeNodes (:672, map /
reduce / weighted-sum), and selectHost (:286, round-robin among max-score
ties via lastNodeIndex). Evaluated sequentially, which makes the feasible
set and tie-breaks deterministic (the reference's 16-way goroutine pool
makes its own truncation/tie order racy; sequential order IS the
single-worker reference behavior, and is the canonical parity target).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.types import Pod, Node, Service, ReplicaSet
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.oracle import predicates as preds
from kubernetes_tpu.oracle import priorities as prios
from kubernetes_tpu.oracle.preemption import pod_fits_on_node_with_nominated

MIN_FEASIBLE_NODES_TO_FIND = 100       # generic_scheduler.go:57
MIN_FEASIBLE_PERCENTAGE = 5            # generic_scheduler.go:62
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 50  # api/types.go:40


def num_feasible_nodes_to_find(num_all_nodes: int, percentage: int) -> int:
    """Adaptive partial-search quota (reference: generic_scheduler.go:434).
    Shared by the oracle and the device scheduler so both stop the node walk
    at exactly the same point."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND or percentage >= 100:
        return num_all_nodes
    adaptive = percentage
    if adaptive <= 0:
        adaptive = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE - num_all_nodes // 125
        if adaptive < MIN_FEASIBLE_PERCENTAGE:
            adaptive = MIN_FEASIBLE_PERCENTAGE
    num = num_all_nodes * adaptive // 100
    if num < MIN_FEASIBLE_NODES_TO_FIND:
        return MIN_FEASIBLE_NODES_TO_FIND
    return num


@dataclass
class PriorityConfig:
    """One Score plugin entry (reference: priorities.PriorityConfig)."""
    name: str
    weight: int = 1
    map_fn: Optional[Callable[[Pod, NodeInfo], int]] = None
    reduce_fn: Optional[Callable[[list[int]], list[int]]] = None
    # function-style priorities compute the whole list at once
    function: Optional[Callable[[Pod, dict[str, NodeInfo], list[Node]], list[int]]] = None


def default_priority_configs(services_fn: Callable[[], list[Service]] = lambda: [],
                             replicasets_fn: Callable[[], list[ReplicaSet]] = lambda: [],
                             hard_pod_affinity_weight: int = 1) -> list[PriorityConfig]:
    """The DefaultProvider priority set (reference: defaults.go:108), all
    weight 1 except NodePreferAvoidPods at 10000
    (register_priorities.go:26)."""

    def selector_spread_function(pod: Pod, node_infos: dict[str, NodeInfo],
                                 nodes: list[Node]) -> list[int]:
        selectors = prios.get_selectors(pod, services_fn(), replicasets_fn())
        hosts = [n.name for n in nodes]
        counts = [prios.selector_spread_map(pod, node_infos[h], selectors) for h in hosts]
        return prios.selector_spread_reduce(node_infos, hosts, counts)

    def interpod_function(pod: Pod, node_infos: dict[str, NodeInfo],
                          nodes: list[Node]) -> list[int]:
        return prios.interpod_affinity_priority(pod, node_infos, nodes,
                                                hard_pod_affinity_weight)

    def image_locality_fn(pod: Pod, node_infos: dict[str, NodeInfo],
                          nodes: list[Node]) -> list[int]:
        total = len(node_infos)
        return [prios.image_locality_map(pod, node_infos[n.name], total) for n in nodes]

    return [
        PriorityConfig("SelectorSpreadPriority", 1, function=selector_spread_function),
        PriorityConfig("InterPodAffinityPriority", 1, function=interpod_function),
        PriorityConfig("LeastRequestedPriority", 1, map_fn=prios.least_requested_map),
        PriorityConfig("BalancedResourceAllocation", 1, map_fn=prios.balanced_allocation_map),
        PriorityConfig("NodePreferAvoidPodsPriority", 10000, map_fn=prios.node_prefer_avoid_pods_map),
        PriorityConfig("NodeAffinityPriority", 1, map_fn=prios.node_affinity_map,
                       reduce_fn=lambda s: prios.normalize_reduce(prios.MAX_PRIORITY, False, s)),
        PriorityConfig("TaintTolerationPriority", 1, map_fn=prios.taint_toleration_map,
                       reduce_fn=lambda s: prios.normalize_reduce(prios.MAX_PRIORITY, True, s)),
        PriorityConfig("ImageLocalityPriority", 1, function=image_locality_fn),
    ]


@dataclass
class ScheduleResult:
    suggested_host: str
    evaluated_nodes: int
    feasible_nodes: int
    # per-host weighted total score, in feasible order (for parity checks)
    host_priority: list[tuple[str, int]] = field(default_factory=list)
    failed_predicates: dict[str, list[str]] = field(default_factory=dict)


class FitError(Exception):
    def __init__(self, pod: Pod, num_all_nodes: int, failed: dict[str, list[str]]):
        super().__init__(f"0/{num_all_nodes} nodes available for {pod.key}")
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.failed_predicates = failed


class GenericScheduler:
    """Deterministic-sequential Schedule(): filter -> score -> select."""

    def __init__(self,
                 percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE,
                 always_check_all_predicates: bool = False,
                 hard_pod_affinity_weight: int = 1,
                 nominated_pods_fn: Callable[[str], list[Pod]] = lambda n: []):
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.always_check_all = always_check_all_predicates
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.nominated_pods_fn = nominated_pods_fn  # podFitsOnNode two-pass (:627)
        self.extenders = []   # SchedulerExtender list (core/extender.go)
        self.last_index = 0         # findNodesThatFit resumable rotation (:486)
        self.last_node_index = 0    # selectHost round-robin counter (:292)

    # -- findNodesThatFit ---------------------------------------------------
    def num_feasible_nodes_to_find(self, num_all_nodes: int) -> int:
        """Reference: generic_scheduler.go:434."""
        return num_feasible_nodes_to_find(num_all_nodes,
                                          self.percentage_of_nodes_to_score)

    def find_nodes_that_fit(self, pod: Pod, node_infos: dict[str, NodeInfo],
                            all_node_names: list[str],
                            predicate_funcs: dict[str, Callable],
                            ) -> tuple[list[Node], dict[str, list[str]], int]:
        """Sequential equivalent of :457 — walk from last_index, stop at
        num_nodes_to_find feasible. Returns (nodes, failed_map, evaluated)."""
        n = len(all_node_names)
        num_to_find = self.num_feasible_nodes_to_find(n)
        filtered: list[Node] = []
        failed: dict[str, list[str]] = {}
        processed = 0
        for i in range(n):
            if len(filtered) >= num_to_find:
                break
            name = all_node_names[(self.last_index + i) % n]
            ni = node_infos[name]
            processed += 1
            fit, reasons = pod_fits_on_node_with_nominated(
                pod, ni, predicate_funcs, self.nominated_pods_fn,
                self.always_check_all, node_infos=node_infos)
            if fit:
                filtered.append(ni.node)
            else:
                failed[name] = reasons
        self.last_index = (self.last_index + processed) % n if n else 0
        return filtered, failed, processed

    # -- PrioritizeNodes ----------------------------------------------------
    def prioritize_nodes(self, pod: Pod, node_infos: dict[str, NodeInfo],
                         priority_configs: list[PriorityConfig],
                         nodes: list[Node]) -> list[tuple[str, int]]:
        """Reference: :672 — when no configs, EqualPriority weight 1."""
        if not priority_configs:
            return [(n.name, 1) for n in nodes]
        totals = [0] * len(nodes)
        for cfg in priority_configs:
            if cfg.function is not None:
                scores = cfg.function(pod, node_infos, nodes)
            else:
                scores = [cfg.map_fn(pod, node_infos[n.name]) for n in nodes]
                if cfg.reduce_fn is not None:
                    scores = cfg.reduce_fn(scores)
            for i, s in enumerate(scores):
                totals[i] += s * cfg.weight
        return [(n.name, t) for n, t in zip(nodes, totals)]

    # -- selectHost ---------------------------------------------------------
    def select_host(self, host_priority: list[tuple[str, int]]) -> str:
        """Reference: :286 — round-robin among max-score ties."""
        if not host_priority:
            raise ValueError("empty priorityList")
        max_score = max(s for _, s in host_priority)
        max_idx = [i for i, (_, s) in enumerate(host_priority) if s == max_score]
        ix = self.last_node_index % len(max_idx)
        self.last_node_index += 1
        return host_priority[max_idx[ix]][0]

    # -- Schedule -----------------------------------------------------------
    def schedule(self, pod: Pod, node_infos: dict[str, NodeInfo],
                 all_node_names: list[str],
                 predicate_funcs: Optional[dict[str, Callable]] = None,
                 priority_configs: Optional[list[PriorityConfig]] = None,
                 ) -> ScheduleResult:
        if predicate_funcs is None:
            predicate_funcs = preds.default_predicate_set(node_infos)
        if priority_configs is None:
            priority_configs = default_priority_configs(
                hard_pod_affinity_weight=self.hard_pod_affinity_weight)
        if not all_node_names:
            raise FitError(pod, 0, {})
        filtered, failed, evaluated = self.find_nodes_that_fit(
            pod, node_infos, all_node_names, predicate_funcs)
        # extender filter pass (generic_scheduler.go:532)
        if filtered and self.extenders:
            for ext in self.extenders:
                filtered, ext_failed = ext.filter(pod, filtered)
                for name, reasons in ext_failed.items():
                    failed.setdefault(name, []).extend(reasons)
                if not filtered:
                    break
        if not filtered:
            raise FitError(pod, len(all_node_names), failed)
        if len(filtered) == 1:
            return ScheduleResult(filtered[0].name, evaluated, 1,
                                  [(filtered[0].name, 0)], failed)
        host_priority = self.prioritize_nodes(pod, node_infos, priority_configs, filtered)
        # extender prioritize pass (generic_scheduler.go:774): extender scores
        # are multiplied by the extender's own weight and added in
        if self.extenders:
            totals = dict(host_priority)
            for ext in self.extenders:
                scores, weight = ext.prioritize(pod, filtered)
                if not weight:
                    continue
                for name, score in scores.items():
                    if name in totals:
                        totals[name] += score * weight
            host_priority = [(name, totals[name]) for name, _ in host_priority]
        host = self.select_host(host_priority)
        return ScheduleResult(host, evaluated, len(filtered), host_priority, failed)
