"""Native (C++) runtime components, built on demand with the system g++.

The compute path is JAX/XLA; the runtime around it follows the reference's
stance of natively-compiled infrastructure (the reference is Go throughout).
Components live here as single-file CPython extensions compiled lazily into
this directory (no pip, no network): `load(name)` rebuilds when the source
is newer than the cached .so and returns None on ANY failure — every
consumer keeps a pure-Python twin with identical semantics, so a missing
toolchain degrades performance, never behavior.
"""
from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_cache: dict[str, object] = {}


def _asan() -> bool:
    """ASan build mode (KTPU_NATIVE_ASAN=1): compile the extensions with
    AddressSanitizer so native bugs surface as aborts-with-reports in a
    dedicated test run, not as silent heap corruption. The instrumented
    artifact gets its own cache name (never clobbers the fast build) and
    only imports when the ASan runtime is preloaded (tests/test_native.py
    runs a subprocess with LD_PRELOAD=libasan); anywhere else the import
    fails and consumers degrade to their twins as usual."""
    return os.environ.get("KTPU_NATIVE_ASAN") == "1"


def _so_path(name: str) -> str:
    tag = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    variant = "_asan" if _asan() else ""
    return os.path.join(_DIR, f"_{name}{variant}{tag}")


def _build(name: str, force: bool = False) -> str:
    """Compile `name`.cpp to its .so when the source is newer than the
    cached artifact (or unconditionally with `force`, for a cached .so
    that exists but won't import — stale or ABI-mismatched on this
    machine, e.g. checked in from a different Python build)."""
    src = os.path.join(_DIR, f"{name}.cpp")
    out = _so_path(name)
    if not force and os.path.exists(out) \
            and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    include = sysconfig.get_paths()["include"]
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           f"-I{include}", src, "-o", out]
    if _asan():
        cmd[1:1] = ["-fsanitize=address", "-fno-omit-frame-pointer", "-g"]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    return out


def _import_so(name: str, path: str):
    loader = importlib.machinery.ExtensionFileLoader(f"_{name}", path)
    spec = importlib.util.spec_from_file_location(
        f"_{name}", path, loader=loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def load(name: str):
    """Import native module `_name`, building it first if needed. An
    import failure of an up-to-date-looking .so forces one rebuild from
    source and retries (mtime can't see ABI mismatches). Returns the
    module, or None when building/loading fails — g++ absence included —
    so every consumer degrades to its pure-Python twin."""
    with _lock:
        if name in _cache:
            return _cache[name]
        mod = None
        try:
            mod = _import_so(name, _build(name))
        except Exception:
            try:
                mod = _import_so(name, _build(name, force=True))
            except Exception:
                mod = None
        _cache[name] = mod
        return mod
