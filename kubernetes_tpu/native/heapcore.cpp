// heapcore — native keyed binary heap for the scheduling queue.
//
// The scheduler's activeQ/backoffQ (reference: pkg/scheduler/util/heap.go:127,
// a Go — natively compiled — keyed heap) order by purely NUMERIC tuples:
// activeQ by (-priority, timestamp, seq) (scheduling_queue.go:107 podsCompare)
// and backoffQ by (expiry, seq). The Python twin (utils/heap.py) pays a
// key-lambda + tuple allocation per comparison; this CPython extension keeps
// the (a, b, c) ordering keys unboxed in a contiguous vector and sifts in
// C++, holding the payload as an opaque PyObject*. Loaded on demand by
// kubernetes_tpu.native (g++ build, no pip); utils/heap.NumericKeyedHeap
// falls back to the Python twin when unavailable — identical semantics
// either way (tests run both).
//
// Doubles hold every ordering component exactly: priorities are int32,
// timestamps are seconds-as-float, and seq counters stay far below 2^53.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Entry {
    double a, b, c;
    std::string key;
    PyObject* payload;   // owned reference
};

inline bool less(const Entry& x, const Entry& y) {
    if (x.a != y.a) return x.a < y.a;
    if (x.b != y.b) return x.b < y.b;
    return x.c < y.c;
}

// The sift/index work is pure C++ (payloads are opaque, refcounts only
// change at the Python boundary), so the batched drain releases the GIL
// around it; `mu` keeps the structure consistent for the GIL-holding
// single-item calls that may interleave. No heapcore mutex section ever
// (re)acquires the GIL, so taking `mu` with the GIL held cannot deadlock.
struct HeapCore {
    PyObject_HEAD
    std::vector<Entry>* items;
    std::unordered_map<std::string, size_t>* index;
    std::mutex* mu;
};

void set_pos(HeapCore* self, size_t i) {
    (*self->index)[(*self->items)[i].key] = i;
}

void swap_entries(HeapCore* self, size_t i, size_t j) {
    std::swap((*self->items)[i], (*self->items)[j]);
    set_pos(self, i);
    set_pos(self, j);
}

size_t sift_up(HeapCore* self, size_t i) {
    auto& v = *self->items;
    while (i > 0) {
        size_t parent = (i - 1) / 2;
        if (less(v[i], v[parent])) {
            swap_entries(self, i, parent);
            i = parent;
        } else {
            break;
        }
    }
    return i;
}

void sift_down(HeapCore* self, size_t i) {
    auto& v = *self->items;
    size_t n = v.size();
    for (;;) {
        size_t smallest = i;
        for (size_t c = 2 * i + 1; c <= 2 * i + 2 && c < n; ++c) {
            if (less(v[c], v[smallest])) smallest = c;
        }
        if (smallest == i) return;
        swap_entries(self, i, smallest);
        i = smallest;
    }
}

// returns an owned reference to the removed payload, or nullptr (no error)
PyObject* remove_at(HeapCore* self, size_t i) {
    auto& v = *self->items;
    PyObject* payload = v[i].payload;
    self->index->erase(v[i].key);
    size_t last = v.size() - 1;
    if (i != last) {
        v[i] = std::move(v[last]);
        v.pop_back();
        set_pos(self, i);
        sift_down(self, sift_up(self, i));
    } else {
        v.pop_back();
    }
    return payload;
}

PyObject* heap_add(HeapCore* self, PyObject* args) {
    const char* key;
    Py_ssize_t klen;
    double a, b, c;
    PyObject* payload;
    if (!PyArg_ParseTuple(args, "s#dddO", &key, &klen, &a, &b, &c, &payload))
        return nullptr;
    std::string k(key, (size_t)klen);
    Py_INCREF(payload);
    std::lock_guard<std::mutex> lk(*self->mu);
    auto it = self->index->find(k);
    if (it != self->index->end()) {
        Entry& e = (*self->items)[it->second];
        Py_DECREF(e.payload);
        e.a = a; e.b = b; e.c = c;
        e.payload = payload;
        sift_down(self, sift_up(self, it->second));
    } else {
        self->items->push_back(Entry{a, b, c, k, payload});
        size_t i = self->items->size() - 1;
        (*self->index)[k] = i;
        sift_up(self, i);
    }
    Py_RETURN_NONE;
}

PyObject* heap_get(HeapCore* self, PyObject* arg) {
    Py_ssize_t klen;
    const char* key = PyUnicode_AsUTF8AndSize(arg, &klen);
    if (!key) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    auto it = self->index->find(std::string(key, (size_t)klen));
    if (it == self->index->end()) Py_RETURN_NONE;
    PyObject* p = (*self->items)[it->second].payload;
    Py_INCREF(p);
    return p;
}

PyObject* heap_delete(HeapCore* self, PyObject* arg) {
    Py_ssize_t klen;
    const char* key = PyUnicode_AsUTF8AndSize(arg, &klen);
    if (!key) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    auto it = self->index->find(std::string(key, (size_t)klen));
    if (it == self->index->end()) Py_RETURN_NONE;
    return remove_at(self, it->second);
}

PyObject* heap_pop(HeapCore* self, PyObject*) {
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->items->empty()) Py_RETURN_NONE;
    return remove_at(self, 0);
}

PyObject* heap_pop_many(HeapCore* self, PyObject* arg) {
    // batched drain: up to `limit` ascending pops as ONE call, the sifts
    // running with the GIL RELEASED (the queue's pop_burst prologue)
    Py_ssize_t limit = PyNumber_AsSsize_t(arg, PyExc_OverflowError);
    if (limit == -1 && PyErr_Occurred()) return nullptr;
    std::vector<PyObject*> popped;   // owned refs transferred from entries
    Py_BEGIN_ALLOW_THREADS
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        while ((Py_ssize_t)popped.size() < limit && !self->items->empty())
            popped.push_back(remove_at(self, 0));
    }
    Py_END_ALLOW_THREADS
    PyObject* out = PyList_New((Py_ssize_t)popped.size());
    if (!out) {
        for (PyObject* p : popped) Py_DECREF(p);
        return nullptr;
    }
    for (size_t i = 0; i < popped.size(); ++i)
        PyList_SET_ITEM(out, (Py_ssize_t)i, popped[i]);
    return out;
}

PyObject* heap_push_many(HeapCore* self, PyObject* arg) {
    // batched insert: a list of (key, a, b, c, payload) entries lands as
    // ONE call, the sifts running with the GIL RELEASED (the informer
    // ingest prologue's twin of pop_many). Per-entry semantics identical
    // to add(): insert or replace by key.
    PyObject* seq = PySequence_Fast(arg, "entries must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    std::vector<Entry> staged;
    staged.reserve((size_t)n);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* t = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 5) {
            PyErr_SetString(PyExc_TypeError,
                            "entry must be (key, a, b, c, payload)");
            for (Entry& e : staged) Py_DECREF(e.payload);
            Py_DECREF(seq);
            return nullptr;
        }
        Py_ssize_t klen;
        const char* key = PyUnicode_AsUTF8AndSize(PyTuple_GET_ITEM(t, 0),
                                                  &klen);
        double a = PyFloat_AsDouble(PyTuple_GET_ITEM(t, 1));
        double b = PyFloat_AsDouble(PyTuple_GET_ITEM(t, 2));
        double c = PyFloat_AsDouble(PyTuple_GET_ITEM(t, 3));
        if (!key || PyErr_Occurred()) {
            for (Entry& e : staged) Py_DECREF(e.payload);
            Py_DECREF(seq);
            return nullptr;
        }
        PyObject* payload = PyTuple_GET_ITEM(t, 4);
        Py_INCREF(payload);
        staged.push_back(Entry{a, b, c, std::string(key, (size_t)klen),
                               payload});
    }
    Py_DECREF(seq);
    // replaced payloads must be decref'd with the GIL held — collect
    // under the mutex (GIL released), release after re-acquiring it
    std::vector<PyObject*> replaced;
    Py_BEGIN_ALLOW_THREADS
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        for (Entry& e : staged) {
            auto it = self->index->find(e.key);
            if (it != self->index->end()) {
                Entry& cur = (*self->items)[it->second];
                replaced.push_back(cur.payload);
                cur.a = e.a; cur.b = e.b; cur.c = e.c;
                cur.payload = e.payload;
                sift_down(self, sift_up(self, it->second));
            } else {
                self->items->push_back(std::move(e));
                size_t i = self->items->size() - 1;
                (*self->index)[(*self->items)[i].key] = i;
                sift_up(self, i);
            }
        }
    }
    Py_END_ALLOW_THREADS
    for (PyObject* p : replaced) Py_DECREF(p);
    Py_RETURN_NONE;
}

PyObject* heap_peek(HeapCore* self, PyObject*) {
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->items->empty()) Py_RETURN_NONE;
    PyObject* p = (*self->items)[0].payload;
    Py_INCREF(p);
    return p;
}

PyObject* heap_list(HeapCore* self, PyObject*) {
    std::lock_guard<std::mutex> lk(*self->mu);
    PyObject* out = PyList_New((Py_ssize_t)self->items->size());
    if (!out) return nullptr;
    for (size_t i = 0; i < self->items->size(); ++i) {
        PyObject* p = (*self->items)[i].payload;
        Py_INCREF(p);
        PyList_SET_ITEM(out, (Py_ssize_t)i, p);
    }
    return out;
}

int heap_contains(HeapCore* self, PyObject* arg) {
    Py_ssize_t klen;
    const char* key = PyUnicode_AsUTF8AndSize(arg, &klen);
    if (!key) {
        PyErr_Clear();
        return 0;
    }
    std::lock_guard<std::mutex> lk(*self->mu);
    return self->index->count(std::string(key, (size_t)klen)) ? 1 : 0;
}

Py_ssize_t heap_len(HeapCore* self) {
    std::lock_guard<std::mutex> lk(*self->mu);
    return (Py_ssize_t)self->items->size();
}

PyObject* heap_new(PyTypeObject* type, PyObject*, PyObject*) {
    HeapCore* self = (HeapCore*)type->tp_alloc(type, 0);
    if (!self) return nullptr;
    self->items = new std::vector<Entry>();
    self->index = new std::unordered_map<std::string, size_t>();
    self->mu = new std::mutex();
    return (PyObject*)self;
}

void heap_dealloc(HeapCore* self) {
    if (self->items) {
        for (Entry& e : *self->items) Py_XDECREF(e.payload);
        delete self->items;
        delete self->index;
        delete self->mu;
    }
    Py_TYPE(self)->tp_free((PyObject*)self);
}

PyMethodDef heap_methods[] = {
    {"add", (PyCFunction)heap_add, METH_VARARGS,
     "add(key, a, b, c, payload) — insert or replace by key"},
    {"get", (PyCFunction)heap_get, METH_O, "payload by key or None"},
    {"delete", (PyCFunction)heap_delete, METH_O,
     "remove by key, returning the payload or None"},
    {"pop", (PyCFunction)heap_pop, METH_NOARGS, "remove + return the min"},
    {"pop_many", (PyCFunction)heap_pop_many, METH_O,
     "pop_many(limit) — up to limit ascending pops as one call (GIL "
     "released during the sifts)"},
    {"push_many", (PyCFunction)heap_push_many, METH_O,
     "push_many(entries) — batched add of (key, a, b, c, payload) tuples "
     "as one call (GIL released during the sifts)"},
    {"peek", (PyCFunction)heap_peek, METH_NOARGS, "the min without removal"},
    {"list", (PyCFunction)heap_list, METH_NOARGS, "payloads, heap order"},
    {nullptr, nullptr, 0, nullptr},
};

PySequenceMethods heap_as_sequence = {
    .sq_length = (lenfunc)heap_len,
    .sq_contains = (objobjproc)heap_contains,
};

PyTypeObject HeapCoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    .tp_name = "_heapcore.HeapCore",
    .tp_basicsize = sizeof(HeapCore),
    .tp_dealloc = (destructor)heap_dealloc,
    .tp_as_sequence = &heap_as_sequence,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = PyDoc_STR("string-keyed binary heap over numeric (a,b,c)"),
    .tp_methods = heap_methods,
    .tp_new = heap_new,
};

PyModuleDef heapcore_module = {
    PyModuleDef_HEAD_INIT, "_heapcore",
    "native scheduling-queue heap core", -1, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__heapcore(void) {
    if (PyType_Ready(&HeapCoreType) < 0) return nullptr;
    PyObject* m = PyModule_Create(&heapcore_module);
    if (!m) return nullptr;
    Py_INCREF(&HeapCoreType);
    if (PyModule_AddObject(m, "HeapCore", (PyObject*)&HeapCoreType) < 0) {
        Py_DECREF(&HeapCoreType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
