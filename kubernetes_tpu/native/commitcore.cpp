// commitcore — native commit core for the versioned store.
//
// The store's three hot host loops behind the fused device pipeline
// (ROADMAP item 3: with a burst at ONE dispatch + ONE packed fetch, the
// serial floor is the host commit tail) become one native call each per
// wave:
//   1. bind_batch / create_batch / commit_wave — the versioned batched
//      store write: resourceVersion assignment, missing-key detection,
//      AlreadyExists raises and watch-log records with semantics
//      bit-identical to store/commit_core.PyCommitCore (the mandatory
//      pure-Python twin and referee; tests/test_commit_core.py pins the
//      two against each other op-for-op).
//   2. flush — watch fan-out: watchers are CURSORS into the per-kind
//      bounded log ring, so delivery is O(watchers) cursor publishes per
//      wave, with slow consumers dropped-with-resync (ExpiredError on the
//      next poll; 410-Gone semantics) instead of buffered unboundedly.
//   3. poll — consumer copy-out, which blocks with the GIL RELEASED
//      (std::condition_variable) and materializes Event objects on the
//      consumer's own thread, so watch delivery overlaps the committing
//      thread's next wave.
//
// Locking contract: the rv counter and the Python-object work (clone,
// setattr, bucket dict writes) run under the CALLER's store lock with the
// GIL held and never touch the core mutex; the log ring + watcher cursors
// are guarded by a std::mutex that is ONLY ever acquired with the GIL
// released (a thread may re-acquire the GIL while holding the mutex — for
// refcounts, never allocations — but never waits for the mutex while
// holding the GIL, so the pair cannot deadlock). Python work that can
// allocate (and hence run GC finalizers that might re-enter this module)
// happens strictly outside the mutex.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

#include <cxxabi.h>   // abi::__forced_unwind (pthread_exit at finalization)
#include <vector>

namespace {

// interned event-type strings + attr names (module init)
PyObject* S_ADDED;
PyObject* S_MODIFIED;
PyObject* S_DELETED;
PyObject* S_clone;
PyObject* S_key;
PyObject* S_node_name;
PyObject* S_resource_version;
PyObject* S_namespace;
PyObject* S_labels;
PyObject* S_node_selector;
PyObject* S_affinity;
PyObject* S_tolerations;
PyObject* S_containers;
PyObject* S_init_containers;
// Scheduled-record construction (commit_wave_binds): attr names + the
// constant field values of a burst bind's audit record
PyObject* S_name;
PyObject* S_involved_kind;
PyObject* S_involved_key;
PyObject* S_type;
PyObject* S_reason;
PyObject* S_message;
PyObject* S_count;
PyObject* S_component;
PyObject* V_Pod;        // "Pod"
PyObject* V_Normal;     // "Normal"
PyObject* V_Scheduled;  // "Scheduled"
PyObject* V_default;    // "default"
PyObject* ONE;
PyObject* ZERO;
PyObject* EMPTY_TUPLE;
PyObject* DEEPCOPY;   // copy.deepcopy (clone() fallback, as store._clone)

struct Entry {
    PyObject* etype;   // owned (interned constant, incref'd per entry)
    PyObject* obj;     // owned
    long long rv;
    double ts = 0.0;   // monotonic commit stamp (watch fan-out lag)
};

// monotonic seconds (only ever DIFFERENCED against itself: the fan-out
// sink receives lags, not absolute times, so the epoch never matters)
double mono_now() {
    return std::chrono::duration<double>(
        std::chrono::steady_clock::now().time_since_epoch()).count();
}

struct KindLog {
    std::deque<Entry> entries;
    long long start = 0;     // absolute seq of entries[0]
    long long flushed = 0;   // absolute seq published to watchers
    long long end() const { return start + (long long)entries.size(); }
};

// One shared subscription class (round 20): every watcher with the same
// (kind, selector) interest shares one materialize-once Event cache and
// one serialize-once wire-line cache. `evs`/`lines` are slot deques
// aligned to the kind log from absolute seq `cache_start` (realigned
// lazily at poll when the ring evicts); slot refs are owned and only
// touched under the mutex with refcount-only GIL re-acquisition (the
// standard lock contract). The selector is an OPAQUE interest key —
// class dedupe only, never an event filter.
struct SubClass {
    std::string kind;
    std::string selector;
    long long members = 0;
    long long cache_start = 0;
    std::deque<PyObject*> evs;     // owned Event or nullptr per log slot
    std::deque<PyObject*> lines;   // owned wire bytes or nullptr per slot
};

struct Watcher {
    std::string kind;
    long long cursor;
    bool resync = false;
    SubClass* cls = nullptr;   // shared class (stable node pointer), or
                               // nullptr in old-shape degenerate mode
};

struct CommitCore {
    PyObject_HEAD
    long long rv;
    long long log_size;
    long long ring_size;
    long long next_wid;
    PyObject* event_cls;     // owned
    PyObject* expired_exc;   // owned
    PyObject* already_exc;   // owned
    std::unordered_map<std::string, KindLog>* logs;
    std::unordered_map<long long, Watcher>* watchers;
    std::unordered_map<std::string, std::vector<long long>>* by_kind;
    // subscription classes keyed "kind\x1fselector" (node-based map:
    // Watcher::cls pointers stay valid until the class is erased at
    // zero members). Guarded by the mutex like the watcher cursors.
    std::unordered_map<std::string, SubClass>* classes;
    bool shared_classes;     // false = old-shape per-watcher degenerate
    // fencing-token table (round 18): scope -> highest lease token
    // validated. Guarded by the CALLER's store lock like the rv counter
    // (GIL held, no mutex) — never touched from consumer threads.
    std::unordered_map<std::string, long long>* fences;
    std::mutex* mu;
    std::condition_variable* cv;
    PyObject* fanout_sink;   // owned, may be null (observability hook)
    PyObject* wire_encoder;  // owned, may be null ((etype, obj, rv)->bytes)
    // watch-plane counters (guarded by the mutex; observability only)
    long long stat_mat;      // Event materializations (cache misses)
    long long stat_shared;   // deliveries served from a class cache
    long long stat_enc;      // wire-line encodes (cache misses)
    long long stat_bytes;    // wire bytes served (hits + misses)
};

KindLog& kind_log(CommitCore* self, const std::string& kind) {
    return (*self->logs)[kind];
}

// -- subscription-class plumbing (mutex held, GIL released) ------------------
// Realign a class's slot deques to the log window [start, end). Evicted
// slot refs go into `stale` for the caller to decref AFTER the mutex is
// dropped (never a decref under the mutex without the GIL).
void class_align(SubClass* c, KindLog& log, std::vector<PyObject*>& stale) {
    while (c->cache_start < log.start && !c->evs.empty()) {
        if (c->evs.front() != nullptr) stale.push_back(c->evs.front());
        if (c->lines.front() != nullptr) stale.push_back(c->lines.front());
        c->evs.pop_front();
        c->lines.pop_front();
        c->cache_start += 1;
    }
    if (c->cache_start < log.start) c->cache_start = log.start;
    while (c->cache_start + (long long)c->evs.size() < log.end()) {
        c->evs.push_back(nullptr);
        c->lines.push_back(nullptr);
    }
}

// Resolve (kind, selector) to its shared class, creating it on first
// membership. A new class covers the full current log window so
// replaying watchers (attach with since_rv) index valid slots. Returns
// nullptr in degenerate mode.
SubClass* join_class(CommitCore* self, const std::string& kind,
                     const std::string& selector, KindLog& log) {
    if (!self->shared_classes) return nullptr;
    std::string key = kind;
    key.push_back('\x1f');
    key += selector;
    auto it = self->classes->find(key);
    if (it == self->classes->end()) {
        SubClass c;
        c.kind = kind;
        c.selector = selector;
        c.cache_start = log.start;
        c.evs.assign(log.entries.size(), nullptr);
        c.lines.assign(log.entries.size(), nullptr);
        it = self->classes->emplace(std::move(key), std::move(c)).first;
    }
    it->second.members += 1;
    return &it->second;
}

void leave_class(CommitCore* self, SubClass* c,
                 std::vector<PyObject*>& stale) {
    if (c == nullptr) return;
    c->members -= 1;
    if (c->members > 0) return;
    for (PyObject* o : c->evs)
        if (o != nullptr) stale.push_back(o);
    for (PyObject* o : c->lines)
        if (o != nullptr) stale.push_back(o);
    std::string key = c->kind;
    key.push_back('\x1f');
    key += c->selector;
    self->classes->erase(key);
}

// Release the GIL for the lifetime of this object (constructor) and take
// it back at destruction. Mutex sections run inside this scope; a
// re-acquire for refcount-only work uses block().
//
// Shutdown hazard: a daemon thread that re-acquires the GIL while the
// interpreter finalizes is pthread_exit()ed by CPython — a forced unwind
// through this extension's C++ frames, which std::terminate()s the whole
// process. When finalization is underway we PARK the thread instead (the
// process is exiting; the thread must not touch Python again). Callers
// ensure no mutex is held when parking (lock guards are declared after
// the GilRelease, so they unwind first; poll() unlocks explicitly).
struct GilRelease {
    PyThreadState* ts;
    GilRelease() : ts(PyEval_SaveThread()) {}
    ~GilRelease() { if (ts) block(); }
    bool finalizing() const { return _Py_IsFinalizing() != 0; }
    void block() {
        if (finalizing()) park();
        // If the interpreter starts finalizing while RestoreThread blocks
        // on the GIL (the race the check above cannot close), CPython 3.10
        // exits the thread via pthread_exit -> a forced unwind through
        // these C++ frames, which std::terminate()s the whole process at
        // the first noexcept frame. Catch the forced-unwind exception and
        // park forever instead: this thread must never run Python again,
        // and park() never returns, so the never-rethrown unwind is
        // abandoned harmlessly until process exit.
        PyThreadState* t = ts;
        ts = nullptr;   // keep the (noexcept) destructor a no-op mid-unwind
        try {
            PyEval_RestoreThread(t);
        } catch (abi::__forced_unwind&) {
            park();
        }
    }
    [[noreturn]] static void park() {
        for (;;)
            std::this_thread::sleep_for(std::chrono::hours(1));
    }
};

// -- staged-append plumbing --------------------------------------------------
// Writers build `staged` entries (owned refs) with the GIL held and no
// mutex, then splice them into the log ring under the mutex (GIL
// released); evicted entries are decref'd after the mutex is dropped.
void splice(CommitCore* self, const std::string& kind,
            std::vector<Entry>& staged, std::vector<Entry>& evicted) {
    double now = mono_now();   // one commit stamp for the whole batch
    GilRelease gil;
    std::lock_guard<std::mutex> lk(*self->mu);
    KindLog& log = kind_log(self, kind);
    for (Entry& e : staged) {
        e.ts = now;
        log.entries.push_back(e);
        if ((long long)log.entries.size() > self->log_size) {
            evicted.push_back(log.entries.front());
            log.entries.pop_front();
            log.start += 1;
        }
    }
    staged.clear();
}

void drop_entries(std::vector<Entry>& evicted) {
    for (Entry& e : evicted) {
        Py_DECREF(e.etype);
        Py_DECREF(e.obj);
    }
    evicted.clear();
}

// snapshot an object crossing the store boundary (store._clone semantics:
// fast clone() when present, copy.deepcopy otherwise)
PyObject* clone_obj(PyObject* obj) {
    PyObject* m = PyObject_GetAttr(obj, S_clone);
    if (m != nullptr) {
        PyObject* out = PyObject_CallNoArgs(m);
        Py_DECREF(m);
        return out;
    }
    if (!PyErr_ExceptionMatches(PyExc_AttributeError)) return nullptr;
    PyErr_Clear();
    return PyObject_CallOneArg(DEEPCOPY, obj);
}

// assign the next rv to `stored` (sets .resource_version); returns rv or
// -1 on error
long long assign_rv(CommitCore* self, PyObject* stored) {
    self->rv += 1;
    PyObject* rvo = PyLong_FromLongLong(self->rv);
    if (!rvo) return -1;
    int rc = PyObject_SetAttr(stored, S_resource_version, rvo);
    Py_DECREF(rvo);
    return rc < 0 ? -1 : self->rv;
}

// -- bind / create bodies (GIL held, no mutex; append via staging) -----------
// returns 0 on success, -1 with a Python error set. Appends MODIFIED
// entries for every landed bind to `staged` and missing keys to `missing`.
int bind_batch_body(CommitCore* self, PyObject* bucket, PyObject* bindings,
                    PyObject* missing, std::vector<Entry>& staged) {
    PyObject* seq = PySequence_Fast(bindings, "bindings must be a sequence");
    if (!seq) return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* pair = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* key;
        PyObject* node;
        if (PyTuple_Check(pair) && PyTuple_GET_SIZE(pair) == 2) {
            key = PyTuple_GET_ITEM(pair, 0);
            node = PyTuple_GET_ITEM(pair, 1);
        } else {
            PyErr_SetString(PyExc_TypeError, "binding must be (key, node)");
            Py_DECREF(seq);
            return -1;
        }
        PyObject* current = PyDict_GetItemWithError(bucket, key);  // borrowed
        if (current == nullptr) {
            if (PyErr_Occurred()) { Py_DECREF(seq); return -1; }
            if (PyList_Append(missing, key) < 0) { Py_DECREF(seq); return -1; }
            continue;
        }
        PyObject* stored = clone_obj(current);
        if (!stored) { Py_DECREF(seq); return -1; }
        if (PyObject_SetAttr(stored, S_node_name, node) < 0) {
            Py_DECREF(stored); Py_DECREF(seq); return -1;
        }
        long long rv = assign_rv(self, stored);
        if (rv < 0) { Py_DECREF(stored); Py_DECREF(seq); return -1; }
        if (PyDict_SetItem(bucket, key, stored) < 0) {
            Py_DECREF(stored); Py_DECREF(seq); return -1;
        }
        Py_INCREF(S_MODIFIED);
        staged.push_back(Entry{S_MODIFIED, stored, rv});  // stored ref moves
    }
    Py_DECREF(seq);
    return 0;
}

// Appends ADDED entries to `staged` and stored objects to `out` (may be
// null). On a duplicate key, raises AlreadyExists but leaves the entries
// staged so far in `staged` (the twin logs them before raising too).
int create_batch_body(CommitCore* self, PyObject* bucket, const char* kind,
                      PyObject* objs, int move, PyObject* out,
                      std::vector<Entry>& staged) {
    PyObject* seq = PySequence_Fast(objs, "objs must be a sequence");
    if (!seq) return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* obj = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* key = PyObject_GetAttr(obj, S_key);
        if (!key) { Py_DECREF(seq); return -1; }
        int dup = PyDict_Contains(bucket, key);
        if (dup != 0) {
            if (dup > 0)
                PyErr_Format(self->already_exc, "%s/%U", kind, key);
            Py_DECREF(key); Py_DECREF(seq);
            return -1;
        }
        PyObject* stored;
        if (move) {
            Py_INCREF(obj);
            stored = obj;
        } else {
            stored = clone_obj(obj);
            if (!stored) { Py_DECREF(key); Py_DECREF(seq); return -1; }
        }
        long long rv = assign_rv(self, stored);
        if (rv < 0) { Py_DECREF(stored); Py_DECREF(key); Py_DECREF(seq); return -1; }
        if (PyDict_SetItem(bucket, key, stored) < 0) {
            Py_DECREF(stored); Py_DECREF(key); Py_DECREF(seq); return -1;
        }
        Py_DECREF(key);
        if (out != nullptr && PyList_Append(out, stored) < 0) {
            Py_DECREF(stored); Py_DECREF(seq); return -1;
        }
        Py_INCREF(S_ADDED);
        staged.push_back(Entry{S_ADDED, stored, rv});  // stored ref moves
    }
    Py_DECREF(seq);
    return 0;
}

// Appends MODIFIED entries to `staged` and stored snapshots to `out` (may
// be null) — the batched update body (round 23). Every object is cloned
// (the caller's object never aliases the bucket), assigned the next rv,
// and replaces its bucket entry. NotFound / rv-CAS refusals are the
// store's per-item pre-scan under the same lock, so everything here lands.
int update_batch_body(CommitCore* self, PyObject* bucket, PyObject* objs,
                      PyObject* out, std::vector<Entry>& staged) {
    PyObject* seq = PySequence_Fast(objs, "objs must be a sequence");
    if (!seq) return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* obj = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* key = PyObject_GetAttr(obj, S_key);
        if (!key) { Py_DECREF(seq); return -1; }
        PyObject* stored = clone_obj(obj);
        if (!stored) { Py_DECREF(key); Py_DECREF(seq); return -1; }
        long long rv = assign_rv(self, stored);
        if (rv < 0) { Py_DECREF(stored); Py_DECREF(key); Py_DECREF(seq); return -1; }
        if (PyDict_SetItem(bucket, key, stored) < 0) {
            Py_DECREF(stored); Py_DECREF(key); Py_DECREF(seq); return -1;
        }
        Py_DECREF(key);
        if (out != nullptr && PyList_Append(out, stored) < 0) {
            Py_DECREF(stored); Py_DECREF(seq); return -1;
        }
        Py_INCREF(S_MODIFIED);
        staged.push_back(Entry{S_MODIFIED, stored, rv});  // stored ref moves
    }
    Py_DECREF(seq);
    return 0;
}

// Appends DELETED entries to `staged` and the popped originals to `gone`
// (may be null) — the batched delete body (round 23). The DELETED payload
// is a snapshot keeping the object's last stored rv; only the log entry
// carries the delete's own rv (store.delete semantics). Missing keys skip.
int delete_batch_body(CommitCore* self, PyObject* bucket, PyObject* keys,
                      PyObject* gone, std::vector<Entry>& staged) {
    PyObject* seq = PySequence_Fast(keys, "keys must be a sequence");
    if (!seq) return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* key = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* current = PyDict_GetItemWithError(bucket, key);  // borrowed
        if (current == nullptr) {
            if (PyErr_Occurred()) { Py_DECREF(seq); return -1; }
            continue;
        }
        Py_INCREF(current);   // keep alive across the DelItem
        PyObject* logged = clone_obj(current);
        if (!logged) { Py_DECREF(current); Py_DECREF(seq); return -1; }
        if (PyDict_DelItem(bucket, key) < 0) {
            Py_DECREF(logged); Py_DECREF(current); Py_DECREF(seq); return -1;
        }
        if (gone != nullptr && PyList_Append(gone, current) < 0) {
            Py_DECREF(logged); Py_DECREF(current); Py_DECREF(seq); return -1;
        }
        Py_DECREF(current);
        self->rv += 1;
        Py_INCREF(S_DELETED);
        staged.push_back(Entry{S_DELETED, logged, self->rv});  // logged ref moves
    }
    Py_DECREF(seq);
    return 0;
}

// -- fencing tokens (round 18; caller holds the store lock) ------------------
// Twin: PyCommitCore.fence_ok / advance_fence / fence_token / fence_table —
// identical semantics (a token below the recorded maximum is superseded).
PyObject* core_fence_ok(CommitCore* self, PyObject* args) {
    const char* scope;
    long long token;
    if (!PyArg_ParseTuple(args, "sL", &scope, &token)) return nullptr;
    auto it = self->fences->find(scope);
    bool ok = it == self->fences->end() || token >= it->second;
    if (ok) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

PyObject* core_advance_fence(CommitCore* self, PyObject* args) {
    const char* scope;
    long long token;
    if (!PyArg_ParseTuple(args, "sL", &scope, &token)) return nullptr;
    auto it = self->fences->find(scope);
    if (it != self->fences->end() && token < it->second) Py_RETURN_FALSE;
    (*self->fences)[scope] = token;
    Py_RETURN_TRUE;
}

PyObject* core_fence_token(CommitCore* self, PyObject* arg) {
    const char* scope = PyUnicode_AsUTF8(arg);
    if (!scope) return nullptr;
    auto it = self->fences->find(scope);
    return PyLong_FromLongLong(it == self->fences->end() ? 0 : it->second);
}

PyObject* core_fence_table(CommitCore* self, PyObject*) {
    PyObject* out = PyDict_New();
    if (!out) return nullptr;
    for (auto& kv : *self->fences) {
        PyObject* v = PyLong_FromLongLong(kv.second);
        if (!v || PyDict_SetItemString(out, kv.first.c_str(), v) < 0) {
            Py_XDECREF(v);
            Py_DECREF(out);
            return nullptr;
        }
        Py_DECREF(v);
    }
    return out;
}

// -- methods ----------------------------------------------------------------
PyObject* core_rv(CommitCore* self, PyObject*) {
    return PyLong_FromLongLong(self->rv);
}

PyObject* core_set_rv(CommitCore* self, PyObject* arg) {
    long long v = PyLong_AsLongLong(arg);
    if (v == -1 && PyErr_Occurred()) return nullptr;
    self->rv = v;
    Py_RETURN_NONE;
}

PyObject* core_next_rv(CommitCore* self, PyObject*) {
    self->rv += 1;
    return PyLong_FromLongLong(self->rv);
}

PyObject* core_append(CommitCore* self, PyObject* args) {
    PyObject* etype;
    const char* kind;
    PyObject* obj;
    long long rv;
    if (!PyArg_ParseTuple(args, "UsOL", &etype, &kind, &obj, &rv))
        return nullptr;
    std::vector<Entry> staged, evicted;
    Py_INCREF(etype);
    Py_INCREF(obj);
    staged.push_back(Entry{etype, obj, rv});
    splice(self, kind, staged, evicted);
    drop_entries(evicted);
    Py_RETURN_NONE;
}

PyObject* core_bind_batch(CommitCore* self, PyObject* args) {
    PyObject* bucket;
    const char* kind;
    PyObject* bindings;
    if (!PyArg_ParseTuple(args, "O!sO", &PyDict_Type, &bucket, &kind,
                          &bindings))
        return nullptr;
    PyObject* missing = PyList_New(0);
    if (!missing) return nullptr;
    std::vector<Entry> staged, evicted;
    if (bind_batch_body(self, bucket, bindings, missing, staged) < 0) {
        // staged entries still enter the log (the twin appends per item
        // before any raise); callers treat a raise as partially-applied
        splice(self, kind, staged, evicted);
        drop_entries(evicted);
        Py_DECREF(missing);
        return nullptr;
    }
    splice(self, kind, staged, evicted);
    drop_entries(evicted);
    return missing;
}

PyObject* core_create_batch(CommitCore* self, PyObject* args) {
    PyObject* bucket;
    const char* kind;
    PyObject* objs;
    int move;
    if (!PyArg_ParseTuple(args, "O!sOp", &PyDict_Type, &bucket, &kind,
                          &objs, &move))
        return nullptr;
    PyObject* out = PyList_New(0);
    if (!out) return nullptr;
    std::vector<Entry> staged, evicted;
    int rc = create_batch_body(self, bucket, kind, objs, move, out, staged);
    splice(self, kind, staged, evicted);
    drop_entries(evicted);
    if (rc < 0) { Py_DECREF(out); return nullptr; }
    return out;
}

PyObject* core_update_batch(CommitCore* self, PyObject* args) {
    PyObject* bucket;
    const char* kind;
    PyObject* objs;
    if (!PyArg_ParseTuple(args, "O!sO", &PyDict_Type, &bucket, &kind,
                          &objs))
        return nullptr;
    PyObject* out = PyList_New(0);
    if (!out) return nullptr;
    std::vector<Entry> staged, evicted;
    int rc = update_batch_body(self, bucket, objs, out, staged);
    // staged entries still enter the log on error (the twin appends per
    // item before any raise); callers treat a raise as partially-applied
    splice(self, kind, staged, evicted);
    drop_entries(evicted);
    if (rc < 0) { Py_DECREF(out); return nullptr; }
    return out;
}

PyObject* core_delete_batch(CommitCore* self, PyObject* args) {
    PyObject* bucket;
    const char* kind;
    PyObject* keys;
    if (!PyArg_ParseTuple(args, "O!sO", &PyDict_Type, &bucket, &kind,
                          &keys))
        return nullptr;
    PyObject* gone = PyList_New(0);
    if (!gone) return nullptr;
    std::vector<Entry> staged, evicted;
    int rc = delete_batch_body(self, bucket, keys, gone, staged);
    splice(self, kind, staged, evicted);
    drop_entries(evicted);
    if (rc < 0) { Py_DECREF(gone); return nullptr; }
    return gone;
}

PyObject* core_commit_wave(CommitCore* self, PyObject* args) {
    PyObject* pod_bucket;
    const char* pod_kind;
    PyObject* bindings;
    PyObject* ev_bucket;
    const char* ev_kind;
    PyObject* recs;
    if (!PyArg_ParseTuple(args, "O!sOO!sO", &PyDict_Type, &pod_bucket,
                          &pod_kind, &bindings, &PyDict_Type, &ev_bucket,
                          &ev_kind, &recs))
        return nullptr;
    PyObject* missing = PyList_New(0);
    if (!missing) return nullptr;
    std::vector<Entry> pod_staged, ev_staged, evicted;
    if (bind_batch_body(self, pod_bucket, bindings, missing,
                        pod_staged) < 0) {
        splice(self, pod_kind, pod_staged, evicted);
        drop_entries(evicted);
        Py_DECREF(missing);
        return nullptr;
    }
    int rc = 0;
    Py_ssize_t n_recs = PySequence_Size(recs);
    if (n_recs < 0) { PyErr_Clear(); n_recs = 0; }
    if (n_recs > 0) {
        PyObject* picked = recs;
        PyObject* filtered = nullptr;
        if (PyList_GET_SIZE(missing) > 0) {
            // recs[i] rides bindings[i]: skip the records of vanished pods
            filtered = PyList_New(0);
            PyObject* bseq = filtered == nullptr ? nullptr
                : PySequence_Fast(bindings, "bindings must be a sequence");
            PyObject* rseq = bseq == nullptr ? nullptr
                : PySequence_Fast(recs, "recs must be a sequence");
            if (rseq == nullptr) {
                Py_XDECREF(bseq); Py_XDECREF(filtered);
                Py_DECREF(missing);
                splice(self, pod_kind, pod_staged, evicted);
                drop_entries(evicted);
                return nullptr;
            }
            Py_ssize_t nb = PySequence_Fast_GET_SIZE(bseq);
            Py_ssize_t nr = PySequence_Fast_GET_SIZE(rseq);
            for (Py_ssize_t i = 0; i < nb && i < nr && rc == 0; ++i) {
                PyObject* key =
                    PyTuple_GET_ITEM(PySequence_Fast_GET_ITEM(bseq, i), 0);
                int found = PySequence_Contains(missing, key);
                if (found < 0) rc = -1;
                else if (found == 0 &&
                         PyList_Append(filtered,
                                       PySequence_Fast_GET_ITEM(rseq, i)) < 0)
                    rc = -1;
            }
            Py_DECREF(bseq);
            Py_DECREF(rseq);
            picked = filtered;
        }
        if (rc == 0)
            rc = create_batch_body(self, ev_bucket, ev_kind, picked, 1,
                                   nullptr, ev_staged);
        Py_XDECREF(filtered);
    }
    splice(self, pod_kind, pod_staged, evicted);
    splice(self, ev_kind, ev_staged, evicted);
    drop_entries(evicted);
    if (rc < 0) { Py_DECREF(missing); return nullptr; }
    return missing;
}

// Build one Scheduled EventRecord payload for a landed binding (key,
// node): name = "{name}.{seq:x}", message = the burst commit's exact
// wording. Mirrors store/record.build_scheduled_records field for field
// (the twin-parity tests compare stored objects attribute-wise).
PyObject* build_scheduled_record(PyObject* record_cls, PyObject* key,
                                 PyObject* node, PyObject* component,
                                 long long seq) {
    // cls.__new__(cls): allocate without running the dataclass __init__
    // (exactly the twin's EventRecord.__new__ + attribute fill)
    PyObject* new_m = PyObject_GetAttrString(record_cls, "__new__");
    if (!new_m) return nullptr;
    PyObject* rec = PyObject_CallOneArg(new_m, record_cls);
    Py_DECREF(new_m);
    if (!rec) return nullptr;
    // split "ns/name" (namespaced keys; cluster-scoped fall back whole)
    Py_ssize_t klen = PyUnicode_GET_LENGTH(key);
    Py_ssize_t slash = PyUnicode_FindChar(key, '/', 0, klen, 1);
    PyObject* ns = nullptr;
    PyObject* nm = nullptr;
    int ok = 1;
    if (slash >= 0 && slash + 1 < klen) {
        ns = PyUnicode_Substring(key, 0, slash);
        nm = PyUnicode_Substring(key, slash + 1, klen);
        if (!ns || !nm) ok = 0;
    } else {
        Py_INCREF(V_default);
        ns = V_default;
        Py_INCREF(key);
        nm = key;
    }
    PyObject* name = nullptr;
    PyObject* msg = nullptr;
    if (ok) {
        // lowercase-hex seq suffix ("{name}.{seq:x}"); snprintf because
        // PyUnicode_FromFormat has no long-long hex conversion
        char hexbuf[24];
        snprintf(hexbuf, sizeof hexbuf, "%llx", (unsigned long long)seq);
        name = PyUnicode_FromFormat("%U.%s", nm, hexbuf);
        msg = PyUnicode_FromFormat("Successfully assigned %U to %U",
                                   key, node);
        if (!name || !msg) ok = 0;
    }
    if (ok) {
        struct { PyObject* attr; PyObject* val; } fields[] = {
            {S_name, name}, {S_namespace, ns},
            {S_involved_kind, V_Pod}, {S_involved_key, key},
            {S_type, V_Normal}, {S_reason, V_Scheduled},
            {S_message, msg}, {S_count, ONE},
            {S_component, component}, {S_resource_version, ZERO},
        };
        for (auto& f : fields) {
            if (PyObject_SetAttr(rec, f.attr, f.val) < 0) { ok = 0; break; }
        }
    }
    Py_XDECREF(ns);
    Py_XDECREF(nm);
    Py_XDECREF(name);
    Py_XDECREF(msg);
    if (!ok) { Py_XDECREF(rec); return nullptr; }
    return rec;
}

PyObject* core_commit_wave_binds(CommitCore* self, PyObject* args) {
    // commit_wave with the Scheduled payloads built HERE (one native
    // call, zero per-pod Python on the commit thread): binding i's
    // record is named seq0+i; vanished pods consume their seq but emit
    // nothing, exactly like the serial path that never reaches its
    // Scheduled event. Twin: PyCommitCore.commit_wave_binds.
    PyObject* pod_bucket;
    const char* pod_kind;
    PyObject* bindings;
    PyObject* ev_bucket;
    const char* ev_kind;
    PyObject* record_cls;
    PyObject* component;
    long long seq0;
    if (!PyArg_ParseTuple(args, "O!sOO!sOUL", &PyDict_Type, &pod_bucket,
                          &pod_kind, &bindings, &PyDict_Type, &ev_bucket,
                          &ev_kind, &record_cls, &component, &seq0))
        return nullptr;
    PyObject* missing = PyList_New(0);
    if (!missing) return nullptr;
    std::vector<Entry> pod_staged, ev_staged, evicted;
    if (bind_batch_body(self, pod_bucket, bindings, missing,
                        pod_staged) < 0) {
        splice(self, pod_kind, pod_staged, evicted);
        drop_entries(evicted);
        Py_DECREF(missing);
        return nullptr;
    }
    int rc = 0;
    PyObject* seq = PySequence_Fast(bindings, "bindings must be a sequence");
    if (!seq) rc = -1;
    PyObject* miss_set = nullptr;
    if (rc == 0 && PyList_GET_SIZE(missing) > 0) {
        miss_set = PySet_New(missing);
        if (!miss_set) rc = -1;
    }
    Py_ssize_t n = rc == 0 ? PySequence_Fast_GET_SIZE(seq) : 0;
    for (Py_ssize_t i = 0; i < n && rc == 0; ++i) {
        PyObject* pair = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            PyErr_SetString(PyExc_TypeError, "binding must be (key, node)");
            rc = -1;
            break;
        }
        PyObject* key = PyTuple_GET_ITEM(pair, 0);
        PyObject* node = PyTuple_GET_ITEM(pair, 1);
        if (miss_set != nullptr) {
            int found = PySet_Contains(miss_set, key);
            if (found < 0) { rc = -1; break; }
            if (found) continue;     // vanished: seq consumed, no record
        }
        PyObject* rec = build_scheduled_record(record_cls, key, node,
                                               component, seq0 + i);
        if (!rec) { rc = -1; break; }
        // create_batch body for ONE prebuilt record (move=True: the
        // record was born here, ownership transfers to the bucket)
        PyObject* rkey = PyObject_GetAttr(rec, S_key);
        int dup = rkey == nullptr ? -1 : PyDict_Contains(ev_bucket, rkey);
        if (dup != 0) {
            if (dup > 0)
                PyErr_Format(self->already_exc, "%s/%U", ev_kind, rkey);
            Py_XDECREF(rkey);
            Py_DECREF(rec);
            rc = -1;
            break;
        }
        long long rv = assign_rv(self, rec);
        if (rv < 0 || PyDict_SetItem(ev_bucket, rkey, rec) < 0) {
            Py_DECREF(rkey);
            Py_DECREF(rec);
            rc = -1;
            break;
        }
        Py_DECREF(rkey);
        Py_INCREF(S_ADDED);
        ev_staged.push_back(Entry{S_ADDED, rec, rv});  // rec ref moves
    }
    Py_XDECREF(miss_set);
    Py_XDECREF(seq);
    splice(self, pod_kind, pod_staged, evicted);
    splice(self, ev_kind, ev_staged, evicted);
    drop_entries(evicted);
    if (rc < 0) { Py_DECREF(missing); return nullptr; }
    return missing;
}

PyObject* core_flush(CommitCore* self, PyObject*) {
    long long dropped = 0;
    {
        GilRelease gil;
        std::lock_guard<std::mutex> lk(*self->mu);
        for (auto& kv : *self->logs) {
            KindLog& log = kv.second;
            if (log.flushed >= log.end()) continue;
            log.flushed = log.end();
            auto it = self->by_kind->find(kv.first);
            if (it == self->by_kind->end()) continue;
            for (long long wid : it->second) {
                Watcher& w = self->watchers->at(wid);
                if (w.resync) continue;
                long long backlog = log.flushed - w.cursor;
                if (w.cursor < log.start || backlog > self->ring_size) {
                    dropped += backlog;
                    w.cursor = log.flushed;
                    w.resync = true;
                }
            }
        }
        self->cv->notify_all();
    }
    return PyLong_FromLongLong(dropped);
}

PyObject* core_attach(CommitCore* self, PyObject* args) {
    const char* kind;
    PyObject* since = Py_None;
    PyObject* selector_obj = Py_None;
    if (!PyArg_ParseTuple(args, "s|OO", &kind, &since, &selector_obj))
        return nullptr;
    long long since_rv = 0;
    bool has_since = since != Py_None;
    if (has_since) {
        since_rv = PyLong_AsLongLong(since);
        if (since_rv == -1 && PyErr_Occurred()) return nullptr;
    }
    // selector: opaque interest key; None joins the kind's default class
    std::string selector;
    if (selector_obj != Py_None) {
        const char* s = PyUnicode_AsUTF8(selector_obj);
        if (s == nullptr) return nullptr;
        selector = s;
    }
    long long wid = -1;
    bool expired = false;
    {
        GilRelease gil;
        std::lock_guard<std::mutex> lk(*self->mu);
        KindLog& log = kind_log(self, kind);
        long long cursor;
        if (!has_since) {
            cursor = log.end();
        } else if (!log.entries.empty() &&
                   since_rv < log.entries.front().rv - 1) {
            expired = true;
            cursor = 0;
        } else {
            // first absolute index with rv > since_rv (rvs are increasing)
            long long lo = 0, hi = (long long)log.entries.size();
            while (lo < hi) {
                long long mid = (lo + hi) / 2;
                if (log.entries[(size_t)mid].rv > since_rv) hi = mid;
                else lo = mid + 1;
            }
            cursor = log.start + lo;
        }
        if (!expired) {
            wid = self->next_wid++;
            Watcher w{kind, cursor};
            w.cls = join_class(self, kind, selector, log);
            (*self->watchers)[wid] = w;
            (*self->by_kind)[kind].push_back(wid);
        }
    }
    if (expired) {
        PyErr_Format(self->expired_exc, "%s: rv %lld older than log window",
                     kind, since_rv);
        return nullptr;
    }
    return PyLong_FromLongLong(wid);
}

PyObject* core_detach(CommitCore* self, PyObject* arg) {
    long long wid = PyLong_AsLongLong(arg);
    if (wid == -1 && PyErr_Occurred()) return nullptr;
    std::vector<PyObject*> stale;
    {
        GilRelease gil;
        std::lock_guard<std::mutex> lk(*self->mu);
        auto it = self->watchers->find(wid);
        if (it != self->watchers->end()) {
            auto& lst = (*self->by_kind)[it->second.kind];
            for (auto v = lst.begin(); v != lst.end(); ++v) {
                if (*v == wid) { lst.erase(v); break; }
            }
            // attach/detach move a refcount, never a backlog: the last
            // member leaving frees the class and its caches
            leave_class(self, it->second.cls, stale);
            self->watchers->erase(it);
        }
        self->cv->notify_all();
    }
    for (PyObject* o : stale) Py_DECREF(o);
    Py_RETURN_NONE;
}

// Shared wait-and-pick half of poll/poll_bytes. On return: `picked`
// holds OWNED entry refs, `cached_ev`/`cached_ln` hold OWNED class-slot
// refs (or nullptr) parallel to `picked`, and `stale` holds OWNED refs
// of cache slots the log ring evicted — the caller releases all of them
// with the GIL held. The shared-hit counter rides the pick (line hits in
// bytes mode, Event hits otherwise), matching PyCommitCore._poll_pick.
struct PickResult {
    bool expired = false;
    bool evicted_window = false;
    std::string kind;
    long long c0 = 0;
    SubClass* cls = nullptr;
    std::vector<Entry> picked;
    std::vector<PyObject*> cached_ev;
    std::vector<PyObject*> cached_ln;
    std::vector<PyObject*> stale;
};

void poll_pick(CommitCore* self, long long wid, bool forever,
               double timeout, long long limit, bool bytes_mode,
               PickResult& r) {
    GilRelease gil;
    std::unique_lock<std::mutex> lk(*self->mu);
    auto deadline = std::chrono::steady_clock::now() +
        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
            std::chrono::duration<double>(timeout > 0 ? timeout : 0));
    for (;;) {
        auto it = self->watchers->find(wid);
        if (it == self->watchers->end()) break;   // stopped -> []
        Watcher& w = it->second;
        r.kind = w.kind;
        if (w.resync) { r.expired = true; break; }
        KindLog& log = kind_log(self, r.kind);
        if (w.cursor < log.start) {
            // the ring evicted entries this watcher never consumed
            w.resync = true;
            r.expired = r.evicted_window = true;
            break;
        }
        long long avail = log.flushed - w.cursor;
        if (avail > 0) {
            long long n = avail < limit ? avail : limit;
            size_t lo = (size_t)(w.cursor - log.start);
            // take raw pointers under the mutex (eviction can't run
            // while we hold it), incref below before releasing it
            for (long long i = 0; i < n; ++i)
                r.picked.push_back(log.entries[lo + (size_t)i]);
            r.c0 = w.cursor;
            w.cursor += n;
            r.cls = w.cls;
            if (r.cls == nullptr) {
                // old-shape private watcher: every pick materializes
                self->stat_mat += n;
            } else {
                class_align(r.cls, log, r.stale);
                size_t base = (size_t)(r.c0 - r.cls->cache_start);
                long long hits = 0;
                for (long long i = 0; i < n; ++i) {
                    PyObject* ce = r.cls->evs[base + (size_t)i];
                    PyObject* cl = r.cls->lines[base + (size_t)i];
                    r.cached_ev.push_back(ce);
                    r.cached_ln.push_back(cl);
                    if ((bytes_mode ? cl : ce) != nullptr) hits += 1;
                }
                self->stat_shared += hits;
            }
            break;
        }
        if (!forever && timeout <= 0) break;
        if (forever) {
            self->cv->wait(lk);
        } else if (self->cv->wait_until(lk, deadline) ==
                   std::cv_status::timeout) {
            timeout = 0;   // one last non-blocking re-check
        }
    }
    if (!r.picked.empty()) {
        // refcount-only work with the GIL re-acquired while STILL
        // holding the mutex (no allocations here — see lock contract);
        // at interpreter shutdown, release the mutex before parking
        if (gil.finalizing()) lk.unlock();
        gil.block();
        for (Entry& e : r.picked) {
            Py_INCREF(e.etype);
            Py_INCREF(e.obj);
        }
        for (PyObject* o : r.cached_ev) Py_XINCREF(o);
        for (PyObject* o : r.cached_ln) Py_XINCREF(o);
    }
}

// First-writer-wins cache fill. `ins_ev[i]` / `ins_ln[i]` are BORROWED
// candidates for absolute seq c0+i (nullptr = nothing to install); slots
// already filled by a racing classmate keep the racer's value-identical
// object. `installed_ev[i]` is set for events THIS call installed — the
// fan-out sink fires for exactly those, so lag is observed once per
// event per class. The counters ride the same mutex hold. GIL is
// re-acquired under the mutex for refcount-only work (lock contract).
void install_shared(CommitCore* self, SubClass* cls, long long c0,
                    const std::vector<PyObject*>& ins_ev,
                    const std::vector<PyObject*>& ins_ln,
                    long long add_mat, long long add_enc,
                    long long add_bytes,
                    std::vector<unsigned char>* installed_ev) {
    GilRelease gil;
    std::unique_lock<std::mutex> lk(*self->mu);
    if (gil.finalizing()) lk.unlock();
    gil.block();
    self->stat_mat += add_mat;
    self->stat_enc += add_enc;
    self->stat_bytes += add_bytes;
    if (cls != nullptr) {
        for (size_t i = 0; i < ins_ev.size(); ++i) {
            if (ins_ev[i] == nullptr) continue;
            long long ci = c0 + (long long)i - cls->cache_start;
            if (ci >= 0 && ci < (long long)cls->evs.size()
                && cls->evs[(size_t)ci] == nullptr) {
                Py_INCREF(ins_ev[i]);
                cls->evs[(size_t)ci] = ins_ev[i];
                if (installed_ev != nullptr) (*installed_ev)[i] = 1;
            }
        }
        for (size_t i = 0; i < ins_ln.size(); ++i) {
            if (ins_ln[i] == nullptr) continue;
            long long ci = c0 + (long long)i - cls->cache_start;
            if (ci >= 0 && ci < (long long)cls->lines.size()
                && cls->lines[(size_t)ci] == nullptr) {
                Py_INCREF(ins_ln[i]);
                cls->lines[(size_t)ci] = ins_ln[i];
            }
        }
    }
    lk.unlock();
}

// fan-out sink: commit->copy-out lag per event, observed here on the
// CONSUMER's thread (mirror of PyCommitCore._sink_fire). A sink failure
// is unraisable, never a delivery failure. `evs` are borrowed.
void fire_sink(CommitCore* self, PyObject* kind_str,
               const std::vector<PyObject*>& evs,
               const std::vector<double>& tss) {
    if (self->fanout_sink == nullptr || evs.empty() || kind_str == nullptr)
        return;
    PyObject* ev_list = PyList_New((Py_ssize_t)evs.size());
    PyObject* lags =
        ev_list != nullptr ? PyList_New((Py_ssize_t)evs.size()) : nullptr;
    bool ok = lags != nullptr;
    double now = mono_now();
    for (size_t i = 0; ok && i < evs.size(); ++i) {
        Py_INCREF(evs[i]);
        PyList_SET_ITEM(ev_list, (Py_ssize_t)i, evs[i]);
        PyObject* lag = PyFloat_FromDouble(now - tss[i]);
        if (lag == nullptr) ok = false;
        else PyList_SET_ITEM(lags, (Py_ssize_t)i, lag);
    }
    if (ok) {
        PyObject* res = PyObject_CallFunctionObjArgs(
            self->fanout_sink, kind_str, ev_list, lags, nullptr);
        if (res == nullptr) PyErr_WriteUnraisable(self->fanout_sink);
        else Py_DECREF(res);
    } else {
        PyErr_Clear();
    }
    Py_XDECREF(ev_list);
    Py_XDECREF(lags);
}

int parse_poll_args(PyObject* args, long long* wid, bool* forever,
                    double* timeout, long long* limit) {
    PyObject* timeout_obj;
    if (!PyArg_ParseTuple(args, "LOL", wid, &timeout_obj, limit))
        return -1;
    *forever = timeout_obj == Py_None;
    *timeout = 0.0;
    if (!*forever) {
        *timeout = PyFloat_AsDouble(timeout_obj);
        if (*timeout == -1.0 && PyErr_Occurred()) return -1;
    }
    return 0;
}

int raise_expired(CommitCore* self, const PickResult& r) {
    if (!r.expired) return 0;
    if (r.evicted_window)
        PyErr_Format(self->expired_exc,
                     "%s: rv window evicted before copy-out",
                     r.kind.c_str());
    else
        PyErr_Format(self->expired_exc,
                     "%s: watch dropped (resync required)", r.kind.c_str());
    return -1;
}

PyObject* core_poll(CommitCore* self, PyObject* args) {
    long long wid, limit;
    bool forever;
    double timeout;
    if (parse_poll_args(args, &wid, &forever, &timeout, &limit) < 0)
        return nullptr;
    PickResult r;
    poll_pick(self, wid, forever, timeout, limit, false, r);
    for (PyObject* o : r.stale) Py_DECREF(o);
    r.stale.clear();
    if (raise_expired(self, r) < 0) return nullptr;
    size_t n = r.picked.size();
    PyObject* out = PyList_New((Py_ssize_t)n);
    PyObject* kind_str = nullptr;
    if (out != nullptr && n > 0)
        kind_str = PyUnicode_FromStringAndSize(r.kind.data(),
                                               (Py_ssize_t)r.kind.size());
    std::vector<unsigned char> miss(n, 0);
    size_t n_miss = 0;
    for (size_t i = 0; i < n; ++i) {
        Entry& e = r.picked[i];
        PyObject* ev = nullptr;
        if (out != nullptr && kind_str != nullptr) {
            if (r.cls != nullptr && r.cached_ev[i] != nullptr) {
                // class cache hit: our owned ref transfers into the list
                ev = r.cached_ev[i];
                r.cached_ev[i] = nullptr;
            } else {
                PyObject* rvo = PyLong_FromLongLong(e.rv);
                if (rvo != nullptr) {
                    ev = PyObject_CallFunctionObjArgs(
                        self->event_cls, e.etype, kind_str, e.obj, rvo,
                        nullptr);
                    Py_DECREF(rvo);
                }
                if (ev != nullptr) { miss[i] = 1; ++n_miss; }
            }
        }
        Py_DECREF(e.etype);
        Py_DECREF(e.obj);
        if (ev == nullptr) {
            Py_CLEAR(out);
            continue;   // keep releasing the remaining picked refs
        }
        PyList_SET_ITEM(out, (Py_ssize_t)i, ev);
    }
    // release cache refs not consumed (hits on the error path + lines)
    for (PyObject* o : r.cached_ev) Py_XDECREF(o);
    for (PyObject* o : r.cached_ln) Py_XDECREF(o);
    if (out != nullptr && n > 0) {
        if (r.cls != nullptr) {
            if (n_miss > 0) {
                std::vector<PyObject*> ins_ev(n, nullptr), no_ln;
                for (size_t i = 0; i < n; ++i)
                    if (miss[i]) ins_ev[i] = PyList_GET_ITEM(out, i);
                std::vector<unsigned char> installed(n, 0);
                install_shared(self, r.cls, r.c0, ins_ev, no_ln,
                               (long long)n_miss, 0, 0, &installed);
                std::vector<PyObject*> sink_evs;
                std::vector<double> tss;
                for (size_t i = 0; i < n; ++i) {
                    if (installed[i]) {
                        sink_evs.push_back(PyList_GET_ITEM(out, i));
                        tss.push_back(r.picked[i].ts);
                    }
                }
                fire_sink(self, kind_str, sink_evs, tss);
            }
        } else {
            std::vector<PyObject*> sink_evs;
            std::vector<double> tss;
            for (size_t i = 0; i < n; ++i) {
                sink_evs.push_back(PyList_GET_ITEM(out, i));
                tss.push_back(r.picked[i].ts);
            }
            fire_sink(self, kind_str, sink_evs, tss);
        }
    }
    Py_XDECREF(kind_str);
    return out;
}

PyObject* core_poll_bytes(CommitCore* self, PyObject* args) {
    long long wid, limit;
    bool forever;
    double timeout;
    if (parse_poll_args(args, &wid, &forever, &timeout, &limit) < 0)
        return nullptr;
    if (self->wire_encoder == nullptr) {
        PyErr_SetString(PyExc_RuntimeError, "wire encoder not set");
        return nullptr;
    }
    PickResult r;
    poll_pick(self, wid, forever, timeout, limit, true, r);
    for (PyObject* o : r.stale) Py_DECREF(o);
    r.stale.clear();
    if (raise_expired(self, r) < 0) return nullptr;
    size_t n = r.picked.size();
    PyObject* out = PyList_New((Py_ssize_t)n);
    PyObject* kind_str = nullptr;
    if (out != nullptr && n > 0)
        kind_str = PyUnicode_FromStringAndSize(r.kind.data(),
                                               (Py_ssize_t)r.kind.size());
    // events materialized by this call (owned): degenerate mode makes one
    // per entry for the sink; shared mode only where the class had none
    std::vector<PyObject*> made_ev(n, nullptr);
    std::vector<unsigned char> ln_miss(n, 0);
    long long n_enc = 0, n_mat = 0, nbytes = 0;
    for (size_t i = 0; i < n; ++i) {
        Entry& e = r.picked[i];
        PyObject* ln = nullptr;
        if (out != nullptr && kind_str != nullptr) {
            if (r.cls != nullptr && r.cached_ln[i] != nullptr) {
                // serialize-once hit: the shared bytes object streams out
                ln = r.cached_ln[i];
                r.cached_ln[i] = nullptr;
            } else {
                PyObject* rvo = PyLong_FromLongLong(e.rv);
                if (rvo != nullptr) {
                    ln = PyObject_CallFunctionObjArgs(
                        self->wire_encoder, e.etype, e.obj, rvo, nullptr);
                    if (ln != nullptr &&
                        (r.cls == nullptr || r.cached_ev[i] == nullptr)) {
                        made_ev[i] = PyObject_CallFunctionObjArgs(
                            self->event_cls, e.etype, kind_str, e.obj, rvo,
                            nullptr);
                        if (made_ev[i] == nullptr) Py_CLEAR(ln);
                        else ++n_mat;
                    }
                    Py_DECREF(rvo);
                }
                if (ln != nullptr) { ln_miss[i] = 1; ++n_enc; }
            }
            if (ln != nullptr) {
                Py_ssize_t sz = PyObject_Size(ln);
                if (sz >= 0) nbytes += sz;
                else PyErr_Clear();
            }
        }
        Py_DECREF(e.etype);
        Py_DECREF(e.obj);
        if (ln == nullptr) {
            Py_CLEAR(out);
            continue;   // keep releasing the remaining picked refs
        }
        PyList_SET_ITEM(out, (Py_ssize_t)i, ln);
    }
    for (PyObject* o : r.cached_ev) Py_XDECREF(o);
    for (PyObject* o : r.cached_ln) Py_XDECREF(o);
    if (out != nullptr && n > 0) {
        if (r.cls != nullptr) {
            std::vector<PyObject*> ins_ln(n, nullptr);
            for (size_t i = 0; i < n; ++i)
                if (ln_miss[i]) ins_ln[i] = PyList_GET_ITEM(out, i);
            std::vector<unsigned char> installed(n, 0);
            install_shared(self, r.cls, r.c0, made_ev, ins_ln,
                           n_mat, n_enc, nbytes, &installed);
            std::vector<PyObject*> sink_evs;
            std::vector<double> tss;
            for (size_t i = 0; i < n; ++i) {
                if (installed[i]) {
                    sink_evs.push_back(made_ev[i]);
                    tss.push_back(r.picked[i].ts);
                }
            }
            fire_sink(self, kind_str, sink_evs, tss);
        } else {
            std::vector<PyObject*> none_ev, none_ln;
            install_shared(self, nullptr, r.c0, none_ev, none_ln,
                           0, n_enc, nbytes, nullptr);
            std::vector<PyObject*> sink_evs;
            std::vector<double> tss;
            for (size_t i = 0; i < n; ++i) {
                if (made_ev[i] != nullptr) {
                    sink_evs.push_back(made_ev[i]);
                    tss.push_back(r.picked[i].ts);
                }
            }
            fire_sink(self, kind_str, sink_evs, tss);
        }
    }
    for (PyObject* o : made_ev) Py_XDECREF(o);
    Py_XDECREF(kind_str);
    return out;
}

PyObject* core_set_fanout_sink(CommitCore* self, PyObject* arg) {
    PyObject* old = self->fanout_sink;
    if (arg == Py_None) {
        self->fanout_sink = nullptr;
    } else {
        Py_INCREF(arg);
        self->fanout_sink = arg;
    }
    Py_XDECREF(old);
    Py_RETURN_NONE;
}

PyObject* core_set_wire_encoder(CommitCore* self, PyObject* arg) {
    PyObject* old = self->wire_encoder;
    if (arg == Py_None) {
        self->wire_encoder = nullptr;
    } else {
        Py_INCREF(arg);
        self->wire_encoder = arg;
    }
    Py_XDECREF(old);
    Py_RETURN_NONE;
}

PyObject* core_set_shared_classes(CommitCore* self, PyObject* arg) {
    int v = PyObject_IsTrue(arg);
    if (v < 0) return nullptr;
    self->shared_classes = v != 0;
    Py_RETURN_NONE;
}

PyObject* core_fanout_stats(CommitCore* self, PyObject*) {
    // snapshot under the mutex into plain C++ rows, build Python objects
    // strictly outside it (allocations never run under the mutex)
    struct Row {
        std::string kind, selector;
        long long members, cached_events, cached_lines, w0, w1;
    };
    std::vector<Row> rows;
    long long mat, shared, enc, nbytes;
    bool sc;
    {
        GilRelease gil;
        std::lock_guard<std::mutex> lk(*self->mu);
        mat = self->stat_mat;
        shared = self->stat_shared;
        enc = self->stat_enc;
        nbytes = self->stat_bytes;
        sc = self->shared_classes;
        for (auto& kv : *self->classes) {
            SubClass& c = kv.second;
            Row row;
            row.kind = c.kind;
            row.selector = c.selector;
            row.members = c.members;
            row.cached_events = 0;
            row.cached_lines = 0;
            for (PyObject* o : c.evs)
                if (o != nullptr) row.cached_events += 1;
            for (PyObject* o : c.lines)
                if (o != nullptr) row.cached_lines += 1;
            row.w0 = c.cache_start;
            row.w1 = c.cache_start + (long long)c.evs.size();
            rows.push_back(std::move(row));
        }
    }
    std::sort(rows.begin(), rows.end(), [](const Row& a, const Row& b) {
        return a.kind != b.kind ? a.kind < b.kind : a.selector < b.selector;
    });
    PyObject* cls_list = PyList_New((Py_ssize_t)rows.size());
    if (cls_list == nullptr) return nullptr;
    for (size_t i = 0; i < rows.size(); ++i) {
        Row& row = rows[i];
        PyObject* d = Py_BuildValue(
            "{s:s, s:s, s:L, s:L, s:L, s:[LL]}",
            "kind", row.kind.c_str(), "selector", row.selector.c_str(),
            "members", row.members, "cached_events", row.cached_events,
            "cached_lines", row.cached_lines, "window", row.w0, row.w1);
        if (d == nullptr) { Py_DECREF(cls_list); return nullptr; }
        PyList_SET_ITEM(cls_list, (Py_ssize_t)i, d);
    }
    PyObject* out = Py_BuildValue(
        "{s:O, s:L, s:L, s:L, s:L, s:O}",
        "shared_classes", sc ? Py_True : Py_False,
        "materializations", mat, "shared_hits", shared,
        "line_encodes", enc, "bytes_served", nbytes,
        "classes", cls_list);
    Py_DECREF(cls_list);
    return out;
}

PyObject* core_backlog(CommitCore* self, PyObject* arg) {
    long long wid = PyLong_AsLongLong(arg);
    if (wid == -1 && PyErr_Occurred()) return nullptr;
    long long n = 0;
    {
        GilRelease gil;
        std::lock_guard<std::mutex> lk(*self->mu);
        auto it = self->watchers->find(wid);
        if (it != self->watchers->end()) {
            KindLog& log = kind_log(self, it->second.kind);
            long long cur = it->second.cursor;
            if (cur < log.start) cur = log.start;
            n = log.flushed - cur;
            if (n < 0) n = 0;
        }
    }
    return PyLong_FromLongLong(n);
}

PyObject* core_log_window(CommitCore* self, PyObject* arg) {
    const char* kind = PyUnicode_AsUTF8(arg);
    if (!kind) return nullptr;
    long long first = 0, last = 0;
    {
        GilRelease gil;
        std::lock_guard<std::mutex> lk(*self->mu);
        KindLog& log = kind_log(self, kind);
        if (!log.entries.empty()) {
            first = log.entries.front().rv;
            last = log.entries.back().rv;
        }
    }
    return Py_BuildValue("(LL)", first, last);
}

// -- lifecycle --------------------------------------------------------------
PyObject* core_new(PyTypeObject* type, PyObject* args, PyObject*) {
    long long log_size, ring_size;
    PyObject* event_cls;
    PyObject* expired_exc;
    PyObject* already_exc;
    if (!PyArg_ParseTuple(args, "LLOOO", &log_size, &ring_size, &event_cls,
                          &expired_exc, &already_exc))
        return nullptr;
    CommitCore* self = (CommitCore*)type->tp_alloc(type, 0);
    if (!self) return nullptr;
    self->rv = 0;
    self->log_size = log_size;
    self->ring_size = ring_size;
    self->next_wid = 0;
    Py_INCREF(event_cls);
    self->event_cls = event_cls;
    Py_INCREF(expired_exc);
    self->expired_exc = expired_exc;
    Py_INCREF(already_exc);
    self->already_exc = already_exc;
    self->logs = new std::unordered_map<std::string, KindLog>();
    self->watchers = new std::unordered_map<long long, Watcher>();
    self->by_kind =
        new std::unordered_map<std::string, std::vector<long long>>();
    self->classes = new std::unordered_map<std::string, SubClass>();
    self->shared_classes = true;
    self->fences = new std::unordered_map<std::string, long long>();
    self->mu = new std::mutex();
    self->cv = new std::condition_variable();
    self->fanout_sink = nullptr;
    self->wire_encoder = nullptr;
    self->stat_mat = 0;
    self->stat_shared = 0;
    self->stat_enc = 0;
    self->stat_bytes = 0;
    return (PyObject*)self;
}

void core_dealloc(CommitCore* self) {
    if (self->logs) {
        // dealloc may run on the FINALIZING thread (shutdown GC), where
        // GilRelease would park forever — lock with the GIL held instead.
        // Safe here: dealloc implies refcount 0, so no poll() is active
        // (an in-flight call holds a reference through its frame), and no
        // mutex section can therefore be waiting on the GIL.
        bool waiters;
        {
            std::lock_guard<std::mutex> lk(*self->mu);
            waiters = !self->watchers->empty();
            self->cv->notify_all();
        }
        for (auto& kv : *self->logs) {
            for (Entry& e : kv.second.entries) {
                Py_DECREF(e.etype);
                Py_DECREF(e.obj);
            }
        }
        for (auto& kv : *self->classes) {
            for (PyObject* o : kv.second.evs) Py_XDECREF(o);
            for (PyObject* o : kv.second.lines) Py_XDECREF(o);
        }
        delete self->logs;
        delete self->by_kind;
        delete self->classes;
        delete self->fences;
        if (!waiters) {
            // a watcher that was never detached may still be blocked in
            // poll (a daemon thread at teardown): destroying a mutex/cv
            // with waiters is UB, so in that case the three small C++
            // objects are deliberately leaked
            delete self->watchers;
            delete self->mu;
            delete self->cv;
        }
    }
    Py_XDECREF(self->event_cls);
    Py_XDECREF(self->expired_exc);
    Py_XDECREF(self->already_exc);
    Py_XDECREF(self->fanout_sink);
    Py_XDECREF(self->wire_encoder);
    Py_TYPE(self)->tp_free((PyObject*)self);
}

PyMethodDef core_methods[] = {
    {"rv", (PyCFunction)core_rv, METH_NOARGS, "current resourceVersion"},
    {"set_rv", (PyCFunction)core_set_rv, METH_O, "set the rv counter"},
    {"next_rv", (PyCFunction)core_next_rv, METH_NOARGS,
     "increment and return the rv counter"},
    {"append", (PyCFunction)core_append, METH_VARARGS,
     "append(etype, kind, obj, rv) — one pending log entry"},
    {"bind_batch", (PyCFunction)core_bind_batch, METH_VARARGS,
     "bind_batch(bucket, kind, bindings) -> missing keys"},
    {"create_batch", (PyCFunction)core_create_batch, METH_VARARGS,
     "create_batch(bucket, kind, objs, move) -> stored objects"},
    {"update_batch", (PyCFunction)core_update_batch, METH_VARARGS,
     "update_batch(bucket, kind, objs) -> stored snapshots (batched "
     "MODIFIED; per-item NotFound/rv-CAS refusal is the store's pre-scan)"},
    {"delete_batch", (PyCFunction)core_delete_batch, METH_VARARGS,
     "delete_batch(bucket, kind, keys) -> popped objects (batched "
     "DELETED; missing keys skipped)"},
    {"commit_wave", (PyCFunction)core_commit_wave, METH_VARARGS,
     "commit_wave(pod_bucket, pod_kind, bindings, ev_bucket, ev_kind, "
     "recs) -> missing keys"},
    {"commit_wave_binds", (PyCFunction)core_commit_wave_binds, METH_VARARGS,
     "commit_wave_binds(pod_bucket, pod_kind, bindings, ev_bucket, "
     "ev_kind, record_cls, component, seq0) -> missing keys; builds the "
     "Scheduled audit payloads natively for every landed binding"},
    {"flush", (PyCFunction)core_flush, METH_NOARGS,
     "publish pending entries to watchers -> events dropped"},
    {"attach", (PyCFunction)core_attach, METH_VARARGS,
     "attach(kind, since_rv=None, selector=None) -> watcher id (raises "
     "on expired rv); identical (kind, selector) watchers share one "
     "subscription class"},
    {"detach", (PyCFunction)core_detach, METH_O, "remove a watcher"},
    {"poll", (PyCFunction)core_poll, METH_VARARGS,
     "poll(wid, timeout, limit) -> list[Event] (GIL released while "
     "blocked; raises ExpiredError when dropped); events materialize "
     "once per subscription class"},
    {"poll_bytes", (PyCFunction)core_poll_bytes, METH_VARARGS,
     "poll_bytes(wid, timeout, limit) -> list[bytes] — pre-encoded wire "
     "lines from the class's serialize-once byte ring"},
    {"set_wire_encoder", (PyCFunction)core_set_wire_encoder, METH_O,
     "set_wire_encoder(callable|None) — (etype, obj, rv) -> wire bytes "
     "for the serialize-once byte ring"},
    {"set_shared_classes", (PyCFunction)core_set_shared_classes, METH_O,
     "set_shared_classes(bool) — False = old-shape per-watcher "
     "degenerate mode for FUTURE attaches (differential tests)"},
    {"fanout_stats", (PyCFunction)core_fanout_stats, METH_NOARGS,
     "watch-plane snapshot: counters + one row per subscription class"},
    {"backlog", (PyCFunction)core_backlog, METH_O,
     "published-but-unconsumed events for a watcher"},
    {"set_fanout_sink", (PyCFunction)core_set_fanout_sink, METH_O,
     "set_fanout_sink(callable|None) — observability hook called at poll "
     "copy-out with (kind, events, lags)"},
    {"log_window", (PyCFunction)core_log_window, METH_O,
     "(first rv retained, last rv) of a kind's log ring"},
    {"fence_ok", (PyCFunction)core_fence_ok, METH_VARARGS,
     "fence_ok(scope, token) -> bool: token not superseded for scope"},
    {"advance_fence", (PyCFunction)core_advance_fence, METH_VARARGS,
     "advance_fence(scope, token) -> bool: record the new maximum "
     "(False when token is already superseded)"},
    {"fence_token", (PyCFunction)core_fence_token, METH_O,
     "current fencing token recorded for a scope (0 when none)"},
    {"fence_table", (PyCFunction)core_fence_table, METH_NOARGS,
     "scope -> token snapshot (demotion carryover / debug)"},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject CommitCoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    .tp_name = "_commitcore.CommitCore",
    .tp_basicsize = sizeof(CommitCore),
    .tp_dealloc = (destructor)core_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = PyDoc_STR("versioned store write log + watch fan-out core"),
    .tp_methods = core_methods,
    .tp_new = core_new,
};

// -- class_signatures: the activeQ drain/encode prologue's hot tuple ---------
// twin: core/tpu_scheduler.TPUScheduler._class_signature — identical
// tuples by construction (the parity tests compare them element-wise)
PyObject* sorted_items(PyObject* d) {
    if (PyDict_Check(d) && PyDict_GET_SIZE(d) == 0) {
        Py_INCREF(EMPTY_TUPLE);
        return EMPTY_TUPLE;
    }
    PyObject* items = PyMapping_Items(d);
    if (!items) return nullptr;
    if (PyList_Sort(items) < 0) { Py_DECREF(items); return nullptr; }
    PyObject* out = PyList_AsTuple(items);
    Py_DECREF(items);
    return out;
}

PyObject* class_signatures(PyObject*, PyObject* arg) {
    PyObject* seq = PySequence_Fast(arg, "pods must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject* out = PyList_New(n);
    if (!out) { Py_DECREF(seq); return nullptr; }
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* p = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* sig = PyTuple_New(8);
        int ok = sig != nullptr;
        if (ok) {
            struct { PyObject* attr; int slot; int sort; } fields[] = {
                {S_namespace, 0, 0}, {S_labels, 1, 1},
                {S_node_selector, 2, 1}, {S_affinity, 3, 0},
                {S_tolerations, 4, 0}, {S_node_name, 5, 0},
                {S_containers, 6, 0}, {S_init_containers, 7, 0},
            };
            for (auto& f : fields) {
                PyObject* v = PyObject_GetAttr(p, f.attr);
                if (!v) { ok = 0; break; }
                if (f.sort) {
                    PyObject* t = sorted_items(v);
                    Py_DECREF(v);
                    if (!t) { ok = 0; break; }
                    v = t;
                }
                PyTuple_SET_ITEM(sig, f.slot, v);
            }
        }
        if (!ok) {
            Py_XDECREF(sig);
            Py_DECREF(out);
            Py_DECREF(seq);
            return nullptr;
        }
        PyList_SET_ITEM(out, i, sig);
    }
    Py_DECREF(seq);
    return out;
}

PyMethodDef module_methods[] = {
    {"class_signatures", (PyCFunction)class_signatures, METH_O,
     "class_signatures(pods) -> [signature tuple per pod] — the batched "
     "twin of TPUScheduler._class_signature"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef commitcore_module = {
    PyModuleDef_HEAD_INIT, "_commitcore",
    "native store commit core (batched write + watch fan-out)", -1,
    module_methods,
};

int intern(PyObject** slot, const char* s) {
    *slot = PyUnicode_InternFromString(s);
    return *slot == nullptr ? -1 : 0;
}

}  // namespace

PyMODINIT_FUNC PyInit__commitcore(void) {
    if (intern(&S_ADDED, "ADDED") < 0 || intern(&S_MODIFIED, "MODIFIED") < 0
        || intern(&S_DELETED, "DELETED") < 0 || intern(&S_clone, "clone") < 0
        || intern(&S_key, "key") < 0 || intern(&S_node_name, "node_name") < 0
        || intern(&S_resource_version, "resource_version") < 0
        || intern(&S_namespace, "namespace") < 0
        || intern(&S_labels, "labels") < 0
        || intern(&S_node_selector, "node_selector") < 0
        || intern(&S_affinity, "affinity") < 0
        || intern(&S_tolerations, "tolerations") < 0
        || intern(&S_containers, "containers") < 0
        || intern(&S_init_containers, "init_containers") < 0
        || intern(&S_name, "name") < 0
        || intern(&S_involved_kind, "involved_kind") < 0
        || intern(&S_involved_key, "involved_key") < 0
        || intern(&S_type, "type") < 0
        || intern(&S_reason, "reason") < 0
        || intern(&S_message, "message") < 0
        || intern(&S_count, "count") < 0
        || intern(&S_component, "component") < 0
        || intern(&V_Pod, "Pod") < 0
        || intern(&V_Normal, "Normal") < 0
        || intern(&V_Scheduled, "Scheduled") < 0
        || intern(&V_default, "default") < 0)
        return nullptr;
    ONE = PyLong_FromLong(1);
    ZERO = PyLong_FromLong(0);
    if (!ONE || !ZERO) return nullptr;
    EMPTY_TUPLE = PyTuple_New(0);
    if (!EMPTY_TUPLE) return nullptr;
    PyObject* copy_mod = PyImport_ImportModule("copy");
    if (!copy_mod) return nullptr;
    DEEPCOPY = PyObject_GetAttrString(copy_mod, "deepcopy");
    Py_DECREF(copy_mod);
    if (!DEEPCOPY) return nullptr;
    if (PyType_Ready(&CommitCoreType) < 0) return nullptr;
    if (PyDict_SetItemString(CommitCoreType.tp_dict, "is_native",
                             Py_True) < 0)
        return nullptr;
    PyObject* m = PyModule_Create(&commitcore_module);
    if (!m) return nullptr;
    Py_INCREF(&CommitCoreType);
    if (PyModule_AddObject(m, "CommitCore", (PyObject*)&CommitCoreType) < 0) {
        Py_DECREF(&CommitCoreType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
