"""Component configuration — KubeSchedulerConfiguration analog.

Mirrors pkg/scheduler/apis/config/types.go:42: scheduler name, algorithm
source (provider | policy file/inline), hard pod-affinity weight, leader
election, preemption switch, percentage of nodes to score, bind timeout —
plus this framework's own switch: the TPUScoring feature gate routing
filter/score through the device kernels.

Round-trips to/from plain dicts (the stand-in for the reference's versioned
serialization, apis/config/v1alpha1) with validation
(apis/config/validation).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict
from typing import Optional

DEFAULT_SCHEDULER_NAME = "default-scheduler"
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 50
DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1
DEFAULT_BIND_TIMEOUT_SECONDS = 100      # scheduler.go:50


@dataclass
class LeaderElectionConfig:
    """component-base config.LeaderElectionConfiguration subset."""
    leader_elect: bool = False
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    lock_object_name: str = "kube-scheduler"


@dataclass
class AlgorithmSource:
    """apis/config/types.go:93 — exactly one of provider / policy."""
    provider: Optional[str] = "DefaultProvider"
    policy_file: Optional[str] = None
    policy_inline: Optional[dict] = None


@dataclass
class SchedulerConfiguration:
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    algorithm_source: AlgorithmSource = field(default_factory=AlgorithmSource)
    hard_pod_affinity_symmetric_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT
    disable_preemption: bool = False
    percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE
    bind_timeout_seconds: float = DEFAULT_BIND_TIMEOUT_SECONDS
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    # feature gates (pkg/features analog); TPUScoring routes filter/score to
    # the device kernels
    feature_gates: dict = field(default_factory=lambda: {"TPUScoring": True})
    plugins_enabled: Optional[list] = None
    # scheduling profiles (round 19 — KubeSchedulerConfiguration.profiles):
    # raw profile dicts ({"schedulerName": ..., "priorities": ...,
    # "rankAwareGang": ..., "gangWeight": ...}); build_profiles() resolves
    # them into a validated profiles.ProfileSet. None = single-profile
    # (scheduler_name + algorithm_source), exactly the pre-profile config.
    profiles: Optional[list] = None

    # -- round trip ----------------------------------------------------------
    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "SchedulerConfiguration":
        cfg = SchedulerConfiguration()
        src = d.get("algorithm_source") or {}
        cfg.algorithm_source = AlgorithmSource(
            provider=src.get("provider", "DefaultProvider"),
            policy_file=src.get("policy_file"),
            policy_inline=src.get("policy_inline"))
        le = d.get("leader_election") or {}
        cfg.leader_election = LeaderElectionConfig(**{
            k: le[k] for k in LeaderElectionConfig.__dataclass_fields__ if k in le})
        for k in ("scheduler_name", "hard_pod_affinity_symmetric_weight",
                  "disable_preemption", "percentage_of_nodes_to_score",
                  "bind_timeout_seconds", "plugins_enabled", "profiles"):
            if k in d:
                setattr(cfg, k, d[k])
        if "feature_gates" in d:
            cfg.feature_gates = dict(cfg.feature_gates, **d["feature_gates"])
        return cfg

    def build_profiles(self):
        """Resolve `profiles` into a validated profiles.ProfileSet, or
        None when the config is single-profile. Validation errors
        (duplicate names, unknown priorities, weight bounds) surface as
        ValidationError, matching the rest of this module."""
        if not self.profiles:
            return None
        from kubernetes_tpu.profiles import ProfileSet
        try:
            return ProfileSet.from_dict({"profiles": self.profiles})
        except ValueError as e:
            raise ValidationError(str(e)) from e

    @staticmethod
    def from_file(path: str) -> "SchedulerConfiguration":
        with open(path) as f:
            return SchedulerConfiguration.from_dict(json.load(f))


class ValidationError(ValueError):
    pass


def validate(cfg: SchedulerConfiguration) -> None:
    """apis/config/validation analog."""
    errs = []
    if not cfg.scheduler_name:
        errs.append("scheduler_name must not be empty")
    if not (0 <= cfg.percentage_of_nodes_to_score <= 100):
        errs.append("percentage_of_nodes_to_score must be in [0, 100]")
    if not (0 <= cfg.hard_pod_affinity_symmetric_weight <= 100):
        errs.append("hard_pod_affinity_symmetric_weight must be in [0, 100]")
    if cfg.bind_timeout_seconds <= 0:
        errs.append("bind_timeout_seconds must be positive")
    src = cfg.algorithm_source
    has_policy = src.policy_file is not None or src.policy_inline is not None
    if src.provider is None and not has_policy:
        errs.append("algorithm_source requires provider or policy")
    if src.policy_file is not None and src.policy_inline is not None:
        errs.append("policy_file and policy_inline are mutually exclusive")
    # provider defaults to DefaultProvider; any OTHER provider alongside a
    # policy is ambiguous (the reference requires exactly one source)
    if has_policy and src.provider not in (None, "DefaultProvider"):
        errs.append("provider and policy are mutually exclusive")
    if cfg.profiles:
        if has_policy:
            errs.append("profiles and policy are mutually exclusive")
        try:
            cfg.build_profiles()
        except ValidationError as e:
            errs.append(str(e))
    if errs:
        raise ValidationError("; ".join(errs))
