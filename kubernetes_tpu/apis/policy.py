"""Scheduler Policy schema — pkg/scheduler/api Policy analog.

Mirrors api/types.go: Policy (:46) with PredicatePolicy (:72),
PriorityPolicy (:82), and ExtenderConfig (:203). Loaded from JSON exactly
like `--policy-config-file` (factory.go:346 CreateFromConfig).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

MAX_PRIORITY = 10      # api/types.go:35
MAX_WEIGHT = (1 << 31) // MAX_PRIORITY  # api/validation: weight*MaxPriority must fit int32


@dataclass
class PredicatePolicy:
    name: str
    # custom-predicate arguments (api/types.go:90 PredicateArgument):
    # {"serviceAffinity": {"labels": [...]}} or
    # {"labelsPresence": {"labels": [...], "presence": bool}}
    argument: Optional[dict] = None


@dataclass
class PriorityPolicy:
    name: str
    weight: int = 1


@dataclass
class ExtenderConfig:
    """api/types.go:203 — out-of-process scheduler webhook."""
    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    preempt_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    managed_resources: tuple = ()


@dataclass
class Policy:
    predicates: list[PredicatePolicy] = field(default_factory=list)
    priorities: list[PriorityPolicy] = field(default_factory=list)
    extenders: list[ExtenderConfig] = field(default_factory=list)
    hard_pod_affinity_symmetric_weight: Optional[int] = None

    @staticmethod
    def from_dict(d: dict) -> "Policy":
        def snake(name: str) -> str:
            out = []
            for ch in name:
                if ch.isupper():
                    out.append("_")
                    out.append(ch.lower())
                else:
                    out.append(ch)
            return "".join(out)

        p = Policy()
        for pd in d.get("predicates", []):
            p.predicates.append(PredicatePolicy(
                name=pd["name"], argument=pd.get("argument")))
        for pr in d.get("priorities", []):
            p.priorities.append(PriorityPolicy(
                name=pr["name"], weight=pr.get("weight", 1)))
        for ex in d.get("extenders", []):
            # accept both the reference's camelCase keys (urlPrefix,
            # filterVerb, managedResources...) and snake_case
            fields = ExtenderConfig.__dataclass_fields__
            kw = {}
            for key, value in ex.items():
                norm = key if key in fields else snake(key)
                if norm in fields:
                    if norm == "managed_resources":
                        # reference shape: [{"name": "example.com/gpu"}, ...]
                        value = tuple(
                            m["name"] if isinstance(m, dict) else m
                            for m in value)
                    kw[norm] = value
            p.extenders.append(ExtenderConfig(**kw))
        if "hardPodAffinitySymmetricWeight" in d:
            p.hard_pod_affinity_symmetric_weight = d["hardPodAffinitySymmetricWeight"]
        elif "hard_pod_affinity_symmetric_weight" in d:
            p.hard_pod_affinity_symmetric_weight = d["hard_pod_affinity_symmetric_weight"]
        return p

    @staticmethod
    def from_json(text: str) -> "Policy":
        return Policy.from_dict(json.loads(text))

    @staticmethod
    def from_file(path: str) -> "Policy":
        with open(path) as f:
            return Policy.from_dict(json.load(f))


class PolicyValidationError(ValueError):
    pass


def validate_policy(policy: Policy) -> None:
    """api/validation/validation.go analog: priority weights must be positive
    and bounded so weight*MaxPriority can't overflow int32."""
    errs = []
    for pr in policy.priorities:
        if pr.weight <= 0:
            errs.append(f"priority {pr.name}: weight must be positive")
        elif pr.weight >= MAX_WEIGHT:
            errs.append(f"priority {pr.name}: weight {pr.weight} too large")
    for ex in policy.extenders:
        if ex.weight <= 0:
            errs.append(f"extender {ex.url_prefix}: weight must be positive")
    bind_count = sum(1 for ex in policy.extenders if ex.bind_verb)
    if bind_count > 1:
        errs.append("only one extender may implement bind")
    if errs:
        raise PolicyValidationError("; ".join(errs))
