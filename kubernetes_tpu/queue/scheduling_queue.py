"""Three-part pending-pod queue with backoff and event-driven wake-ups.

Mirrors the semantics of pkg/scheduler/internal/queue/scheduling_queue.go:
- activeQ: heap by (priority desc, enqueue time asc) — pods ready to schedule.
- podBackoffQ: heap by backoff-completion time — pods recently failed.
- unschedulableQ: map — pods waiting for a cluster event.
- moveRequestCycle (:290): a failed pod whose scheduling cycle predates the
  last MoveAllToActiveQueue request goes to backoff (something changed while
  it was being scheduled), otherwise to unschedulable.
- Backoff 1s initial, doubling to 10s max (pod_backoff.go:41, wired :184).
- Unschedulable pods are flushed to active after 60s (:52, :368).
- nominatedPodMap (:725): pods nominated onto a node by preemption.
"""
from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.types import Pod, has_pod_affinity_terms
from kubernetes_tpu.coscheduling.types import pod_group_key
from kubernetes_tpu.obs import ledger as obs_ledger
from kubernetes_tpu.utils.clock import Clock, RealClock
from kubernetes_tpu.utils.heap import KeyedHeap, NumericKeyedHeap

INITIAL_BACKOFF = 1.0          # seconds (scheduling_queue.go:184)
MAX_BACKOFF = 10.0
UNSCHEDULABLE_TIMEOUT = 60.0   # seconds (scheduling_queue.go:52)

# gang members share their group's (priority, timestamp, seq) sort anchor
# so they pop ADJACENTLY; the member's own enqueue order survives as a
# fraction below the inter-pod seq resolution (seqs are integers >= 1
# apart, so members can never interleave with a neighboring group)
_GROUP_MEMBER_STEP = 2.0 ** -20


@dataclass
class _QueuedPod:
    pod: Pod
    timestamp: float
    seq: int = 0        # FIFO tie-break for equal (priority, timestamp)
    expiry: float = 0.0  # backoff-completion time, snapshotted at enqueue so
    #                      the backoffQ heap key never mutates under the heap


class PodBackoffMap:
    """Per-pod attempt counter → exponential backoff (pod_backoff.go:41)."""

    def __init__(self, initial: float = INITIAL_BACKOFF, max_backoff: float = MAX_BACKOFF):
        self.initial = initial
        self.max = max_backoff
        self._attempts: dict[str, int] = {}
        self._last_update: dict[str, float] = {}

    def backoff_pod(self, key: str, now: float) -> None:
        self._attempts[key] = self._attempts.get(key, 0) + 1
        self._last_update[key] = now

    def backoff_time(self, key: str) -> float:
        """Duration of the current backoff window for the pod."""
        attempts = self._attempts.get(key, 0)
        if attempts == 0:
            return 0.0
        return min(self.initial * (2 ** (attempts - 1)), self.max)

    def backoff_expiry(self, key: str) -> float:
        return self._last_update.get(key, 0.0) + self.backoff_time(key)

    def clear(self, key: str) -> None:
        self._attempts.pop(key, None)
        self._last_update.pop(key, None)


class NominatedPodMap:
    """pods nominated to run on nodes by preemption (:725)."""

    def __init__(self):
        self._by_node: dict[str, list[Pod]] = {}
        self._node_of: dict[str, str] = {}  # pod key -> node

    def add(self, pod: Pod, node_name: str = "") -> None:
        self.delete(pod)
        node = node_name or pod.nominated_node_name
        if not node:
            return
        self._node_of[pod.key] = node
        self._by_node.setdefault(node, []).append(pod)

    def delete(self, pod: Pod) -> None:
        node = self._node_of.pop(pod.key, None)
        if node is None:
            return
        lst = self._by_node.get(node, [])
        self._by_node[node] = [p for p in lst if p.key != pod.key]
        if not self._by_node[node]:
            del self._by_node[node]

    def update(self, old: Pod, new: Pod) -> None:
        self.delete(old)
        self.add(new)

    def pods_for_node(self, node_name: str) -> list[Pod]:
        return list(self._by_node.get(node_name, []))

    def all_pods(self) -> list[Pod]:
        """Every nominated pod (crash-restart recovery prunes entries the
        store no longer backs, then re-adds from the relist)."""
        return [p for lst in self._by_node.values() for p in lst]

    def has_any(self) -> bool:
        return bool(self._by_node)





class PriorityQueue:
    def __init__(self, clock: Optional[Clock] = None,
                 initial_backoff: float = INITIAL_BACKOFF,
                 max_backoff: float = MAX_BACKOFF,
                 unschedulable_timeout: float = UNSCHEDULABLE_TIMEOUT):
        self.clock = clock or RealClock()
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._seq = itertools.count()
        # gang sort anchors: group key -> (priority, timestamp, seq) of the
        # first member seen, so later members sort adjacent to it
        # (coscheduling: gangs form contiguous segments in pop order)
        self._group_anchor: dict[str, tuple[int, float, int]] = {}
        # per-GROUP exponential backoff — a failed gang parks as a unit so
        # queued singletons behind it are not starved by hot re-attempts
        self._gang_backoff = PodBackoffMap(initial_backoff, max_backoff)
        # both orderings are numeric triples -> native heap core when built
        # (utils/heap.NumericKeyedHeap; Python twin otherwise)
        self._active = NumericKeyedHeap(
            key_fn=lambda q: q.pod.key,
            triple_fn=self._active_triple)
        self._backoffq = NumericKeyedHeap(
            key_fn=lambda q: q.pod.key,
            triple_fn=lambda q: (q.expiry, q.seq, 0.0))
        self._unschedulable: dict[str, _QueuedPod] = {}
        self._backoff = PodBackoffMap(initial_backoff, max_backoff)
        self.unschedulable_timeout = unschedulable_timeout
        self.nominated = NominatedPodMap()
        self._scheduling_cycle = 0
        self._move_request_cycle = -1
        self._closed = False
        self._last_backoff_sweep = self.clock.now()

    def _active_triple(self, q: _QueuedPod) -> tuple:
        """activeQ ordering (priority desc, timestamp asc, seq asc) with
        gang adjacency: a pod group's members all ride the anchor of the
        group's FIRST-seen member — group priority/creation, per the gang
        ordering contract — so a drained burst sees each gang as one
        contiguous run. Member order inside the group stays enqueue order
        (the sub-integer seq fraction)."""
        gk = pod_group_key(q.pod)
        if gk is None:
            return (-q.pod.priority, q.timestamp, q.seq)
        anchor = self._group_anchor.get(gk)
        if anchor is None:
            anchor = self._group_anchor[gk] = (q.pod.priority, q.timestamp,
                                               q.seq)
        prio, ts, seq0 = anchor
        frac = min((q.seq - seq0) * _GROUP_MEMBER_STEP, 0.999999)
        return (-prio, ts, seq0 + frac)

    # -- basic ops ----------------------------------------------------------
    def add(self, pod: Pod) -> None:
        """New pending pod → activeQ (reference: Add :267)."""
        with self._cond:
            q = _QueuedPod(pod, self.clock.now(), next(self._seq))
            self._active.add(q)
            self._unschedulable.pop(pod.key, None)
            self._backoffq.delete(pod.key)
            self.nominated.add(pod)
            # lifecycle ledger: monotonic arrival stamp (first-enqueue
            # wins, so backoff re-entries keep their true queue wait)
            obs_ledger.LEDGER.stamp_enqueue(pod.key)
            self._cond.notify()

    def add_many(self, pods: list) -> None:
        """Batched add for informer-delivered arrival runs: ONE lock
        acquisition, one shared enqueue timestamp (relative order inside
        the batch rides the seq counter, exactly like per-pod adds), one
        heap-core push for the whole batch, and one batched ledger
        stamp — the round-17 ingest prologue (per-pod add() semantics
        otherwise identical)."""
        if not pods:
            return
        with self._cond:
            now = self.clock.now()
            qs = []
            for pod in pods:
                q = _QueuedPod(pod, now, next(self._seq))
                self._unschedulable.pop(pod.key, None)
                self._backoffq.delete(pod.key)
                self.nominated.add(pod)
                qs.append(q)
            self._active.add_many(qs)
            obs_ledger.LEDGER.stamp_enqueue_many(
                [p.key for p in pods], t=now)
            self._cond.notify_all()

    def add_if_not_present(self, pod: Pod) -> None:
        with self._cond:
            if pod.key in self._active or pod.key in self._backoffq \
                    or pod.key in self._unschedulable:
                return
            self.add(pod)

    def add_unschedulable_if_not_present(self, pod: Pod, pod_scheduling_cycle: int) -> None:
        """Failed pod re-entry (reference: :300)."""
        with self._cond:
            if pod.key in self._unschedulable or pod.key in self._active \
                    or pod.key in self._backoffq:
                return
            now = self.clock.now()
            self._backoff.backoff_pod(pod.key, now)
            q = _QueuedPod(pod, now, next(self._seq),
                           expiry=self._backoff.backoff_expiry(pod.key))
            if self._move_request_cycle >= pod_scheduling_cycle:
                self._backoffq.add(q)
                self._cond.notify()
            else:
                self._unschedulable[pod.key] = q
            self.nominated.add(pod)
            obs_ledger.LEDGER.stamp_enqueue(pod.key)  # first-enqueue wins

    def pop(self, timeout: Optional[float] = None) -> Optional[Pod]:
        """Blocks until a pod is ready (reference: :389). Flushes backoff /
        unschedulable timers opportunistically so single-threaded callers
        don't need the background goroutines.

        The blocking `timeout` is wall-clock (it is caller plumbing, not
        scheduling semantics), while backoff/flush timing uses the injected
        clock — so a FakeClock test can time out of an empty queue."""
        import time as _time
        with self._cond:
            deadline = None if timeout is None else _time.monotonic() + timeout
            while True:
                self._flush_locked()
                q = self._active.pop()
                if q is not None:
                    self._scheduling_cycle += 1
                    obs_ledger.LEDGER.stamp(q.pod.key, obs_ledger.POP)
                    return q.pod
                if self._closed:
                    return None
                wait = 0.02
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                self._cond.wait(wait)

    def pop_burst(self, limit: int) -> list[tuple[Pod, int]]:
        """Drain up to `limit` ready pods under one lock acquisition and
        ONE heap-core call (pop_many: the sifts run with the GIL released
        on the native core) — (pod, scheduling_cycle) pairs, cycle
        numbering identical to `limit` successive pop() calls.
        Non-blocking; the burst shell's drain prologue."""
        with self._cond:
            self._flush_locked()
            base = self._scheduling_cycle
            qs = self._active.pop_many(limit)
            self._scheduling_cycle += len(qs)
            if qs:
                obs_ledger.LEDGER.stamp_many(
                    [q.pod.key for q in qs], obs_ledger.POP)
            return [(q.pod, base + i + 1) for i, q in enumerate(qs)]

    # -- gang (coscheduling) ops --------------------------------------------
    def pop_group(self, group_key: str,
                  limit: int = 1 << 16) -> list[tuple[Pod, int]]:
        """Drain every ACTIVE member of `group_key` (up to `limit`), in the
        order the activeQ would have popped them — the shell uses this to
        complete a gang whose tail the burst drain limit cut off, so gangs
        are always attempted whole. Non-blocking; backoff/unschedulable
        members stay put (they rejoin at their own expiry)."""
        with self._cond:
            self._flush_locked()
            members = [q for q in self._active.list()
                       if pod_group_key(q.pod) == group_key]
            members.sort(key=self._active_triple)
            out: list[tuple[Pod, int]] = []
            for q in members[:limit]:
                self._active.delete(q.pod.key)
                self._scheduling_cycle += 1
                out.append((q.pod, self._scheduling_cycle))
            if out:
                obs_ledger.LEDGER.stamp_many(
                    [p.key for p, _c in out], obs_ledger.POP)
            return out

    def park_group(self, group_key: str, pods: list[Pod]) -> float:
        """A gang attempt failed (or the group is still incomplete): park
        every given member in the backoffQ under ONE per-group exponential
        backoff window, so the members leave the activeQ together, re-enter
        together when the window expires, and queued singletons behind the
        gang are not starved by hot re-attempts. Returns the window's
        expiry time."""
        with self._cond:
            now = self.clock.now()
            self._gang_backoff.backoff_pod(group_key, now)
            expiry = self._gang_backoff.backoff_expiry(group_key)
            for pod in pods:
                self._active.delete(pod.key)
                self._unschedulable.pop(pod.key, None)
                self._backoffq.delete(pod.key)
                self._backoffq.add(_QueuedPod(pod, now, next(self._seq),
                                              expiry=expiry))
                self.nominated.add(pod)
            return expiry

    def clear_group(self, group_key: str) -> None:
        """Forget a group's backoff + sort anchor (its gang committed, or
        the group object was deleted)."""
        with self._cond:
            self._gang_backoff.clear(group_key)
            self._group_anchor.pop(group_key, None)

    def group_backoff_remaining(self, group_key: str) -> float:
        with self._lock:
            return max(0.0, self._gang_backoff.backoff_expiry(group_key)
                       - self.clock.now())

    @staticmethod
    def _is_pod_updated(old: Optional[Pod], new: Pod) -> bool:
        """Reference: :412 isPodUpdated — resourceVersion and the whole
        status are stripped before comparing, so the scheduler's own
        condition/nomination writes don't clear an unschedulable pod's
        backoff (they'd otherwise hot-loop it through the scheduler)."""
        if old is None:
            return True

        def strip(p: Pod) -> Pod:
            # exactly the status-equivalent fields plus resourceVersion; a
            # *spec* nodeName change must still count as an update
            c = p.clone()
            c.resource_version = 0
            c.nominated_node_name = ""
            c.phase = "Pending"
            c.conditions = ()
            c.start_time = None
            return c

        return strip(old) != strip(new)

    def update(self, old: Optional[Pod], new: Pod) -> None:
        """Reference: :430 — refresh in place; a *spec* update to an
        unschedulable pod moves it back to active (status-only updates just
        refresh the stored object)."""
        with self._cond:
            self.nominated.update(old or new, new)
            if new.key in self._active:
                self._active.add(_QueuedPod(new, self.clock.now(), next(self._seq)))
                self._cond.notify()
                return
            if new.key in self._backoffq:
                expiry = self._backoffq.get(new.key).expiry
                self._backoffq.add(_QueuedPod(new, self.clock.now(), next(self._seq),
                                              expiry=expiry))
                return
            if new.key in self._unschedulable:
                if self._is_pod_updated(old, new):
                    del self._unschedulable[new.key]
                    self._backoff.clear(new.key)
                    self._active.add(_QueuedPod(new, self.clock.now(), next(self._seq)))
                    self._cond.notify()
                else:
                    q = self._unschedulable[new.key]
                    self._unschedulable[new.key] = _QueuedPod(
                        new, q.timestamp, next(self._seq), expiry=q.expiry)
                return
            self.add(new)

    def delete(self, pod: Pod) -> None:
        with self._cond:
            self._active.delete(pod.key)
            self._backoffq.delete(pod.key)
            self._unschedulable.pop(pod.key, None)
            self._backoff.clear(pod.key)
            self.nominated.delete(pod)

    def update_many(self, pairs: list) -> None:
        """Batched update (round 23): one queue-lock acquisition for a
        whole informer run of (old, new) pairs — per-pair semantics are
        exactly update()'s (the inner acquires are reentrant no-ops)."""
        with self._cond:
            for old, new in pairs:
                self.update(old, new)

    def delete_many(self, pods: list) -> None:
        """Batched delete (round 23): one queue-lock acquisition for a
        whole informer run."""
        with self._cond:
            for pod in pods:
                self.delete(pod)

    # -- event-driven moves --------------------------------------------------
    def move_all_to_active(self) -> None:
        """Cluster changed → retry everything (reference: :519)."""
        with self._cond:
            now = self.clock.now()
            for key, q in list(self._unschedulable.items()):
                q.expiry = self._backoff.backoff_expiry(key)
                if q.expiry > now:
                    self._backoffq.add(q)
                else:
                    self._active.add(q)
                del self._unschedulable[key]
            self._move_request_cycle = self._scheduling_cycle
            self._cond.notify_all()

    def assigned_pod_added(self, pod: Pod) -> None:
        """An assigned pod landed → unschedulable pods with (anti)affinity may
        now fit (reference: AssignedPodAdded :486)."""
        self._move_pods_with_affinity()

    def assigned_pod_updated(self, pod: Pod) -> None:
        self._move_pods_with_affinity()

    def _move_pods_with_affinity(self) -> None:
        with self._cond:
            now = self.clock.now()
            moved = False  # noqa: F841 kept for notify gating
            for key, q in list(self._unschedulable.items()):
                if has_pod_affinity_terms(q.pod):
                    q.expiry = self._backoff.backoff_expiry(key)
                    if q.expiry > now:
                        self._backoffq.add(q)
                    else:
                        self._active.add(q)
                    del self._unschedulable[key]
                    moved = True
            # record the move request even when nothing moved: a pod mid-cycle
            # must land in backoffQ, not unschedulableQ
            # (reference: scheduling_queue.go:519 sets moveRequestCycle always)
            self._move_request_cycle = self._scheduling_cycle
            if moved:
                self._cond.notify_all()

    # -- timers --------------------------------------------------------------
    def _flush_locked(self) -> None:
        now = self.clock.now()
        # backoff completed → active (reference: :334)
        while True:
            head = self._backoffq.peek()
            if head is None or head.expiry > now:
                break
            self._backoffq.pop()
            self._active.add(head)
        # unschedulable leftover > 60s → active (reference: :368)
        for key, q in list(self._unschedulable.items()):
            if now - q.timestamp > self.unschedulable_timeout:
                del self._unschedulable[key]
                self._active.add(q)
        # sweep stale backoff records for pods no longer queued
        # (reference: PodBackoffMap.CleanupPodsCompletesBackingoff)
        if now - self._last_backoff_sweep > 2 * self._backoff.max:
            self._last_backoff_sweep = now
            for key in list(self._backoff._attempts):
                if key in self._active or key in self._backoffq \
                        or key in self._unschedulable:
                    continue
                if self._backoff.backoff_expiry(key) + self._backoff.max < now:
                    self._backoff.clear(key)

    def flush(self) -> None:
        with self._cond:
            self._flush_locked()
            self._cond.notify_all()

    # -- introspection -------------------------------------------------------
    @property
    def scheduling_cycle(self) -> int:
        with self._lock:
            return self._scheduling_cycle

    def pending_pods(self) -> dict[str, list[Pod]]:
        with self._lock:
            return {
                "active": [q.pod for q in self._active.list()],
                "backoff": [q.pod for q in self._backoffq.list()],
                "unschedulable": [q.pod for q in self._unschedulable.values()],
            }

    def num_pending(self) -> int:
        with self._lock:
            return len(self._active) + len(self._backoffq) + len(self._unschedulable)

    def active_depth(self) -> int:
        """O(1) activeQ depth — the serving backpressure gate's watermark
        input (deliberately NOT num_pending: backoff/unschedulable pods
        re-enter on their own timers and shedding new arrivals on their
        account would starve a recovering cluster)."""
        with self._lock:
            return len(self._active)

    def parked_gangs(self) -> dict[str, dict]:
        """Gangs currently under a group backoff window, with deadlines —
        the /debug/sched view of why a PodGroup isn't being attempted."""
        with self._lock:
            now = self.clock.now()
            out = {}
            for gk in self._gang_backoff._attempts:
                expiry = self._gang_backoff.backoff_expiry(gk)
                out[gk] = {
                    "attempts": self._gang_backoff._attempts[gk],
                    "backoff_expiry": round(expiry, 3),
                    "remaining_seconds": round(max(0.0, expiry - now), 3),
                }
            return out

    def debug_state(self) -> dict:
        """One /debug/sched section: queue depths, cycle counter, parked
        gangs with deadlines, nominated-pod count."""
        with self._lock:
            state = {
                "active_depth": len(self._active),
                "backoff_depth": len(self._backoffq),
                "unschedulable_depth": len(self._unschedulable),
                "scheduling_cycle": self._scheduling_cycle,
                "move_request_cycle": self._move_request_cycle,
                "nominated_nodes": len(self.nominated._by_node),
            }
        state["parked_gangs"] = self.parked_gangs()
        return state

    def clear_backoff(self, pod: Pod) -> None:
        with self._cond:
            self._backoff.clear(pod.key)
            q = self._backoffq.delete(pod.key)
            if q is not None:
                q.expiry = 0.0
                self._active.add(q)
                self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
