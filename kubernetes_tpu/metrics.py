"""Metrics exposition — pkg/scheduler/metrics/metrics.go analog.

Renders the scheduler's counters and queue gauges in Prometheus text
exposition format (the /metrics endpoint payload, server.go:284-295).
The metric names mirror the reference's set: schedule_attempts_total,
binding totals, preemption counters, pending_pods by queue.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from kubernetes_tpu.scheduler import Scheduler

PREFIX = "scheduler"


def render_metrics(sched: "Scheduler") -> str:
    """One scrape of the scheduler's metric families."""
    m = sched.metrics
    pending = sched.queue.pending_pods()
    lines = [
        f"# HELP {PREFIX}_schedule_attempts_total Number of attempts to schedule pods, by result.",
        f"# TYPE {PREFIX}_schedule_attempts_total counter",
    ]
    for result, count in sorted(m.schedule_attempts.items()):
        lines.append(
            f'{PREFIX}_schedule_attempts_total{{result="{result}"}} {count}')
    lines += [
        f"# HELP {PREFIX}_binding_total Number of successful pod bindings.",
        f"# TYPE {PREFIX}_binding_total counter",
        f"{PREFIX}_binding_total {m.binding_count}",
        f"# HELP {PREFIX}_total_preemption_attempts Total preemption attempts.",
        f"# TYPE {PREFIX}_total_preemption_attempts counter",
        f"{PREFIX}_total_preemption_attempts {m.preemption_attempts}",
        f"# HELP {PREFIX}_pod_preemption_victims Number of preemption victims.",
        f"# TYPE {PREFIX}_pod_preemption_victims counter",
        f"{PREFIX}_pod_preemption_victims {m.preemption_victims}",
        f"# HELP {PREFIX}_e2e_scheduling_duration_seconds_sum Sum of end-to-end scheduling latency.",
        f"# TYPE {PREFIX}_e2e_scheduling_duration_seconds_sum counter",
        f"{PREFIX}_e2e_scheduling_duration_seconds_sum {m.e2e_latency_sum:.6f}",
        f"# HELP {PREFIX}_pending_pods Pending pods by queue.",
        f"# TYPE {PREFIX}_pending_pods gauge",
    ]
    for queue_name in ("active", "backoff", "unschedulable"):
        lines.append(
            f'{PREFIX}_pending_pods{{queue="{queue_name}"}} '
            f'{len(pending[queue_name])}')
    lines += [
        f"# HELP {PREFIX}_cache_nodes Nodes tracked by the scheduler cache.",
        f"# TYPE {PREFIX}_cache_nodes gauge",
        f"{PREFIX}_cache_nodes {sched.cache.node_count()}",
        f"# HELP {PREFIX}_cache_pods Pods tracked by the scheduler cache.",
        f"# TYPE {PREFIX}_cache_pods gauge",
        f"{PREFIX}_cache_pods {sched.cache.pod_count()}",
    ]
    return "\n".join(lines) + "\n"


def reset_metrics(sched: "Scheduler") -> None:
    """DELETE /metrics analog (metrics.Reset, metrics.go:242)."""
    m = sched.metrics
    m.schedule_attempts = {"scheduled": 0, "unschedulable": 0, "error": 0}
    m.binding_count = 0
    m.preemption_attempts = 0
    m.preemption_victims = 0
    m.e2e_latency_sum = 0.0
