"""Metrics exposition — pkg/scheduler/metrics/metrics.go analog.

Renders the scheduler's counters and queue gauges in Prometheus text
exposition format (the /metrics endpoint payload, server.go:284-295).
The metric names mirror the reference's set: schedule_attempts_total,
binding totals, preemption counters, pending_pods by queue.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from kubernetes_tpu.scheduler import Scheduler

PREFIX = "scheduler"


def render_metrics(sched: "Scheduler") -> str:
    """One scrape of the scheduler's metric families."""
    m = sched.metrics
    pending = sched.queue.pending_pods()
    lines = [
        f"# HELP {PREFIX}_schedule_attempts_total Number of attempts to schedule pods, by result.",
        f"# TYPE {PREFIX}_schedule_attempts_total counter",
    ]
    for result, count in sorted(m.schedule_attempts.items()):
        lines.append(
            f'{PREFIX}_schedule_attempts_total{{result="{result}"}} {count}')
    lines += [
        f"# HELP {PREFIX}_binding_total Number of successful pod bindings.",
        f"# TYPE {PREFIX}_binding_total counter",
        f"{PREFIX}_binding_total {m.binding_count}",
        f"# HELP {PREFIX}_total_preemption_attempts Total preemption attempts.",
        f"# TYPE {PREFIX}_total_preemption_attempts counter",
        f"{PREFIX}_total_preemption_attempts {m.preemption_attempts}",
        f"# HELP {PREFIX}_pod_preemption_victims Number of preemption victims.",
        f"# TYPE {PREFIX}_pod_preemption_victims counter",
        f"{PREFIX}_pod_preemption_victims {m.preemption_victims}",
    ]
    # per-phase duration histograms (metrics.go:67-169
    # scheduling_duration_seconds / binding_duration_seconds /
    # e2e_scheduling_duration_seconds) — phases here are the TPU pipeline's:
    # encode/kernel/fetch plus algorithm/preemption/binding
    lines += [
        f"# HELP {PREFIX}_scheduling_duration_seconds Scheduling phase latency, by operation.",
        f"# TYPE {PREFIX}_scheduling_duration_seconds histogram",
    ]
    for phase in sorted(m.phase_duration):
        lines += m.phase_duration[phase].render(
            f"{PREFIX}_scheduling_duration_seconds",
            labels=f'operation="{phase}"')
    lines += [
        f"# HELP {PREFIX}_binding_duration_seconds Binding latency.",
        f"# TYPE {PREFIX}_binding_duration_seconds histogram",
    ]
    lines += m.binding_duration.render(f"{PREFIX}_binding_duration_seconds")
    lines += [
        f"# HELP {PREFIX}_e2e_scheduling_duration_seconds End-to-end scheduling latency.",
        f"# TYPE {PREFIX}_e2e_scheduling_duration_seconds histogram",
    ]
    lines += m.e2e_duration.render(f"{PREFIX}_e2e_scheduling_duration_seconds")
    lines += [
        f"# HELP {PREFIX}_pending_pods Pending pods by queue.",
        f"# TYPE {PREFIX}_pending_pods gauge",
    ]
    for queue_name in ("active", "backoff", "unschedulable"):
        lines.append(
            f'{PREFIX}_pending_pods{{queue="{queue_name}"}} '
            f'{len(pending[queue_name])}')
    lines += [
        f"# HELP {PREFIX}_cache_nodes Nodes tracked by the scheduler cache.",
        f"# TYPE {PREFIX}_cache_nodes gauge",
        f"{PREFIX}_cache_nodes {sched.cache.node_count()}",
        f"# HELP {PREFIX}_cache_pods Pods tracked by the scheduler cache.",
        f"# TYPE {PREFIX}_cache_pods gauge",
        f"{PREFIX}_cache_pods {sched.cache.pod_count()}",
    ]
    return "\n".join(lines) + "\n"


def reset_metrics(sched: "Scheduler") -> None:
    """DELETE /metrics analog (metrics.Reset, metrics.go:242)."""
    m = sched.metrics
    from kubernetes_tpu.scheduler import Histogram
    m.schedule_attempts = {"scheduled": 0, "unschedulable": 0, "error": 0}
    m.binding_count = 0
    m.preemption_attempts = 0
    m.preemption_victims = 0
    m.e2e_latency_sum = 0.0
    m.phase_duration = {}
    m.binding_duration = Histogram()
    m.e2e_duration = Histogram()
