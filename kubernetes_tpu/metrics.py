"""Scheduler metrics exposition — pkg/scheduler/metrics/metrics.go analog.

Renders the scheduler's counters and queue gauges in Prometheus text
exposition format (the /metrics endpoint payload, server.go:284-295),
built on the shared obs registry (kubernetes_tpu/obs) instead of the old
hand-rolled string renderer — label values are escaped per the text
format now, and the family set is lintable (obs.lint).

The scheduler's live counters stay in SchedulerMetrics (scheduler.py);
each scrape snapshots them into a fresh Registry so concurrent scrapes
and resets never tear a family mid-render. The metric names mirror the
reference's set: schedule_attempts_total, binding totals, preemption
counters, pending_pods by queue.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

from kubernetes_tpu.obs.registry import Registry

if TYPE_CHECKING:
    from kubernetes_tpu.scheduler import Scheduler

PREFIX = "scheduler"


def _copy_histogram(fam, src, *labels) -> None:
    """Snapshot one scheduler.Histogram into a registry child (same
    cumulative-bucket layout, reference ExponentialBuckets(0.001, 2, 15))."""
    child = fam.labels(*labels)
    child.buckets = list(src.buckets)
    child.count = src.count
    child.sum = src.sum


def build_registry(sched: "Scheduler") -> Registry:
    """One scrape of the scheduler's metric families as a Registry."""
    m = sched.metrics
    pending = sched.queue.pending_pods()
    r = Registry()
    attempts = r.counter(
        f"{PREFIX}_schedule_attempts_total",
        "Number of attempts to schedule pods, by result.", ("result",))
    for result, count in m.schedule_attempts.items():
        attempts.labels(result).inc(count)
    r.counter(f"{PREFIX}_binding_total",
              "Number of successful pod bindings.").inc(m.binding_count)
    r.counter(f"{PREFIX}_total_preemption_attempts",
              "Total preemption attempts.").inc(m.preemption_attempts)
    r.counter(f"{PREFIX}_pod_preemption_victims",
              "Number of preemption victims.").inc(m.preemption_victims)
    # per-phase duration histograms (metrics.go:67-169
    # scheduling_duration_seconds / binding_duration_seconds /
    # e2e_scheduling_duration_seconds) — phases here are the TPU pipeline's:
    # encode/kernel/fetch plus algorithm/preemption/binding
    phases = r.histogram(
        f"{PREFIX}_scheduling_duration_seconds",
        "Scheduling phase latency, by operation.", ("operation",))
    for phase in sorted(m.phase_duration):
        _copy_histogram(phases, m.phase_duration[phase], phase)
    binding = r.histogram(f"{PREFIX}_binding_duration_seconds",
                          "Binding latency.")
    _copy_histogram(binding, m.binding_duration)
    e2e = r.histogram(f"{PREFIX}_e2e_scheduling_duration_seconds",
                      "End-to-end scheduling latency.")
    _copy_histogram(e2e, m.e2e_duration)
    pend = r.gauge(f"{PREFIX}_pending_pods", "Pending pods by queue.",
                   ("queue",))
    for queue_name in ("active", "backoff", "unschedulable"):
        pend.labels(queue_name).set(len(pending[queue_name]))
    r.gauge(f"{PREFIX}_cache_nodes",
            "Nodes tracked by the scheduler cache.").set(
        sched.cache.node_count())
    r.gauge(f"{PREFIX}_cache_pods",
            "Pods tracked by the scheduler cache.").set(
        sched.cache.pod_count())
    return r


def render_metrics(sched: "Scheduler") -> str:
    """One scrape of the scheduler's metric families."""
    return build_registry(sched).render()


def reset_metrics(sched: "Scheduler") -> None:
    """DELETE /metrics analog (metrics.Reset, metrics.go:242)."""
    sched.metrics.reset()
