"""Factory: predicate/priority registries, algorithm providers, config wiring.

Mirrors pkg/scheduler/factory/ (RegisterFitPredicate plugins.go:106,
CreateFromProvider :336, CreateFromConfig :346, CreateFromKeys :417) and
pkg/scheduler/algorithmprovider/defaults (defaultPredicates :40,
defaultPriorities :108, ClusterAutoscalerProvider swapping LeastRequested
for MostRequested :99).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.apis.config import SchedulerConfiguration, validate
from kubernetes_tpu.apis.policy import Policy, validate_policy
from kubernetes_tpu.oracle import predicates as preds
from kubernetes_tpu.oracle import priorities as prios
from kubernetes_tpu.oracle.generic_scheduler import PriorityConfig

# -- predicate registry -------------------------------------------------------
# The effective DefaultProvider set with TaintNodesByCondition on
# (defaults.go:40,60-90): condition/pressure predicates are replaced by
# taints + CheckNodeUnschedulable.
DEFAULT_PREDICATE_NAMES = [
    "NoVolumeZoneConflict", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount", "MaxCSIVolumeCountPred", "MatchInterPodAffinity",
    "NoDiskConflict", "GeneralPredicates", "CheckVolumeBinding",
    "CheckNodeUnschedulable", "PodToleratesNodeTaints",
]

_EXTRA_PREDICATES: dict[str, Callable] = {}


def register_fit_predicate(name: str, factory: Callable) -> None:
    """plugins.go:106 RegisterFitPredicate —
    `factory(node_infos, services_fn) -> fn`."""
    _EXTRA_PREDICATES[name] = factory


def register_custom_fit_predicate(policy_pred) -> bool:
    """plugins.go:204 RegisterCustomFitPredicate — map a Policy predicate
    with an argument onto its checker. Returns True when registered."""
    arg = policy_pred.argument or {}
    if "labelsPresence" in arg:
        spec = arg["labelsPresence"]
        register_fit_predicate(
            policy_pred.name,
            lambda ni, sf, _s=spec: preds.make_node_label_presence(
                _s.get("labels", []), bool(_s.get("presence", True))))
        return True
    if "serviceAffinity" in arg:
        spec = arg["serviceAffinity"]
        register_fit_predicate(
            policy_pred.name,
            lambda ni, sf, _s=spec: preds.make_service_affinity(
                _s.get("labels", []), ni, sf))
        return True
    return False


def build_predicate_set(names: list[str],
                        node_infos,
                        volume_listers=None,
                        volume_binder=None,
                        services_fn: Callable = lambda: []) -> dict[str, Callable]:
    """CreateFromKeys predicate assembly: the named subset, evaluated in
    predicates.PREDICATE_ORDERING."""
    base = preds.default_predicate_set(node_infos,
                                       volume_listers=volume_listers,
                                       volume_binder=volume_binder)
    # keep the metadata-invalidation handle (not a predicate; preemption and
    # the nominated-ghost two-pass need it)
    out = {"_ipa_checker": base["_ipa_checker"]}
    for name in names:
        if name in base:
            out[name] = base[name]
        elif name in _EXTRA_PREDICATES:
            out[name] = _EXTRA_PREDICATES[name](node_infos, services_fn)
        elif name in ("PodFitsResources", "PodFitsHostPorts", "MatchNodeSelector",
                      "HostName"):
            out[name] = {
                "PodFitsResources": preds.pod_fits_resources,
                "PodFitsHostPorts": preds.pod_fits_host_ports,
                "MatchNodeSelector": preds.pod_match_node_selector,
                "HostName": preds.pod_fits_host,
            }[name]
        else:
            raise KeyError(f"unknown predicate {name!r}")
    return out


# -- priority registry --------------------------------------------------------
DEFAULT_PRIORITY_WEIGHTS = {
    "SelectorSpreadPriority": 1,
    "InterPodAffinityPriority": 1,
    "LeastRequestedPriority": 1,
    "BalancedResourceAllocation": 1,
    "NodePreferAvoidPodsPriority": 10000,   # register_priorities.go:26
    "NodeAffinityPriority": 1,
    "TaintTolerationPriority": 1,
    "ImageLocalityPriority": 1,
}

_EXTRA_PRIORITIES: dict[str, Callable] = {}


def register_priority(name: str, config_factory: Callable) -> None:
    """plugins.go RegisterPriorityConfigFactory analog:
    `config_factory(weight, services_fn, replicasets_fn, hard_weight) ->
    PriorityConfig`."""
    _EXTRA_PRIORITIES[name] = config_factory


def build_priority_configs(name_weights: dict[str, int],
                           services_fn=lambda: [],
                           replicasets_fn=lambda: [],
                           hard_pod_affinity_weight: int = 1) -> list[PriorityConfig]:
    def spread_fn(pod, node_infos, nodes):
        selectors = prios.get_selectors(pod, services_fn(), replicasets_fn())
        hosts = [n.name for n in nodes]
        counts = [prios.selector_spread_map(pod, node_infos[h], selectors)
                  for h in hosts]
        return prios.selector_spread_reduce(node_infos, hosts, counts)

    def interpod_fn(pod, node_infos, nodes):
        return prios.interpod_affinity_priority(pod, node_infos, nodes,
                                                hard_pod_affinity_weight)

    def image_fn(pod, node_infos, nodes):
        total = len(node_infos)
        return [prios.image_locality_map(pod, node_infos[n.name], total)
                for n in nodes]

    builders = {
        "SelectorSpreadPriority": lambda w: PriorityConfig(
            "SelectorSpreadPriority", w, function=spread_fn),
        "InterPodAffinityPriority": lambda w: PriorityConfig(
            "InterPodAffinityPriority", w, function=interpod_fn),
        "LeastRequestedPriority": lambda w: PriorityConfig(
            "LeastRequestedPriority", w, map_fn=prios.least_requested_map),
        "MostRequestedPriority": lambda w: PriorityConfig(
            "MostRequestedPriority", w, map_fn=prios.most_requested_map),
        "RequestedToCapacityRatioPriority": lambda w: PriorityConfig(
            "RequestedToCapacityRatioPriority", w, map_fn=prios.make_rtcr_map()),
        "BalancedResourceAllocation": lambda w: PriorityConfig(
            "BalancedResourceAllocation", w, map_fn=prios.balanced_allocation_map),
        "NodePreferAvoidPodsPriority": lambda w: PriorityConfig(
            "NodePreferAvoidPodsPriority", w, map_fn=prios.node_prefer_avoid_pods_map),
        "ResourceLimitsPriority": lambda w: PriorityConfig(
            "ResourceLimitsPriority", w, map_fn=prios.resource_limits_map),
        "NodeAffinityPriority": lambda w: PriorityConfig(
            "NodeAffinityPriority", w, map_fn=prios.node_affinity_map,
            reduce_fn=lambda s: prios.normalize_reduce(prios.MAX_PRIORITY, False, s)),
        "TaintTolerationPriority": lambda w: PriorityConfig(
            "TaintTolerationPriority", w, map_fn=prios.taint_toleration_map,
            reduce_fn=lambda s: prios.normalize_reduce(prios.MAX_PRIORITY, True, s)),
        "ImageLocalityPriority": lambda w: PriorityConfig(
            "ImageLocalityPriority", w, function=image_fn),
        "EqualPriority": lambda w: PriorityConfig(
            "EqualPriority", w, map_fn=prios.equal_priority_map),
    }
    out = []
    for name, weight in name_weights.items():
        if name in builders:
            out.append(builders[name](weight))
        elif name in _EXTRA_PRIORITIES:
            out.append(_EXTRA_PRIORITIES[name](
                weight, services_fn, replicasets_fn, hard_pod_affinity_weight))
        else:
            raise KeyError(f"unknown priority {name!r}")
    return out


# -- TPU kernel support matrix ------------------------------------------------
# priority name -> kernel weight key (ops/kernels.DEFAULT_WEIGHTS)
TPU_WEIGHT_KEYS = {
    "SelectorSpreadPriority": "selector_spread",
    "InterPodAffinityPriority": "interpod",
    "LeastRequestedPriority": "least_requested",
    "MostRequestedPriority": "most_requested",
    "RequestedToCapacityRatioPriority": "rtcr",
    "BalancedResourceAllocation": "balanced",
    "NodePreferAvoidPodsPriority": "prefer_avoid",
    "NodeAffinityPriority": "node_affinity",
    "TaintTolerationPriority": "taint_toleration",
    "ImageLocalityPriority": "image_locality",
}

TPU_SUPPORTED_PREDICATES = {
    "GeneralPredicates", "PodFitsResources", "PodFitsHostPorts",
    "MatchNodeSelector", "HostName", "CheckNodeUnschedulable",
    "PodToleratesNodeTaints", "MatchInterPodAffinity",
    # volume predicates are always-fit in this version
    "NoVolumeZoneConflict", "MaxEBSVolumeCount", "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount", "MaxCSIVolumeCountPred", "NoDiskConflict",
    "CheckVolumeBinding",
}


def tpu_kernel_weights(name_weights: dict[str, int]) -> Optional[dict]:
    """Kernel weight dict for a priority selection, or None when a priority
    has no device implementation (callers fall back to the oracle)."""
    from kubernetes_tpu.ops.kernels import DEFAULT_WEIGHTS
    weights = {k: 0 for k in DEFAULT_WEIGHTS}
    for name, w in name_weights.items():
        key = TPU_WEIGHT_KEYS.get(name)
        if key is None:
            return None
        weights[key] = w
    return weights


def tpu_supports_predicates(names: list[str]) -> bool:
    return all(n in TPU_SUPPORTED_PREDICATES for n in names)


# -- algorithm providers ------------------------------------------------------
@dataclass(frozen=True)
class AlgorithmProvider:
    predicate_names: tuple
    priority_weights: tuple  # of (name, weight)


_PROVIDERS: dict[str, AlgorithmProvider] = {}


def register_algorithm_provider(name: str, predicate_names: list[str],
                                priority_weights: dict[str, int]) -> None:
    _PROVIDERS[name] = AlgorithmProvider(
        tuple(predicate_names), tuple(priority_weights.items()))


def get_algorithm_provider(name: str) -> AlgorithmProvider:
    if name not in _PROVIDERS:
        raise KeyError(f"unknown algorithm provider {name!r}")
    return _PROVIDERS[name]


register_algorithm_provider("DefaultProvider", DEFAULT_PREDICATE_NAMES,
                            DEFAULT_PRIORITY_WEIGHTS)
# ClusterAutoscalerProvider: MostRequested replaces LeastRequested
# (defaults.go:99 registerAlgorithmProvider)
_ca = dict(DEFAULT_PRIORITY_WEIGHTS)
del _ca["LeastRequestedPriority"]
_ca["MostRequestedPriority"] = 1
register_algorithm_provider("ClusterAutoscalerProvider",
                            DEFAULT_PREDICATE_NAMES, _ca)


# -- config -> Scheduler ------------------------------------------------------
def resolve_algorithm(cfg: SchedulerConfiguration
                      ) -> tuple[list[str], dict[str, int], Policy]:
    """AlgorithmSource resolution (scheduler.go:162-192): provider name or
    Policy. Returns (predicate_names, priority_weights, policy)."""
    src = cfg.algorithm_source
    if src.policy_file or src.policy_inline:
        if src.policy_file:
            policy = Policy.from_file(src.policy_file)
        else:
            policy = Policy.from_dict(src.policy_inline)
        validate_policy(policy)
        default = get_algorithm_provider("DefaultProvider")
        pred_names = ([p.name for p in policy.predicates]
                      if policy.predicates else list(default.predicate_names))
        prio_weights = ({p.name: p.weight for p in policy.priorities}
                        if policy.priorities else dict(default.priority_weights))
        return pred_names, prio_weights, policy
    provider = get_algorithm_provider(src.provider or "DefaultProvider")
    return (list(provider.predicate_names), dict(provider.priority_weights),
            Policy())


def create_scheduler(store, cfg: Optional[SchedulerConfiguration] = None,
                     extender_endpoints: Optional[dict] = None, **kw):
    """cmd/kube-scheduler Run + scheduler.New analog: validated config in,
    fully wired Scheduler out. `extender_endpoints` maps extender url_prefix
    to a callable-endpoint dict for in-process extenders."""
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.core.extender import SchedulerExtender
    cfg = cfg or SchedulerConfiguration()
    validate(cfg)
    from kubernetes_tpu.utils import features
    features.set_gates(cfg.feature_gates)
    pred_names, prio_weights, policy = resolve_algorithm(cfg)
    for pd in policy.predicates:
        if pd.argument:
            register_custom_fit_predicate(pd)
    hard_weight = (policy.hard_pod_affinity_symmetric_weight
                   if policy.hard_pod_affinity_symmetric_weight is not None
                   else cfg.hard_pod_affinity_symmetric_weight)
    extenders = [
        SchedulerExtender(ec, endpoints=(extender_endpoints or {}).get(
            ec.url_prefix))
        for ec in policy.extenders]
    use_tpu = bool(cfg.feature_gates.get("TPUScoring")) \
        and tpu_kernel_weights(prio_weights) is not None \
        and tpu_supports_predicates(pred_names) \
        and not extenders
    kw.setdefault("extenders", extenders)
    # production wiring shards the node axis across every visible chip;
    # direct Scheduler construction stays single-chip unless asked
    kw.setdefault("mesh", "auto")
    return Scheduler(
        store,
        scheduler_name=cfg.scheduler_name,
        use_tpu=use_tpu,
        percentage_of_nodes_to_score=cfg.percentage_of_nodes_to_score,
        hard_pod_affinity_weight=hard_weight,
        disable_preemption=cfg.disable_preemption,
        predicate_names=pred_names,
        priority_weights=prio_weights,
        plugins_enabled=cfg.plugins_enabled,
        **kw)
