"""Multi-chip node-axis sharding: per-shard filter/score, ICI all-gather,
global select.

The node matrix is the scale axis (the reference's equivalent is the node
count, walked by 16 goroutines — generic_scheduler.go:518). Here the axis is
sharded across a `jax.sharding.Mesh`: every chip evaluates feasibility and
scores for its node rows; the tiny per-node results (feasible bits + int64
totals, ~16B/node) ride an ICI all-gather; the selection (rotation cumsum,
quota, round-robin tie-break) runs replicated so every chip agrees on the
binding decision. XLA inserts the collectives from sharding constraints —
the scaling-book recipe, not hand-written NCCL.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import kubernetes_tpu.ops  # noqa: F401  (x64)
from kubernetes_tpu.ops import kernels as K

NODE_AXIS = "nodes"

# node-array fields sharded along the node axis; everything else replicates
_SHARDED_1D = (
    "valid", "alloc_cpu", "alloc_mem", "alloc_eph", "allowed_pods",
    "req_cpu", "req_mem", "req_eph", "nz_cpu", "nz_mem", "pod_count",
    "zone_id",
)
_SHARDED_2D = ("alloc_scalar", "req_scalar")
# per-pod [N] arrays sharded the same way
_POD_SHARDED = (
    "sel_ok", "taints_ok", "unsched_ok", "ports_ok", "host_ok",
    "interpod_code", "node_aff_counts", "taint_counts", "spread_counts",
    "interpod_counts", "interpod_tracked", "image_sums", "prefer_avoid",
)


def make_mesh(n_devices: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices).reshape(-1), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(NODE_AXIS))


def node_sharding_2d(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(NODE_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _put_by_keys(mesh: Mesh, arrays: dict, sharded_keys,
                 sharded_spec: NamedSharding,
                 sharded_2d_spec: NamedSharding | None = None) -> dict:
    """device_put `arrays`: keys in `sharded_keys` get the node-axis spec
    (2D keys their own spec when given); everything else replicates."""
    repl = replicated(mesh)
    n_dev = mesh.devices.size
    out = {}
    for k, v in arrays.items():
        # inert [*, 1] broadcast fields can't split over the node axis —
        # they replicate (the kernel broadcasts them per shard)
        splittable = np.shape(v)[-1] % n_dev == 0 if np.ndim(v) else False
        if sharded_2d_spec is not None and k in _SHARDED_2D:
            out[k] = jax.device_put(v, sharded_2d_spec)
        elif k in sharded_keys and splittable:
            out[k] = jax.device_put(v, sharded_spec)
        else:
            out[k] = jax.device_put(v, repl)
    return out


def shard_node_arrays(mesh: Mesh, nodes: dict) -> dict:
    """device_put node arrays with the node axis split across the mesh."""
    return _put_by_keys(mesh, nodes, _SHARDED_1D, node_sharding(mesh),
                        node_sharding_2d(mesh))


def shard_pod_arrays(mesh: Mesh, pod: dict) -> dict:
    return _put_by_keys(mesh, pod, _POD_SHARDED, node_sharding(mesh))


def shard_pod_batch(mesh: Mesh, pods: dict) -> dict:
    """device_put a stacked [B, ...] pod batch: per-node [B, N] arrays are
    sharded along the node axis (axis 1); per-pod scalars replicate."""
    return _put_by_keys(mesh, pods, _POD_SHARDED,
                        NamedSharding(mesh, P(None, NODE_AXIS)))


def _constrain_nodes(mesh: Mesh, nodes: dict) -> dict:
    """Pin node arrays to the node-axis sharding inside jit."""
    shard = node_sharding(mesh)
    shard2 = node_sharding_2d(mesh)
    n_dev = mesh.devices.size
    out = {}
    for k, v in nodes.items():
        if k in _SHARDED_2D:
            out[k] = jax.lax.with_sharding_constraint(v, shard2)
        elif k in _SHARDED_1D and v.shape[-1] % n_dev == 0:
            out[k] = jax.lax.with_sharding_constraint(v, shard)
        else:
            out[k] = v
    return out


def sharded_cycle_fn(mesh: Mesh, z_pad: int, weights=None,
                     use_wtab: bool = False):
    """A jitted scheduling cycle with the node axis sharded across the mesh.

    The per-node phases (feasibility, scores) are constrained to the node
    sharding so each chip evaluates its rows; GSPMD inserts the collectives
    (the feasibility cumsum and score reductions become all-gathers/psums
    over ICI) and the tiny scalar selection epilogue replicates. Decisions
    are bit-identical to the single-device kernel (tests/test_sharding.py).
    Returns fn(nodes, pod, last_index, last_node_index, num_to_find, n_real)
    — with `use_wtab`, fn takes a trailing replicated [P, K] profile
    weight table and `pod` carries `profile_id`.
    """
    weights_tuple = tuple(sorted((weights or K.DEFAULT_WEIGHTS).items()))

    if use_wtab:
        def fn(nodes, pod, last_index, last_node_index, num_to_find,
               n_real, wtab):
            nodes = _constrain_nodes(mesh, nodes)
            return K._cycle_core(nodes, pod, last_index, last_node_index,
                                 num_to_find, n_real, dict(weights_tuple),
                                 z_pad, wtab=wtab)
    else:
        def fn(nodes, pod, last_index, last_node_index, num_to_find,
               n_real):
            nodes = _constrain_nodes(mesh, nodes)
            return K._cycle_core(nodes, pod, last_index, last_node_index,
                                 num_to_find, n_real, dict(weights_tuple),
                                 z_pad)

    return jax.jit(fn)


_UNIFORM_CACHE: dict = {}


def sharded_uniform_fn(mesh: Mesh, weights_tuple, flags, b_cap, k_batch,
                       rotate, ban, has_extra, use_wtab: bool = False):
    """The uniform K-pods-per-pass burst kernel (kernels._uniform_core) with
    its node-axis state sharded over the mesh — the north-star multi-chip
    configuration (BASELINE.json configs[4]; the 16-way fan-out it replaces
    is generic_scheduler.go:518).

    Each chip folds and rescores its node rows inside the while-loop; the
    scratch-padded [N+1] carried vectors (scores, banned set, resource rows)
    are pinned to the node sharding every pass, so GSPMD keeps the O(N)
    sweep distributed and inserts all-gathers only for the tiny tie-cumsum /
    searchsorted epilogue (bool + int32 per node over ICI). Decisions are
    bit-identical to the single-device kernel (tests/test_sharding.py).
    Compiled once per (mesh, class-shape) and cached."""
    # Mesh is hashable/eq-comparable: content-equal meshes share the entry
    # (keying on id() would recompile per Mesh object and pin dead meshes)
    key = (mesh, weights_tuple, flags, b_cap, k_batch, rotate, ban,
           has_extra, use_wtab)
    fn = _UNIFORM_CACHE.get(key)
    if fn is not None:
        return fn
    shard1 = node_sharding(mesh)
    shard2 = NamedSharding(mesh, P(None, NODE_AXIS))

    def constrain(v):
        # GSPMD pads the odd scratch column onto the last shard
        return jax.lax.with_sharding_constraint(
            v, shard2 if v.ndim == 2 else shard1)

    if use_wtab:
        # profile tensor mode: the tiny [P, K] weight table replicates and
        # the class's row is gathered once by the scalar profile id
        def f(nodes, cls, n_pods, lni, n_real, perm, oid_seq, extra_ok,
              wtab, pid):
            nodes = _constrain_nodes(mesh, nodes)
            return K._uniform_core(nodes, cls, n_pods, lni, n_real, perm,
                                   oid_seq, extra_ok, dict(weights_tuple),
                                   flags, b_cap, k_batch, rotate, ban,
                                   has_extra, constrain=constrain,
                                   wtab=wtab, pid=pid)
    else:
        def f(nodes, cls, n_pods, lni, n_real, perm, oid_seq, extra_ok):
            nodes = _constrain_nodes(mesh, nodes)
            return K._uniform_core(nodes, cls, n_pods, lni, n_real, perm,
                                   oid_seq, extra_ok, dict(weights_tuple),
                                   flags, b_cap, k_batch, rotate, ban,
                                   has_extra, constrain=constrain)

    fn = _UNIFORM_CACHE[key] = jax.jit(f)
    return fn


def node_constrainer(mesh: Mesh):
    """A pytree-aware `constrain` hook for the kernel cores: node-axis
    leaves ([N] vectors, [N, *] planes — the axis is FIRST on every
    carried state/spread/ghost/victim structure) are pinned to the mesh's
    node sharding; leaves whose leading dim can't split evenly (inert [1]
    broadcasts, scalars, scratch-padded odd lengths) pass through
    untouched and replicate. The cores call this on every loop carry, so
    GSPMD keeps the O(N) sweep distributed across iterations instead of
    collapsing the carry onto one chip."""
    n_dev = mesh.devices.size
    s1 = node_sharding(mesh)
    s2 = node_sharding_2d(mesh)

    def one(v):
        if v.ndim >= 1 and v.shape[0] > 1 and v.shape[0] % n_dev == 0:
            return jax.lax.with_sharding_constraint(
                v, s2 if v.ndim == 2 else s1)
        return v

    return lambda tree: jax.tree_util.tree_map(one, tree)


# jit caches for the sharded kernel programs, keyed on (mesh, statics) —
# Mesh is hashable/eq-comparable, so content-equal meshes share entries
_SCAN_CACHE: dict = {}
_SEG_CACHE: dict = {}
_PRESSURE_CACHE: dict = {}
_PREEMPT_CACHE: dict = {}


def sharded_scan_fn(mesh: Mesh, z_pad: int, weights_tuple, rotate: bool,
                    carry_spread: bool, rotate_pos: bool,
                    use_wtab: bool = False):
    """The generic lax.scan burst kernel (kernels._batch_core) with the
    node axis sharded over the mesh — the SAME program single-device runs,
    parameterized by the sharding spec: each chip folds the selected pod's
    deltas into its node rows every step (the carried _MUTABLE state and
    spread vector are pinned to the node sharding), rotation perm rows
    replicate (they are tiny [L, N] index tables), and the per-node
    feasibility/score vectors ride XLA collectives (all-gather over ICI)
    into the replicated select epilogue. Decisions are bit-identical to
    the single-device scan (tests/test_sharding.py + the sharded fuzz
    variants). Compiled once per (mesh, statics) and cached."""
    key = (mesh, z_pad, weights_tuple, rotate, carry_spread, rotate_pos,
           use_wtab)
    fn = _SCAN_CACHE.get(key)
    if fn is not None:
        return fn
    c = node_constrainer(mesh)

    if use_wtab:
        # profile tensor mode: the replicated [P, K] weight table rides the
        # operands and each step gathers its pod's row (profile_id in pods)
        def f(nodes, mut0, pods, wtab, last_index, last_node_index,
              num_to_find, n_real, perms, inv_perms, oid_seq, spread0):
            nodes = _constrain_nodes(mesh, nodes)
            return K._batch_core(nodes, mut0, pods, last_index,
                                 last_node_index, num_to_find, n_real,
                                 perms, inv_perms, oid_seq, spread0, z_pad,
                                 dict(weights_tuple), rotate, carry_spread,
                                 rotate_pos=rotate_pos, constrain=c,
                                 wtab=wtab)
    else:
        def f(nodes, mut0, pods, last_index, last_node_index, num_to_find,
              n_real, perms, inv_perms, oid_seq, spread0):
            nodes = _constrain_nodes(mesh, nodes)
            return K._batch_core(nodes, mut0, pods, last_index,
                                 last_node_index, num_to_find, n_real,
                                 perms, inv_perms, oid_seq, spread0, z_pad,
                                 dict(weights_tuple), rotate, carry_spread,
                                 rotate_pos=rotate_pos, constrain=c)

    fn = _SCAN_CACHE[key] = jax.jit(f)
    return fn


def sharded_segments_fn(mesh: Mesh, z_pad: int, weights_tuple,
                        rot_mode: int, carry_spread: bool,
                        use_wtab: bool = False, gang_score: bool = False):
    """The fused segmented drain-window kernel (kernels._segments_core)
    sharded over the mesh: the whole while_loop carry — live mutable rows,
    spread, AND the in-scan gang checkpoint — stays under
    NamedSharding(mesh, P("nodes")); a gang rewind is a shard-local
    element-wise select between two identically-sharded carries, rotation
    stays indexed by the consumed-count t with the perm tables replicated,
    and the single [4B] packed output replicates (per-pod, tiny).
    Decisions bit-identical to the single-device fused kernel."""
    key = (mesh, z_pad, weights_tuple, rot_mode, carry_spread, use_wtab,
           gang_score)
    fn = _SEG_CACHE.get(key)
    if fn is not None:
        return fn
    c = node_constrainer(mesh)

    if use_wtab or gang_score:
        # profile tensor mode / rank-aware gang set-scoring: the weight
        # table replicates (a dummy rides when only gang_score is on) and
        # the tiny [z_pad] gang zone-count carry replicates with the
        # scalar walk counters
        def f(nodes, mut0, pods, seg_start, gang, n_pods, last_index,
              last_node_index, num_to_find, n_real, perms, inv_perms,
              oid_seq, spread0, wtab):
            nodes = _constrain_nodes(mesh, nodes)
            return K._segments_core(nodes, mut0, pods, seg_start, gang,
                                    n_pods, last_index, last_node_index,
                                    num_to_find, n_real, perms, inv_perms,
                                    oid_seq, spread0, z_pad,
                                    dict(weights_tuple), rot_mode,
                                    carry_spread, constrain=c,
                                    wtab=wtab if use_wtab else None,
                                    gang_score=gang_score)
    else:
        def f(nodes, mut0, pods, seg_start, gang, n_pods, last_index,
              last_node_index, num_to_find, n_real, perms, inv_perms,
              oid_seq, spread0):
            nodes = _constrain_nodes(mesh, nodes)
            return K._segments_core(nodes, mut0, pods, seg_start, gang,
                                    n_pods, last_index, last_node_index,
                                    num_to_find, n_real, perms, inv_perms,
                                    oid_seq, spread0, z_pad,
                                    dict(weights_tuple), rot_mode,
                                    carry_spread, constrain=c)

    fn = _SEG_CACHE[key] = jax.jit(f)
    return fn


def sharded_pressure_fn(mesh: Mesh, z_pad: int, weights_tuple):
    """The schedule-else-preempt pressure kernel (kernels._pressure_core)
    sharded over the mesh: mutable rows, the accumulated nominated-ghost
    load, and the [N, P] victim planes all split on the node axis; the
    5-criteria node pick reduces over tiny per-node aggregates and
    replicates. Decisions bit-identical to the single-device kernel."""
    key = (mesh, z_pad, weights_tuple)
    fn = _PRESSURE_CACHE.get(key)
    if fn is not None:
        return fn
    c = node_constrainer(mesh)

    def f(nodes, mut0, ghost0, pods, vic, last_index, last_node_index,
          num_to_find, n_real):
        nodes = _constrain_nodes(mesh, nodes)
        return K._pressure_core(nodes, c(mut0), c(ghost0), pods, c(vic),
                                last_index, last_node_index, num_to_find,
                                n_real, z_pad, dict(weights_tuple),
                                constrain=c)

    fn = _PRESSURE_CACHE[key] = jax.jit(f)
    return fn


def sharded_preempt_fn(mesh: Mesh, check_res: bool, has_req: bool):
    """The single-preemptor victim scan (kernels._preempt_scan_core)
    sharded over the mesh — per-node victim selection and the reprieve
    scan run shard-local; the staged pick replicates."""
    key = (mesh, check_res, has_req)
    fn = _PREEMPT_CACHE.get(key)
    if fn is not None:
        return fn
    c = node_constrainer(mesh)

    def f(nodes, vic, pod, feas_static, order_rank, n_real, max_prio):
        nodes = _constrain_nodes(mesh, nodes)
        return K._preempt_scan_core(nodes, c(vic), pod, c(feas_static),
                                    c(order_rank), n_real, max_prio,
                                    check_res, has_req, constrain=c)

    fn = _PREEMPT_CACHE[key] = jax.jit(f)
    return fn


def shard_victim_planes(mesh: Mesh, planes: dict) -> dict:
    """device_put the resident [N, P] victim-table planes with the node
    axis (axis 0) split across the mesh — the round-9 VictimStack under
    NamedSharding(mesh, P("nodes")). Planes whose row count can't split
    evenly replicate (tiny clusters)."""
    n_dev = mesh.devices.size
    s2 = node_sharding_2d(mesh)
    repl = replicated(mesh)
    return {k: jax.device_put(
                v, s2 if np.ndim(v) == 2 and np.shape(v)[0] % n_dev == 0
                else repl)
            for k, v in planes.items()}


def sharded_batch_fn(mesh: Mesh, z_pad: int, weights=None):
    """The full scheduling *step* over the mesh: a `lax.scan` burst with the
    node axis sharded and the complete mutable-state fold (kernels._MUTABLE —
    req_cpu/mem/eph/scalar, nz_cpu/nz_mem, pod_count) constrained back onto
    the node sharding every iteration.

    This is the multi-chip twin of kernels.schedule_batch, now riding the
    SAME _batch_core the single-device jit compiles (one code path
    parameterized by the sharding spec): each chip folds the selected
    pod's deltas into its node rows; the per-node feasibility / score
    vectors ride XLA collectives (all-gather over ICI) for the replicated
    selection epilogue inside _cycle_core. Decisions are bit-identical to
    the single-device scan (see tests/test_sharding.py)."""
    weights_tuple = tuple(sorted((weights or K.DEFAULT_WEIGHTS).items()))
    inner = sharded_scan_fn(mesh, z_pad, weights_tuple, rotate=False,
                            carry_spread=False, rotate_pos=False)

    def fn(nodes, pods, last_index, last_node_index, num_to_find, n_real):
        z = jnp.zeros((1, 1), jnp.int32)
        mut0 = {k: nodes[k] for k in K._MUTABLE}
        state, li, lni, _spread, outs = inner(
            nodes, mut0, pods, last_index, last_node_index, num_to_find,
            n_real, z, z, jnp.zeros(1, jnp.int32), jnp.zeros((), jnp.int64))
        return state, li, lni, outs

    return fn
