"""Scheduler extender — the out-of-process filter/score/bind webhook.

Mirrors pkg/scheduler/core/extender.go (HTTPExtender :86, Filter :258,
Prioritize :318, Bind :360, ProcessPreemption :135) over this framework's
transport: an in-process callable endpoint (the common test/bench form) or
a real HTTP JSON endpoint, selected by the config's url_prefix.
"""
from __future__ import annotations

import json
import urllib.request
from typing import Callable, Optional

from kubernetes_tpu.api.types import Pod, Node
from kubernetes_tpu.apis.policy import ExtenderConfig


class ExtenderError(Exception):
    pass


class SchedulerExtender:
    """One configured extender. For callable transport, pass `endpoints`:
    {"filter": fn(args_dict)->result_dict, "prioritize": ..., "bind": ...,
    "preempt": ...} — the same JSON-shaped dicts the HTTP form sends."""

    def __init__(self, config: ExtenderConfig,
                 endpoints: Optional[dict[str, Callable]] = None):
        self.config = config
        self.endpoints = endpoints or {}

    @property
    def weight(self) -> int:
        return self.config.weight

    def is_interested(self, pod: Pod) -> bool:
        """extender.go IsInterested: an extender with managed_resources only
        handles pods requesting at least one of them; otherwise all pods."""
        managed = self.config.managed_resources
        if not managed:
            return True
        for c in list(pod.containers) + list(pod.init_containers):
            for name, _q in c.requests:
                if name in managed:
                    return True
        return False

    @property
    def is_ignorable(self) -> bool:
        """Ignorable extenders don't fail scheduling when unreachable
        (extender.go IsIgnorable)."""
        return self.config.ignorable

    def _call(self, verb: str, payload: dict) -> dict:
        if verb in self.endpoints:
            return self.endpoints[verb](payload)
        url = f"{self.config.url_prefix.rstrip('/')}/{verb}"
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())

    # -- Filter (extender.go:258) --------------------------------------------
    def filter(self, pod: Pod, nodes: list[Node]
               ) -> tuple[list[Node], dict[str, list[str]]]:
        if not self.config.filter_verb or not self.is_interested(pod):
            return nodes, {}
        payload = {
            "pod": pod.key,
            "nodes": [n.name for n in nodes],
        }
        try:
            result = self._call(self.config.filter_verb, payload)
        except Exception as e:
            if self.is_ignorable:
                return nodes, {}
            raise ExtenderError(f"extender filter failed: {e}") from e
        if result.get("error"):
            raise ExtenderError(result["error"])
        keep = set(result.get("nodeNames", [n.name for n in nodes]))
        failed = {name: [reason] for name, reason in
                  (result.get("failedNodes") or {}).items()}
        return [n for n in nodes if n.name in keep], failed

    # -- Prioritize (extender.go:318) ------------------------------------------
    def prioritize(self, pod: Pod, nodes: list[Node]
                   ) -> tuple[dict[str, int], int]:
        """Returns ({host: score}, weight); scores are the extender's own
        0-10 range, weighted by the caller."""
        if not self.config.prioritize_verb or not self.is_interested(pod):
            return {n.name: 0 for n in nodes}, 0
        payload = {"pod": pod.key, "nodes": [n.name for n in nodes]}
        try:
            result = self._call(self.config.prioritize_verb, payload)
        except Exception as e:
            if self.is_ignorable:
                return {n.name: 0 for n in nodes}, 0
            raise ExtenderError(f"extender prioritize failed: {e}") from e
        scores = {h["host"]: int(h["score"]) for h in result.get("hostPriorityList", [])}
        return scores, self.config.weight

    # -- Bind (extender.go:360) -------------------------------------------------
    @property
    def is_binder(self) -> bool:
        return bool(self.config.bind_verb)

    def bind(self, pod: Pod, node_name: str) -> None:
        result = self._call(self.config.bind_verb,
                            {"pod": pod.key, "node": node_name})
        if result.get("error"):
            raise ExtenderError(result["error"])

    # -- ProcessPreemption (extender.go:135) -------------------------------------
    def process_preemption(self, pod: Pod,
                           nodes_to_victims: dict[str, list[Pod]]
                           ) -> dict[str, list[Pod]]:
        """Lets the extender veto/trim preemption candidates. Payload carries
        victim pod keys per node; the response echoes the surviving map."""
        if not self.config.preempt_verb:
            return nodes_to_victims
        payload = {
            "pod": pod.key,
            "nodeNameToVictims": {n: [p.key for p in v]
                                  for n, v in nodes_to_victims.items()},
        }
        try:
            result = self._call(self.config.preempt_verb, payload)
        except Exception as e:
            if self.is_ignorable:
                return nodes_to_victims
            raise ExtenderError(f"extender preempt failed: {e}") from e
        surviving = result.get("nodeNameToVictims")
        if surviving is None:
            return nodes_to_victims
        out = {}
        for name, victim_keys in surviving.items():
            if name not in nodes_to_victims:
                continue
            keep = set(victim_keys)
            out[name] = [p for p in nodes_to_victims[name] if p.key in keep]
        return out
