"""Device circuit breaker — degrade to the host oracle, never to wrong
decisions.

A tunneled chip fails in bursts: one dropped dispatch is usually followed
by more, and every failed launch costs a full round-trip timeout before
the caller learns anything. The breaker gives the TPU drivers the standard
three-state contract (closed -> open -> half-open), tuned for the repo's
parity posture: every degraded path (whole-burst refusal -> serial loop,
serial cycle -> host twin, preemption -> oracle Preemptor) is already
bit-identical to the device path, so tripping the breaker changes
THROUGHPUT only — the parity fuzzes run green with the fault plane
injecting at every device seam.

- closed: device path allowed; consecutive faults count.
- open (tripped after `fault_threshold` consecutive faults): every device
  gate (`allow_device`) refuses — bursts refuse up front (the shell runs
  the serial loop on the host twin), serial cycles pick the twin.
- half-open: after `probe_after` refused gates, ONE probe launch is
  allowed through; success re-closes, a fault re-opens (and the refusal
  counter restarts).

State is published on `tpu_device_circuit_state` (0 closed / 1 half-open /
2 open) and every recorded fault on `tpu_device_faults_total{seam}`.
"""
from __future__ import annotations

import threading

from kubernetes_tpu import obs

CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half-open", OPEN: "open"}

CIRCUIT_STATE = obs.gauge(
    "tpu_device_circuit_state",
    "Device circuit breaker state: 0 closed (device path live), 1 "
    "half-open (one probe in flight), 2 open (host-only mode — every "
    "decision rides the oracle twin until a probe succeeds).")
DEVICE_FAULTS = obs.counter(
    "tpu_device_faults_total",
    "Device-path faults absorbed by the circuit breaker, by seam "
    "(device.dispatch / device.fetch, plus device.runtime for faults the "
    "chaos plane did not inject). Every fault degraded a burst or cycle "
    "to the serial oracle path; none changed a decision.", ("seam",))


class DeviceCircuitBreaker:
    def __init__(self, fault_threshold: int = 3, probe_after: int = 16):
        self.fault_threshold = int(fault_threshold)
        self.probe_after = int(probe_after)
        self._state = CLOSED
        self._consecutive = 0
        self._denied = 0
        self._lock = threading.Lock()
        self.faults_total = 0
        self.trips_total = 0
        self.promotions_total = 0
        CIRCUIT_STATE.set(CLOSED)

    # -- gates ---------------------------------------------------------------
    def allow_device(self) -> bool:
        """One device-path gate. Closed: allow. Open: refuse, counting
        refusals toward the half-open probe window. Half-open: allow (the
        probe — the next record_fault/record_success resolves it)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN:
                return True
            self._denied += 1
            if self._denied >= self.probe_after:
                self._set(HALF_OPEN)
                return True
            return False

    # -- outcomes ------------------------------------------------------------
    def record_fault(self, seam: str = "device.runtime") -> None:
        DEVICE_FAULTS.labels(seam).inc()
        with self._lock:
            self.faults_total += 1
            self._consecutive += 1
            if self._state == HALF_OPEN \
                    or self._consecutive >= self.fault_threshold:
                if self._state != OPEN:
                    self.trips_total += 1
                self._denied = 0
                self._set(OPEN)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state != CLOSED:
                self.promotions_total += 1
                self._set(CLOSED)

    def _set(self, state: int) -> None:
        self._state = state
        CIRCUIT_STATE.set(state)

    # -- introspection -------------------------------------------------------
    @property
    def state(self) -> str:
        return _STATE_NAMES[self._state]

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "state": _STATE_NAMES[self._state],
                "consecutive_faults": self._consecutive,
                "faults_total": self.faults_total,
                "trips_total": self.trips_total,
                "promotions_total": self.promotions_total,
                "denied_since_trip": self._denied,
                "fault_threshold": self.fault_threshold,
                "probe_after": self.probe_after,
            }
