"""TPU-backed scheduling algorithm — the device twin of the oracle.

Drop-in for oracle.GenericScheduler (same schedule() contract, same
ScheduleResult/FitError), but filter/score/select run as one fused kernel
over the dense node matrix (ops/kernels.py). Decision parity: identical
suggested hosts, feasible sets, evaluated counts, and integer scores.

Two paths:
- schedule(): one pod per launch — used for parity testing and for pods with
  features the burst path doesn't batch yet.
- schedule_burst(): a `lax.scan` over many pending pods against one
  snapshot, folding each decision's resource delta into device state —
  serially-equivalent decisions at one launch (the throughput path;
  reference equivalent is the serial scheduleOne loop, scheduler.go:438).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.cache.node_info import NodeInfo
from kubernetes_tpu.oracle import predicates as P
from kubernetes_tpu.oracle.generic_scheduler import (
    ScheduleResult, FitError, num_feasible_nodes_to_find,
    DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE,
)
from kubernetes_tpu.ops.node_state import (
    NodeStateEncoder, PodEncoder, PodFeatures, NodeBatch,
    IPA_EXISTING_ANTI, IPA_OWN_AFFINITY, IPA_OWN_ANTI,
)
from kubernetes_tpu.ops import kernels as K
from kubernetes_tpu import chaos, obs
from kubernetes_tpu.core import StaleNodeRefusal
from kubernetes_tpu.core.breaker import DeviceCircuitBreaker
from kubernetes_tpu.obs import trace as obs_trace
from kubernetes_tpu.obs import flight as obs_flight
from kubernetes_tpu.obs import ledger as obs_ledger

# exception classes the circuit breaker absorbs at the device seams: the
# chaos plane's injected DeviceFault plus jax's real runtime error (what a
# dropped tunnel dispatch/readback actually raises)
_DEVICE_FAULTS = chaos.device_fault_types()

#: rotation-row cache miss sentinel (None is a legal cached value:
#: "this order IS the identity")
_ROT_MISS = object()

import jax
import jax.numpy as jnp

# device-pipeline counters (the /metrics view of PROFILE.md's cost model:
# every dispatch pays the tunnel RTT, every fetch ships bytes, and every
# fallback/refusal moves work back to host Python)
DEVICE_DISPATCH = obs.counter(
    "tpu_device_dispatch_total",
    "Device program dispatches, by op.", ("op",))
DEVICE_FETCHED_BYTES = obs.counter(
    "tpu_device_fetched_bytes_total",
    "Bytes fetched device-to-host, by op.", ("op",))
DEVICE_FETCHES = obs.counter(
    "tpu_device_fetches_total",
    "Device-to-host fetch synchronizations, by op — the tunnel contract "
    "says each one pays a full round trip, so per-launch fetch counts are "
    "load-bearing (one per wave/launch, never per pod).", ("op",))
PIPELINE_OVERLAP = obs.counter(
    "tpu_pipeline_overlap_seconds_total",
    "Seconds of host commit work performed while a later burst wave was "
    "in flight on the device (the pipelined-wave overlap win).")
BURST_WAVES = obs.counter(
    "tpu_burst_waves_total",
    "Burst commit waves, by path — since round 10 a wave is a commit "
    "window consumed out of the single fetched decision block, not a "
    "separate device launch (tpu_device_fetches_total pins that).",
    ("path",))
BURST_SEGMENTS = obs.counter(
    "tpu_burst_scan_segments_total",
    "Segments scheduled through the fused segmented burst scan, by kind: "
    "'run' (singleton sub-ranges) and 'gang' (all-or-nothing PodGroup "
    "sub-ranges whose checkpoint/rewind happens inside the device carry).",
    ("kind",))
ORACLE_FALLBACKS = obs.counter(
    "tpu_oracle_fallback_total",
    "Decisions routed off the device path (host twin / serial rerun), "
    "by reason.", ("reason",))
ICI_ALLGATHER = obs.counter(
    "tpu_ici_allgather_bytes_total",
    "Analytic model of the cross-device bytes the sharded kernels ship "
    "per burst, by op: each scheduling cycle's ICI all-gather moves the "
    "per-node feasibility bit, the i32 walk cumsum, and the i64 score "
    "lane (~16B/node-row) to the d-1 peer shards; the replicated select "
    "epilogue adds nothing per pod. Zero when the mesh is single-device "
    "or absent. XLA does not expose actual collective bytes, so this is "
    "the documented traffic model, not a NIC counter.", ("op",))
# per-cycle cross-device payload of the sharded select epilogue (bytes per
# node row): feasible bool (4 padded) + i32 rank/cumsum lane + i64 score
ICI_BYTES_PER_ROW = 16
PRESSURE_GATES = obs.counter(
    "tpu_pressure_gate_rejections_total",
    "preempt_pressure_burst refusals, by gate.", ("gate",))
DISCARDED_FOLDS = obs.counter(
    "tpu_burst_folds_discarded_total",
    "Device-resident burst folds dropped after a mid-burst failure.")
GANG_REWIND_FOLDS = obs.counter(
    "gang_rewind_folds_total",
    "Device-resident fold sets discarded by a gang (PodGroup) rewind — a "
    "trial-placed gang that missed minMember dropped its in-flight folds "
    "and the carries rewound to the pre-gang checkpoint.")

# span names for the burst phase markers ("kernel" is the async dispatch;
# "fetch" is where device time is actually PAID — CLAUDE.md: the tunnel's
# block_until_ready doesn't block, so readback timing IS device timing)
_PHASE_SPANS = {"encode": ("burst.encode", "host"),
                "kernel": ("burst.dispatch", "device"),
                "fetch": ("burst.fetch", "device")}
# phase -> pod-lifecycle ledger stamp slot: the same boundary that closes
# a burst phase span stamps every in-flight pod of the burst (one clock
# read + O(pods) dict writes; committed pods already left the ledger)
_PHASE_SLOTS = {"encode": obs_ledger.ENCODE,
                "kernel": obs_ledger.DISPATCH,
                "fetch": obs_ledger.FETCH}

# every reason the victim-table eligibility gate can refuse a preemption
# for (the old single "victims-not-inert" label, split per class so
# /metrics shows WHICH gate sends scans back to the oracle). `preempt`
# prefixes with "preempt-victims-", preempt_pressure_burst with
# "victims-"; test_obs pins the set.
VICTIM_GATE_REASONS = ("affinity-terms", "ports", "scalar", "term-match",
                       "overflow")

# fallback/gate labels RETIRED in round 15: the sharded kernels now model
# rotation, carried spread, gang segments, and pressure scans, so these
# refusal paths were deleted outright. A dead label reading 0 forever would
# mask a silent regression back to host scheduling — test_obs pins that no
# live code path (and no eager registration) resurrects them.
RETIRED_FALLBACK_REASONS = ("burst-sharded-rotation", "burst-sharded-spread",
                            "fused-mesh-mode")
RETIRED_PRESSURE_GATES = ("mesh-mode",)


def _fetched_nbytes(obj) -> int:
    """Total nbytes of a fetched pytree (dict/list/tuple of ndarrays)."""
    if isinstance(obj, dict):
        return sum(_fetched_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_fetched_nbytes(v) for v in obj)
    return int(getattr(obj, "nbytes", 8))


def _pad_pow2(n: int, minimum: int = 1) -> int:
    c = minimum
    while c < n:
        c *= 2
    return c


@jax.jit
def _scatter_rows(dev: dict, rows, upd: dict) -> dict:
    """Write generation-dirty rows into the device-resident node matrix —
    the sparse delta upload of SURVEY §2.4 (mirror of the cache's
    incremental snapshot walk, reference cache.go:210-246). One dispatch
    for all fields."""
    out = dict(dev)
    for k, v in upd.items():
        out[k] = dev[k].at[rows].set(v)
    return out


class TPUScheduler:
    def __init__(self,
                 percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE,
                 hard_pod_affinity_weight: int = 1,
                 services_fn=lambda: [],
                 replicasets_fn=lambda: [],
                 collect_host_priority: bool = True,
                 nominated=None,
                 volume_listers=None, volume_binder=None,
                 node_tree=None,
                 serial_path: str = "device",
                 mesh=None):
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.services_fn = services_fn
        self.replicasets_fn = replicasets_fn
        self.collect_host_priority = collect_host_priority
        self.check_resources = True   # PodFitsResources enabled (provider/policy)
        self.weights = None           # None -> kernels.DEFAULT_WEIGHTS
        self.enabled_predicates = None  # None -> all
        self.priority_name_weights = None  # provider/policy priorities by name
        # scheduling profiles (round 19): when a ProfileSet with real
        # multi-profile content attaches (set_profiles), scoring runs the
        # [profiles x priorities] weight-tensor path — per-pod rows
        # gathered on device by profile_id, one launch scoring every
        # profile; None / a degenerate default set keeps the exact
        # pre-profile kernel programs
        self.profiles = None
        self._ptab = None             # host [P, K] tensor (tensor mode)
        self._wtab_dev = None         # device-resident copy, lazy
        self._union_weights = None    # static cross-profile gate dict
        self._profile_static = None   # per-profile static kernel rows
        self._gang_score = False      # any profile rank-aware
        self._oracle_cfgs_prof = None  # per-profile host-twin configs
        # NominatedPodMap handle; when preemption has nominated pods, cycles
        # fall back to the oracle's two-pass fitting (podFitsOnNode :627) —
        # the device kernel doesn't model ghost pods yet
        self.nominated = nominated
        self.volume_listers = volume_listers
        self.volume_binder = volume_binder
        # NodeTree handle: burst decisions must replay the per-cycle
        # zone-interleaved enumeration rotation (node_tree.py rotation_map);
        # None = callers that feed a fixed name order (tests, sharded twin)
        self.node_tree = node_tree
        self._oracle = None
        self._oracle_cfgs = None
        self.last_index = 0
        self.last_node_index = 0
        # single-pod path policy: "device" (kernel always — the parity-test
        # configuration), "host" (twin always), "adaptive" (measure both,
        # use the faster; the production shell's choice)
        self.serial_path = serial_path
        self._lat_ora: Optional[float] = None
        self._lat_dev: Optional[float] = None
        self._serial_cycles = 0
        # multi-chip mode: node axis sharded over a jax.sharding.Mesh
        # (parallel/sharding.py — per-shard filter/score, ICI all-gather,
        # replicated select). mesh="auto" builds one over every visible
        # device; None stays single-chip. Cycles, generic-scan bursts AND
        # the uniform K-batch kernel all run sharded — the north-star
        # multi-chip config (BASELINE.json configs[4]) rides the uniform
        # path with per-shard sweeps and a replicated tie-walk epilogue.
        if mesh == "auto":
            import jax as _jax
            mesh = None
            if len(_jax.devices()) > 1:
                from kubernetes_tpu.parallel import sharding as S
                mesh = S.make_mesh()
        self.mesh = mesh
        self._sharded_cycle = None
        # optional SchedulerMetrics handle (the shell injects it): burst
        # calls observe encode/kernel/fetch phase durations
        # (scheduling_duration_seconds{operation}, metrics.go:67-169)
        self.metrics = None
        # mid-burst node-death scan (the shell injects
        # `(decided_hosts, all_names) -> dead set` against its store):
        # when a node vanishes between dispatch and commit, the wave
        # driver raises StaleNodeRefusal BEFORE any of the launch's
        # decisions commit — the shell invalidates the node and replans
        # post-churn
        self.stale_scan = None
        self.encoder = NodeStateEncoder()
        # device-resident node matrix: full upload on rebuild, dirty-row
        # scatter otherwise (SURVEY §2.4 delta uploader)
        self._dev_nodes: Optional[dict] = None
        self._dev_key = None
        # device-resident victim table (the [N, P] slot planes preemption
        # scans read): full upload on rebuild/permute, dirty-row scatter
        # otherwise — same delta contract as the node matrix
        self._dev_vic: Optional[dict] = None
        self._dev_vic_key = None
        # encode vs device-scan wall seconds of the last pressure launch
        # (bench.py --mode preempt reports the split)
        self.last_preempt_phases: Optional[dict] = None
        # upload/scatter epoch: bumps whenever HOST data lands in the
        # device matrix (burst folds do NOT bump it) — a gang checkpoint
        # whose epoch still matches can restore its pinned matrix without
        # a re-upload (kernels.gang_carry_checkpoint's zero-copy rewind)
        self._dev_epoch = 0
        # inert per-pod fields are shape [1] and broadcast in the kernel —
        # the common case uploads ~nothing (vs [N] per field per pod)
        self._defaults = {
            "ones_bool": np.ones(1, dtype=bool),
            "zeros_i64": np.zeros(1, dtype=np.int64),
            "zeros_i8": np.zeros(1, dtype=np.int8),
            "zeros_bool": np.zeros(1, dtype=bool),
            "tens_i64": np.full(1, 10, dtype=np.int64),
        }
        # shared scalar singletons: identical-by-identity inputs let
        # _stack_pods broadcast instead of stacking B python objects
        self._true = np.bool_(True)
        self._false = np.bool_(False)
        self._zero_i64 = np.int64(0)
        self._zero_scalars: dict[int, np.ndarray] = {}
        # single-worker readback executor for the pipelined burst waves
        # (lazy: serial-only configurations never start the thread)
        self._fetch_pool = None
        # zero ghost-load vectors by n_pad (device arrays are immutable, so
        # every pressure launch can share one set instead of re-creating
        # four jnp.zeros per wave)
        self._ghost_zeros: dict[int, dict] = {}
        # device circuit breaker: a failed launch/fetch degrades that
        # burst/cycle to the serial oracle path (decisions identical);
        # repeated faults trip to host-only mode, re-promoted by a
        # half-open probe (core/breaker.py)
        self.breaker = DeviceCircuitBreaker()
        # walk counters at the last wave window handed to the commit
        # callback — the scheduler shell's crash-restart checkpoint source
        # (None = no exact per-window counters on this path)
        self.commit_marker: Optional[dict] = None
        # rotation-row cache (round 17): order_for_start(rr) -> axis-index
        # row, keyed per NodeBatch OBJECT (a rebuild/permute makes a fresh
        # batch, invalidating by identity). A serving loop cuts hundreds
        # of small windows per second against a stable tree; without this
        # every window re-extracts each distinct enumeration order as an
        # O(N) python walk — the encode prologue's top cost at 1k nodes.
        self._rot_rows: dict[int, np.ndarray] = {}
        self._rot_rows_b: Optional[int] = None

    def _shared_zero_scalar(self, n: int) -> np.ndarray:
        arr = self._zero_scalars.get(n)
        if arr is None:
            arr = self._zero_scalars[n] = np.zeros(n, dtype=np.int64)
        return arr

    def _note_ici(self, op: str, n_cycles: int, n_pad: int) -> None:
        """Book the analytic ICI all-gather traffic of a sharded launch:
        `n_cycles` scheduling cycles (for the uniform kernel, decisions —
        an upper bound on O(N) passes), ICI_BYTES_PER_ROW per node row,
        shipped to the d-1 peer shards. No-op off the mesh."""
        if self.mesh is None:
            return
        d = int(self.mesh.devices.size)
        if d <= 1:
            return
        ICI_ALLGATHER.labels(op).inc(
            int(n_cycles) * int(n_pad) * ICI_BYTES_PER_ROW * (d - 1) // d)

    # -- scheduling profiles (round 19) --------------------------------------
    def set_profiles(self, profiles) -> None:
        """Attach a profiles.ProfileSet. In tensor mode (multiple
        profiles, non-default vectors, or any rank-aware profile) every
        scoring path switches to the resident [profiles x priorities]
        weight tensor: windows gather each pod's row by profile_id, the
        static `weights` dicts become the cross-profile union gate, and
        the fused segment kernel compiles the gang set-scoring carry in
        when any profile is rank-aware. A degenerate default set keeps
        the pre-profile programs — decisions trivially bit-identical."""
        self.profiles = profiles
        self._ptab = None
        self._wtab_dev = None
        self._union_weights = None
        self._profile_static = None
        self._gang_score = False
        self._oracle_cfgs_prof = None
        self._oracle_cfgs = None   # rebuilt per profile on next fallback
        if profiles is not None and profiles.tensor_mode():
            self._ptab = profiles.weight_table()
            self._union_weights = profiles.union_kernel_weights()
            self._profile_static = [profiles.kernel_row(i)
                                    for i in range(len(profiles))]
            self._gang_score = any(p.rank_aware for p in profiles)

    def _profile_id(self, pod: Pod) -> int:
        if self.profiles is None:
            return 0
        pid = self.profiles.index_of(pod.scheduler_name)
        return 0 if pid is None else pid

    def _profile_ids(self, pods: list):
        """Per-pod profile-id vector for a window (None off the tensor
        path). Gathered columnar from the encode-at-admission row cache
        when every row is live; the per-pod fallback is bit-identical by
        the row contract."""
        if self._ptab is None:
            return None
        rc = self.pod_rows
        if rc is not None:
            g = rc.gather(pods, ("profile_id",))
            if g is not None:
                return g["profile_id"].astype(np.int64)
        return np.asarray([self._profile_id(p) for p in pods], np.int64)

    def _wtab(self):
        """The device-resident weight tensor (uploaded once; tiny, so it
        replicates across the mesh)."""
        if self._wtab_dev is None:
            tab = jnp.asarray(self._ptab, jnp.int64)
            if self.mesh is not None:
                from kubernetes_tpu.parallel import sharding as S
                tab = jax.device_put(tab, S.replicated(self.mesh))
            self._wtab_dev = tab
        return self._wtab_dev

    # -- device input assembly ----------------------------------------------
    _NODE_FIELDS = ("valid", "alloc_cpu", "alloc_mem", "alloc_eph",
                    "allowed_pods", "req_cpu", "req_mem", "req_eph",
                    "nz_cpu", "nz_mem", "pod_count", "alloc_scalar",
                    "req_scalar", "zone_id")

    def _node_arrays(self, b: NodeBatch) -> dict:
        """Device node matrix, kept resident across cycles; only rows the
        encoder marked generation-dirty are re-uploaded. In mesh mode the
        node axis is split across the chips at upload time."""
        key = (b.n_pad, len(b.scalar_names), id(b))
        if self._dev_nodes is None or self._dev_key != key or b.dirty_rows is None:
            host = {k: np.asarray(getattr(b, k)) for k in self._NODE_FIELDS}
            if self.mesh is not None:
                from kubernetes_tpu.parallel import sharding as S
                self._dev_nodes = S.shard_node_arrays(self.mesh, host)
            else:
                self._dev_nodes = {k: jnp.asarray(v) for k, v in host.items()}
            DEVICE_DISPATCH.labels("upload").inc()
            self._dev_epoch += 1
            self._dev_key = key
            b.dirty_rows = []   # host state fully mirrored; start tracking
            return self._dev_nodes
        if b.dirty_rows:
            # dedupe, then pad the row list to a power-of-two bucket
            # (duplicate writes of identical values are harmless) so the
            # scatter compiles per bucket, not per row count
            rows = np.asarray(sorted(set(b.dirty_rows)), dtype=np.int32)
            bucket = _pad_pow2(len(rows), 16)
            rows = np.concatenate(
                [rows, np.full(bucket - len(rows), rows[0], dtype=np.int32)])
            upd = {k: getattr(b, k)[rows] for k in self._NODE_FIELDS}
            self._dev_nodes = _scatter_rows(self._dev_nodes, rows, upd)
            DEVICE_DISPATCH.labels("scatter").inc()
            self._dev_epoch += 1
            b.dirty_rows = []
        return self._dev_nodes

    def _pod_arrays(self, f: PodFeatures, n_pad: int,
                    upd_fields: bool = False, pod: Optional[Pod] = None) -> dict:
        """Dense device inputs for one pod. Feature fields the pod doesn't
        exercise stay shape [1] (kernel broadcasts them) — `n_pad` is only
        the target for fields the encoder actually materialized."""
        d = self._defaults
        out = {
            "req_cpu": self._zero_i64 if f.req_cpu == 0 else np.int64(f.req_cpu),
            "req_mem": self._zero_i64 if f.req_mem == 0 else np.int64(f.req_mem),
            "req_eph": self._zero_i64 if f.req_eph == 0 else np.int64(f.req_eph),
            "req_scalar": (f.req_scalar if f.req_scalar.any()
                           else self._shared_zero_scalar(len(f.req_scalar))),
            "has_request": self._true if f.has_request else self._false,
            "unknown_scalar": self._true if f.unknown_scalars else self._false,
            "skip": self._false,
            "check_resources": self._true if self.check_resources else self._false,
            "nz_cpu": np.int64(f.nz_cpu),
            "nz_mem": np.int64(f.nz_mem),
            "sel_ok": f.sel_ok if f.sel_ok is not None else d["ones_bool"],
            "taints_ok": f.taints_ok if f.taints_ok is not None else d["ones_bool"],
            "unsched_ok": f.unsched_ok if f.unsched_ok is not None else d["ones_bool"],
            "ports_ok": f.ports_ok if f.ports_ok is not None else d["ones_bool"],
            "host_ok": f.host_ok if f.host_ok is not None else d["ones_bool"],
            "disk_ok": f.disk_ok if f.disk_ok is not None else d["ones_bool"],
            "maxvol_ok": f.maxvol_ok if f.maxvol_ok is not None else d["ones_bool"],
            "volbind_ok": f.volbind_ok if f.volbind_ok is not None else d["ones_bool"],
            "volzone_ok": f.volzone_ok if f.volzone_ok is not None else d["ones_bool"],
            "interpod_code": f.interpod_code if f.interpod_code is not None else d["zeros_i8"],
            "node_aff_counts": f.node_aff_counts if f.node_aff_counts is not None else d["zeros_i64"],
            "taint_counts": f.taint_counts if f.taint_counts is not None else d["zeros_i64"],
            "spread_counts": f.spread_counts if f.spread_counts is not None else d["zeros_i64"],
            "interpod_counts": f.interpod_counts if f.interpod_counts is not None else d["zeros_i64"],
            "interpod_tracked": f.interpod_tracked if f.interpod_tracked is not None else d["zeros_bool"],
            "image_sums": f.image_sums if f.image_sums is not None else d["zeros_i64"],
            "prefer_avoid": f.prefer_avoid if f.prefer_avoid is not None else d["tens_i64"],
        }
        if upd_fields:
            # node-state delta on add (regular containers only, node_info.py
            # calculate_resource; reference: node_info.go:578)
            from kubernetes_tpu.cache.node_info import calculate_resource
            upd = calculate_resource(pod)
            if upd.scalar:
                upd_scalar = np.zeros_like(f.req_scalar)
                for name, q in upd.scalar.items():
                    upd_scalar[list(self.encoder._scalar_vocab).index(name)] = q
            else:
                upd_scalar = self._shared_zero_scalar(len(f.req_scalar))
            out.update({
                "upd_cpu": self._zero_i64 if upd.milli_cpu == 0 else np.int64(upd.milli_cpu),
                "upd_mem": self._zero_i64 if upd.memory == 0 else np.int64(upd.memory),
                "upd_eph": self._zero_i64 if upd.ephemeral_storage == 0
                           else np.int64(upd.ephemeral_storage),
                "upd_scalar": upd_scalar,
            })
        return out

    @staticmethod
    def _stack_pods(per_pod: list[dict]) -> dict:
        """Stack per-pod dicts to [B, ...] arrays. A field that is inert
        ([1]-shaped) for every pod stays [B, 1] — the scan broadcasts it —
        so plain pods upload O(B) data, not O(B*N). Fields holding the SAME
        object for every pod (the shared inert defaults / scalar singletons)
        are broadcast views, not B-element stacks."""
        out = {}
        for k in per_pod[0]:
            vals = [pp[k] for pp in per_pod]
            v0 = vals[0]
            if all(v is v0 for v in vals):
                out[k] = np.broadcast_to(v0, (len(vals),) + np.shape(v0))
                continue
            shapes = {np.shape(v) for v in vals}
            if len(shapes) > 1:
                # mixed inert/dense: broadcast the inert ones up
                target = max(shapes, key=len) if len({len(s) for s in shapes}) > 1 \
                    else max(shapes)
                vals = [np.broadcast_to(v, target) for v in vals]
            out[k] = np.stack(vals)
        return out

    # -- reason decoding -----------------------------------------------------
    def _decode_reasons(self, b: NodeBatch, f: PodFeatures, idx: int,
                        fail_first: np.ndarray, general_bits: np.ndarray) -> list[str]:
        code = int(fail_first[idx])
        if code == K.FAIL_UNSCHEDULABLE:
            return [P.ERR_NODE_UNSCHEDULABLE]
        if code == K.FAIL_TAINTS:
            return [P.ERR_TAINTS_TOLERATIONS_NOT_MATCH]
        if code == K.FAIL_DISK:
            return ["NoDiskConflict"]
        if code == K.FAIL_MAXVOL:
            return ["MaxVolumeCount"]
        if code in (K.FAIL_VOLBIND, K.FAIL_VOLZONE):
            if f.volbind_reasons and idx in f.volbind_reasons:
                return list(f.volbind_reasons[idx])
            return (["VolumeBindingNoMatch"] if code == K.FAIL_VOLBIND
                    else ["NoVolumeZoneConflict"])
        if code == K.FAIL_INTERPOD:
            ipa = int(f.interpod_code[idx]) if f.interpod_code is not None else 0
            if ipa == IPA_EXISTING_ANTI:
                return [P.ERR_POD_AFFINITY_NOT_MATCH,
                        P.ERR_EXISTING_PODS_ANTI_AFFINITY_RULES_NOT_MATCH]
            if ipa == IPA_OWN_AFFINITY:
                return [P.ERR_POD_AFFINITY_NOT_MATCH, P.ERR_POD_AFFINITY_RULES_NOT_MATCH]
            return [P.ERR_POD_AFFINITY_NOT_MATCH, P.ERR_POD_ANTI_AFFINITY_RULES_NOT_MATCH]
        # general predicates, reason order as predicates.general_predicates
        bits = int(general_bits[idx])
        reasons = []
        if bits & (1 << K.BIT_PODS):
            reasons.append(P.insufficient_resource("pods"))
        if bits & (1 << K.BIT_CPU):
            reasons.append(P.insufficient_resource("cpu"))
        if bits & (1 << K.BIT_MEM):
            reasons.append(P.insufficient_resource("memory"))
        if bits & (1 << K.BIT_EPH):
            reasons.append(P.insufficient_resource("ephemeral-storage"))
        for s, name in enumerate(b.scalar_names):
            if bits & (1 << (K.BIT_SCALAR0 + s)):
                reasons.append(P.insufficient_resource(name))
        if bits & (1 << K.BIT_UNKNOWN_SCALAR):
            reasons.extend(P.insufficient_resource(n) for n in f.unknown_scalars)
        if bits & (1 << K.BIT_HOST):
            reasons.append(P.ERR_POD_NOT_MATCH_HOST_NAME)
        if bits & (1 << K.BIT_PORTS):
            reasons.append(P.ERR_POD_NOT_FITS_HOST_PORTS)
        if bits & (1 << K.BIT_SELECTOR):
            reasons.append(P.ERR_NODE_SELECTOR_NOT_MATCH)
        return reasons

    def _oracle_fallback(self):
        from kubernetes_tpu.oracle.generic_scheduler import (
            GenericScheduler, default_priority_configs)
        if self._oracle is None:
            self._oracle = GenericScheduler(
                percentage_of_nodes_to_score=self.percentage_of_nodes_to_score,
                hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                nominated_pods_fn=self.nominated.pods_for_node)
            if self.profiles is not None:
                # per-profile twin configs: the serial referee scores with
                # the SAME weight vector the tensor row carries
                self._oracle_cfgs_prof = [
                    self.profiles.oracle_configs(
                        i, services_fn=self.services_fn,
                        replicasets_fn=self.replicasets_fn,
                        hard_pod_affinity_weight=self.hard_pod_affinity_weight)
                    for i in range(len(self.profiles))]
                self._oracle_cfgs = self._oracle_cfgs_prof[0]
            elif self.priority_name_weights is not None:
                from kubernetes_tpu.factory import build_priority_configs
                self._oracle_cfgs = build_priority_configs(
                    self.priority_name_weights,
                    services_fn=self.services_fn,
                    replicasets_fn=self.replicasets_fn,
                    hard_pod_affinity_weight=self.hard_pod_affinity_weight)
            else:
                self._oracle_cfgs = default_priority_configs(
                    services_fn=self.services_fn, replicasets_fn=self.replicasets_fn,
                    hard_pod_affinity_weight=self.hard_pod_affinity_weight)
        return self._oracle

    # -- single-pod cycle ----------------------------------------------------
    # Adaptive path selection: a synchronous single-pod decision on the
    # device costs a full dispatch+readback round trip (~100ms over a
    # tunneled chip, microseconds locally), while the host twin costs
    # O(nodes) Python. Neither dominates universally, so schedule() measures
    # both and keeps using the faster — decisions are identical either way
    # (the twin is the parity referee). The device is probed only once the
    # twin's cycle exceeds _DEVICE_PROBE_MS, so small clusters never pay a
    # speculative round trip; the slower path is re-probed periodically so a
    # changed cluster size or link can flip the choice back.
    _DEVICE_PROBE_MS = 30.0
    _REPROBE_EVERY = 1024

    def _schedule_host_twin(self, pod: Pod, node_infos: dict[str, NodeInfo],
                            all_node_names: list[str],
                            extra_configs=None) -> ScheduleResult:
        o = self._oracle_fallback()
        o.last_index, o.last_node_index = self.last_index, self.last_node_index
        from kubernetes_tpu.factory import (
            build_predicate_set, DEFAULT_PREDICATE_NAMES)
        funcs = build_predicate_set(
            sorted(self.enabled_predicates) if self.enabled_predicates
            else DEFAULT_PREDICATE_NAMES,
            node_infos, volume_listers=self.volume_listers,
            volume_binder=self.volume_binder,
            services_fn=self.services_fn)
        cfgs = self._oracle_cfgs
        if self._oracle_cfgs_prof is not None:
            cfgs = self._oracle_cfgs_prof[self._profile_id(pod)]
        if extra_configs:
            cfgs = list(cfgs) + list(extra_configs)
        try:
            return o.schedule(pod, node_infos, all_node_names,
                              predicate_funcs=funcs,
                              priority_configs=cfgs)
        finally:
            self.last_index = o.last_index
            self.last_node_index = o.last_node_index

    def _serial_pick_host_twin(self) -> bool:
        ora, dev = self._lat_ora, self._lat_dev
        if ora is None:
            return True                      # first cycle: host twin
        if ora < self._DEVICE_PROBE_MS / 1e3:
            return True                      # twin fast enough; don't probe
        if dev is None:
            return False                     # twin is slow: probe the device
        if self._serial_cycles % self._REPROBE_EVERY == 0:
            return ora >= dev                # re-probe the losing path
        return ora < dev

    def _device_fault(self, exc: BaseException) -> str:
        """Book one absorbed device fault with the circuit breaker; returns
        the seam name (injected faults carry theirs, real tunnel errors
        book as device.runtime)."""
        seam = getattr(exc, "seam", "device.runtime")
        self.breaker.record_fault(seam)
        return seam

    def schedule(self, pod: Pod, node_infos: dict[str, NodeInfo],
                 all_node_names: list[str],
                 extra_configs=None) -> ScheduleResult:
        if not all_node_names:
            raise FitError(pod, 0, {})
        self._serial_cycles += 1
        if extra_configs:
            # trial-scoped extra priorities (the rank-aware gang serial
            # referee's GangLocalityPriority, bound to live trial state):
            # the host twin IS the reference for that objective
            use_twin = True
            reason = "gang-locality-serial"
        elif self.nominated is not None and self.nominated.has_any():
            use_twin = True     # two-pass ghost-pod fitting lives on the twin
            reason = "nominated-ghosts"
        elif not self.breaker.allow_device():
            use_twin = True     # circuit open: host-only until a probe wins
            reason = "circuit-open"
        elif self.serial_path == "adaptive":
            use_twin = self._serial_pick_host_twin()
            reason = "adaptive-twin-faster"
        else:
            use_twin = self.serial_path == "host"
            reason = "serial-path-host"
        if use_twin:
            ORACLE_FALLBACKS.labels(reason).inc()
        import time as _time
        t0 = _time.perf_counter()
        try:
            if use_twin:
                return self._schedule_host_twin(pod, node_infos,
                                                all_node_names,
                                                extra_configs=extra_configs)
            try:
                return self._schedule_device(pod, node_infos, all_node_names)
            except _DEVICE_FAULTS as e:
                # a failed launch/fetch degrades THIS cycle to the host
                # twin — the decision is identical; only latency differs
                self._device_fault(e)
                ORACLE_FALLBACKS.labels("device-fault").inc()
                use_twin = True
                return self._schedule_host_twin(pod, node_infos,
                                                all_node_names)
        finally:
            dt = _time.perf_counter() - t0
            if use_twin:
                self._lat_ora = dt if self._lat_ora is None \
                    else 0.7 * self._lat_ora + 0.3 * dt
            else:
                self._lat_dev = dt if self._lat_dev is None \
                    else 0.7 * self._lat_dev + 0.3 * dt

    def _schedule_device(self, pod: Pod, node_infos: dict[str, NodeInfo],
                         all_node_names: list[str]) -> ScheduleResult:
        b = self.encoder.encode(node_infos, all_node_names)
        nodes = self._node_arrays(b)
        enc = PodEncoder(node_infos, b, self.services_fn(), self.replicasets_fn(),
                         hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                         enabled=self.enabled_predicates,
                         volume_listers=self.volume_listers,
                         volume_binder=self.volume_binder,
                         state_encoder=self.encoder)
        feats = enc.encode(pod)
        pod_in = self._pod_arrays(feats, b.n_pad)
        wtab = None
        weights = self.weights
        if self._ptab is not None:
            # tensor mode: the pod's profile row is gathered on device —
            # one compiled cycle program scores every profile
            pod_in["profile_id"] = np.int64(self._profile_id(pod))
            wtab = self._wtab()
            weights = self._union_weights
        n = b.n_real
        num_to_find = num_feasible_nodes_to_find(n, self.percentage_of_nodes_to_score)
        z_pad = _pad_pow2(len(b.zone_names), 4)
        chaos.check("device.dispatch")
        if self.mesh is not None:
            # node axis split over the chips; collectives ride ICI and the
            # select epilogue replicates (parallel/sharding.py)
            from kubernetes_tpu.parallel import sharding as S
            ckey = (z_pad, wtab is not None)
            if self._sharded_cycle is None or self._sharded_cycle[0] != ckey:
                self._sharded_cycle = (ckey, S.sharded_cycle_fn(
                    self.mesh, z_pad=z_pad, weights=weights,
                    use_wtab=wtab is not None))
            pod_sharded = S.shard_pod_arrays(self.mesh, pod_in)
            if wtab is not None:
                out = self._sharded_cycle[1](
                    nodes, pod_sharded,
                    K._i64(self.last_index), K._i64(self.last_node_index),
                    K._i64(num_to_find), K._i64(n), wtab)
            else:
                out = self._sharded_cycle[1](
                    nodes, pod_sharded,
                    K._i64(self.last_index), K._i64(self.last_node_index),
                    K._i64(num_to_find), K._i64(n))
        else:
            out = K.schedule_cycle(nodes, pod_in, self.last_index,
                                   self.last_node_index,
                                   num_to_find, n, z_pad, weights=weights,
                                   wtab=wtab)
        # ONE device->host fetch for everything the decision needs: each
        # separate readback pays a full dispatch round trip (ruinous over a
        # tunneled device), so the scalars and per-node vectors come back
        # together
        fetch = {"selected": out["selected"], "found": out["found"],
                 "evaluated": out["evaluated"],
                 "next_last_index": out["next_last_index"],
                 "next_last_node_index": out["next_last_node_index"]}
        need_vectors = self.collect_host_priority
        if need_vectors:
            fetch.update(kept=out["kept"], total=out["total"],
                         fail_first=out["fail_first"],
                         general_bits=out["general_bits"])
        t_fetch = obs_trace.now()
        chaos.node_dead_point("dispatch-fetch")
        chaos.check("device.fetch")
        h = jax.device_get(fetch)
        chaos.node_dead_point("fetch-commit")
        self.breaker.record_success()
        DEVICE_DISPATCH.labels("cycle").inc()
        DEVICE_FETCHES.labels("cycle").inc()
        DEVICE_FETCHED_BYTES.labels("cycle").inc(_fetched_nbytes(h))
        obs_trace.add_span("cycle.fetch", t_fetch, obs_trace.now(),
                           cat="device")
        found = int(h["found"])
        evaluated = int(h["evaluated"])
        start = self.last_index
        self.last_index = int(h["next_last_index"])
        if found == 0:
            if need_vectors:
                fail_first, general_bits = h["fail_first"], h["general_bits"]
            else:
                fail_first, general_bits = jax.device_get(
                    (out["fail_first"], out["general_bits"]))
            failed = {}
            for pos in range(evaluated):
                idx = (start + pos) % n
                failed[b.names[idx]] = self._decode_reasons(
                    b, feats, idx, fail_first, general_bits)
            raise FitError(pod, n, failed)
        self.last_node_index = int(h["next_last_node_index"])
        sel = int(h["selected"])
        host = b.names[sel]
        host_priority = []
        failed = {}
        if need_vectors:
            kept, total = h["kept"], h["total"]
            fail_first, general_bits = h["fail_first"], h["general_bits"]
            for pos in range(evaluated):
                idx = (start + pos) % n
                if kept[idx]:
                    # single-feasible-node cycles skip scoring entirely
                    # (generic_scheduler.go:244-250)
                    score = 0 if found == 1 else int(total[idx])
                    host_priority.append((b.names[idx], score))
                elif fail_first[idx] != K.FAIL_NONE:
                    failed[b.names[idx]] = self._decode_reasons(
                        b, feats, idx, fail_first, general_bits)
        return ScheduleResult(host, evaluated, found, host_priority, failed)

    # -- burst path ----------------------------------------------------------
    # per-node mask fields that CANNOT change from in-burst placements —
    # they depend on node labels/taints/spec and pre-burst pods only
    _STATIC_MASKS = ("sel_ok", "taints_ok", "unsched_ok", "host_ok",
                     "ports_ok")
    # score/filter families the uniform kernel does not model at all
    _INERT_REQUIRED = ("disk_ok", "maxvol_ok", "volbind_ok", "volzone_ok",
                       "node_aff_counts", "taint_counts", "spread_counts",
                       "image_sums", "prefer_avoid")

    @staticmethod
    def _class_signature(pod: Pod):
        """Spec fields that determine a pod's device features against a fixed
        snapshot — equal signatures imply identical encoder output. The
        canonical definition lives in ops.pod_rows (the encode-at-admission
        row cache stores it); this staticmethod stays the public twin the
        parity tests pin against the native batch."""
        from kubernetes_tpu.ops.pod_rows import pod_class_signature
        return pod_class_signature(pod)

    @staticmethod
    def class_signatures(pods: list) -> list:
        """Batched _class_signature — the burst encode prologue's per-pod
        tuple build as ONE native call (commitcore.class_signatures) when
        the extension is built, with this module's per-pod static method as
        the twin (tuples are equal element-for-element by construction;
        pinned by the commit-core parity tests)."""
        from kubernetes_tpu import native
        mod = native.load("commitcore")
        if mod is not None:
            return mod.class_signatures(pods)
        sig = TPUScheduler._class_signature
        return [sig(p) for p in pods]

    def _signatures(self, pods: list) -> list:
        """Window-prologue signatures: gathered from the encode-at-
        admission row cache when the shell attached one (interned — equal
        sigs are the SAME tuple object, so uniformity checks and the
        per-sig memos below hit by identity), else the batched native
        build. Values are bit-identical either way (pod_rows fuzz)."""
        rc = self.pod_rows
        if rc is not None:
            return rc.signatures(pods)
        return self.class_signatures(pods)

    def _uniform_class(self, p0: Pod, f0, b: NodeBatch,
                       node_infos: dict[str, NodeInfo]) -> Optional[tuple]:
        """Eligibility + class extraction for a burst of pods spec-identical
        to `p0` (the caller verified signatures): when the feature
        interactions are expressible as (static per-node mask, optional
        self-node ban), return (cls_scalars, extra_ok, ban); else None.

        Mirrors the eligibility contract in kernels.py: static families
        merge into extra_ok; in-burst interactions must reduce to each
        placement banning its own node (host ports / self-matching hostname
        anti-affinity); score families must be provably uniform across
        valid nodes so they cancel out of the tie structure."""
        from kubernetes_tpu.cache.node_info import calculate_resource
        from kubernetes_tpu.api.types import (
            get_container_ports, LABEL_HOSTNAME)
        if f0.unknown_scalars:
            return None
        upd = calculate_resource(p0)
        upd_scalar = np.zeros_like(f0.req_scalar)
        for name, q in upd.scalar.items():
            upd_scalar[list(self.encoder._scalar_vocab).index(name)] = q
        cls = {"req_cpu": f0.req_cpu, "req_mem": f0.req_mem,
               "req_eph": f0.req_eph, "req_scalar": f0.req_scalar,
               "nz_cpu": f0.nz_cpu, "nz_mem": f0.nz_mem,
               "upd_cpu": upd.milli_cpu, "upd_mem": upd.memory,
               "upd_eph": upd.ephemeral_storage,
               "upd_scalar": upd_scalar,
               "has_request": f0.has_request}
        for field in self._INERT_REQUIRED:
            if getattr(f0, field) is not None:
                return None
        nreal = b.n_real
        # interpod scores must be a constant shift: every valid node tracked
        # and equal counts -> min-max normalizes to 0 everywhere, and stays
        # 0 as in-burst placements (no preferred terms, symmetric hard
        # affinity over a single topology group) add uniformly
        if f0.interpod_counts is not None or f0.interpod_tracked is not None:
            tr, ic = f0.interpod_tracked, f0.interpod_counts
            if tr is None or not bool(np.all(tr[:nreal])):
                return None
            if ic is None or (nreal and int(np.ptp(ic[:nreal])) != 0):
                return None
        extra: Optional[np.ndarray] = None

        def and_mask(m) -> None:
            nonlocal extra
            if m is not None:
                mm = np.asarray(m, dtype=bool)
                if mm.shape[0] != b.n_pad:      # inert [1] fields
                    return
                extra = mm.copy() if extra is None else (extra & mm)

        for field in self._STATIC_MASKS:
            and_mask(getattr(f0, field))
        if f0.interpod_code is not None:
            and_mask(f0.interpod_code == 0)
        ban = bool(get_container_ports(p0))   # identical host ports conflict
        a = p0.affinity
        if a is not None and (a.pod_affinity is not None
                              or a.pod_anti_affinity is not None):
            pa, paa = a.pod_affinity, a.pod_anti_affinity
            if (pa and pa.preferred) or (paa and paa.preferred):
                return None

            def self_match(term) -> bool:
                if term.namespaces and p0.namespace not in term.namespaces:
                    return False
                return term.label_selector is not None \
                    and term.label_selector.matches(p0.labels)

            ban_anti = False
            for term in (paa.required if paa else ()):
                if self_match(term):
                    # in-burst placements ban their topology group; the
                    # node-ban fold is exact only for singleton groups
                    if term.topology_key != LABEL_HOSTNAME:
                        return None
                    ban_anti = True
            for term in (pa.required if pa else ()):
                if self_match(term):
                    # placements add matches in their group; feasibility
                    # stays at the static base only when every valid node
                    # is in ONE group (then it is all-pass after bootstrap)
                    vals = set()
                    for i in range(nreal):
                        node = node_infos[b.names[i]].node
                        vals.add(None if node is None
                                 else node.labels.get(term.topology_key))
                    if len(vals) != 1 or None in vals:
                        return None
            if ban_anti:
                hosts = set()
                for i in range(nreal):
                    node = node_infos[b.names[i]].node
                    h = None if node is None else node.labels.get(LABEL_HOSTNAME)
                    if h is None or h in hosts:
                        return None       # hostname groups must be singleton
                    hosts.add(h)
                ban = True
        return cls, extra, ban

    def _axis_order(self, all_node_names: list):
        """(axis_order, start0) for a burst launch: the node order to
        encode the mirror on, plus the zone-start index whose enumeration
        equals `all_node_names` when the resident axis is KEPT STALE.

        A rotating tree hands every window a differently-ordered
        enumeration; re-encoding the mirror on it forces an O(N) host
        permute plus a FULL device re-upload per window — the serving
        prologue's biggest fixed cost. But the kernels model per-cycle
        enumerations through the rotation program uniformly (cycle 0 is
        only special by convention), so when this launch's enumeration is
        provably order_for_start(r) of the resident axis's tree
        (NodeTree.last_enum_start + the membership-keyed order cache),
        the mirror keeps its axis and cycle 0 rides order id r — a
        gather, not a recompute. Any doubt (membership moved, caller-fed
        name lists, mid-state enumerations, non-rotating trees) falls
        back to axis == enumeration, the pre-round-17 behavior."""
        tree = self.node_tree
        b = self.encoder._batch
        if tree is None or b is None or b.names == all_node_names \
                or not self._tree_rotates():
            return all_node_names, None
        rr = tree.last_enum_start
        if rr is None:
            return all_node_names, None
        order = tree._order_cache.get(rr)
        if order is None or order != all_node_names:
            return all_node_names, None
        if len(b.names) != len(all_node_names) \
                or set(b.names) != set(all_node_names):
            return all_node_names, None   # membership moved: rebuild
        return b.names, rr

    def _rot_cached(self, b: NodeBatch, rr: int, identity: np.ndarray,
                    kind: str):
        """Padded axis-index row for the enumeration starting at zone
        index `rr`, or None when it equals the identity (axis) order —
        cached per NodeBatch object (`kind` keys the two pad layouts:
        "u" pads with the n_pad scratch row, "g" with the invalid-row
        tail). The tree's orders are a function of its membership, and
        membership changes always rebuild/permute the batch (a fresh
        object), so identity-keyed invalidation is exact."""
        if self._rot_rows_b != id(b):
            self._rot_rows = {}
            self._rot_rows_b = id(b)
        key = (kind, rr)
        got = self._rot_rows.get(key, _ROT_MISS)
        if got is not _ROT_MISS:
            return got
        names = self.node_tree.order_for_start(rr)
        raw = np.fromiter((b.index[nm] for nm in names), np.int32,
                          len(names))
        if np.array_equal(raw, identity[: len(raw)]):
            row = None
        elif kind == "u":
            row = np.concatenate([
                raw, np.full(b.n_pad + 1 - len(raw), b.n_pad,
                             dtype=np.int32)])
        else:
            row = np.concatenate([
                raw, np.arange(b.n_real, b.n_pad, dtype=np.int32)])
        self._rot_rows[key] = row
        return row

    def _rot_identity(self, b: NodeBatch, kind: str) -> np.ndarray:
        """The axis-order (identity) permutation row, cached with the
        per-order rows."""
        if self._rot_rows_b != id(b):
            self._rot_rows = {}
            self._rot_rows_b = id(b)
        key = ("id", kind)
        row = self._rot_rows.get(key)
        if row is None:
            if kind == "u":
                row = np.concatenate([
                    np.arange(b.n_real, dtype=np.int32),
                    np.full(b.n_pad + 1 - b.n_real, b.n_pad,
                            dtype=np.int32)])
            else:
                row = np.arange(b.n_pad, dtype=np.int32)
            self._rot_rows[key] = row
        return row

    def _burst_rotation(self, b: NodeBatch, n_pods: int,
                        start0: Optional[int] = None):
        """Per-cycle enumeration orders for a burst: pod 0 rides the device
        axis (the list_names() enumeration the shell just consumed); pod
        i >= 1 rides the order starting at the tree's current zone index
        walked i-1 steps through rotation_map. Returns None only when the
        tree can NEVER rotate (equal-size zones, single zone, no tree); an
        identity walk on a rotating tree still returns the (all-zero)
        machinery — rotation presence is a CLUSTER property, not a
        per-burst one, so the jit signature never flips between bursts
        (each flip costs a fresh multi-second XLA compile). The permutation
        row count is padded to a power-of-two bucket for the same reason."""
        if not self._tree_rotates():
            return None
        tree = self.node_tree
        nxt = tree.rotation_map()
        r = tree.zone_index
        length = n_pods + K.K_BATCH
        identity = self._rot_identity(b, "u")
        perm_rows = [identity]
        id_of_r: dict[int, int] = {}

        def order_id(rr: int) -> int:
            iid = id_of_r.get(rr)
            if iid is None:
                row = self._rot_cached(b, rr, identity, "u")
                if row is None:
                    iid = 0
                else:
                    perm_rows.append(row)
                    iid = len(perm_rows) - 1
                id_of_r[rr] = iid
            return iid

        seq = np.zeros(length, dtype=np.int32)
        if start0 is not None:
            # stale-axis mode (_axis_order): cycle 0's enumeration is
            # order_for_start(start0) of the RESIDENT axis, shipped as a
            # rotation order like every later cycle — no mirror permute
            seq[0] = order_id(start0)
        if nxt[r] == r:
            # fixed-point walk: every cycle >= 1 repeats P_r
            seq[1:] = order_id(r)
        else:
            for i in range(1, length):
                seq[i] = order_id(r)
                r = nxt[r]
        # stacked table cached by the row set (rows are pinned in the
        # per-batch cache, so the id tuple is stable): windows against a
        # stable tree reuse ONE host array — and downstream, one device
        # conversion (kernels._PERM_DEV_CACHE keys on its identity)
        skey = ("stack-u", tuple(map(id, perm_rows)))
        perms = self._rot_rows.get(skey)
        if perms is None:
            perms = np.stack(perm_rows)
            l_pad = _pad_pow2(len(perm_rows), 4)
            if len(perm_rows) < l_pad:
                perms = np.concatenate(
                    [perms,
                     np.repeat(perms[:1], l_pad - len(perm_rows), axis=0)])
            self._rot_rows[skey] = perms
        return perms, seq

    def _tree_rotates(self) -> bool:
        """True when the NodeTree's per-cycle enumeration can EVER differ
        from the device axis: multiple zones with uneven sizes (even sizes
        return the cursor to its start every full enumeration, so every
        cycle repeats the axis order)."""
        tree = self.node_tree
        if tree is None or len(tree._zones) <= 1:
            return False
        sizes = {len(tree._tree[z]) for z in tree._zones}
        return len(sizes) > 1

    def _generic_rotation(self, b: NodeBatch, bucket: int,
                          start0: Optional[int] = None):
        """(perms[L, n_pad], inv_perms, oid_seq[bucket]) for the generic
        scan: each in-burst cycle's enumeration order as axis indices
        (invalid rows tail every permutation so position-space feasibility
        masks them out). oid_seq[0] is the axis itself (the enumeration the
        shell just consumed for pod 0)."""
        tree = self.node_tree
        if tree is None:
            return None
        nxt = tree.rotation_map()
        r = tree.zone_index
        n_pad = b.n_pad
        identity = self._rot_identity(b, "g")
        perm_rows = [identity]
        id_of_r: dict[int, int] = {}

        def order_id(rr: int) -> int:
            iid = id_of_r.get(rr)
            if iid is None:
                row = self._rot_cached(b, rr, identity, "g")
                if row is None:
                    iid = 0
                else:
                    perm_rows.append(row)
                    iid = len(perm_rows) - 1
                id_of_r[rr] = iid
            return iid

        seq = np.zeros(bucket, dtype=np.int32)
        if start0 is not None:
            seq[0] = order_id(start0)   # stale-axis mode (_axis_order)
        for t in range(1, bucket):
            seq[t] = order_id(r)
            r = nxt[r]
        # the number of distinct orders varies with the starting zone index;
        # pad to a fixed row bucket so one compile serves every burst
        l_pad = _pad_pow2(len(perm_rows), 4)
        while len(perm_rows) < l_pad:
            perm_rows.append(perm_rows[0])
        skey = ("stack-g", tuple(map(id, perm_rows)))
        got = self._rot_rows.get(skey)
        if got is None:
            perms = np.stack(perm_rows)
            inv = np.empty_like(perms)
            for l in range(perms.shape[0]):
                inv[l, perms[l]] = np.arange(n_pad, dtype=np.int32)
            got = self._rot_rows[skey] = (perms, inv)
        perms, inv = got
        return perms, inv, seq

    # -- fused bursts, wave-windowed commit ----------------------------------
    # Round 10 moved the wave chain INTO the kernel: a burst is ONE
    # dispatch and ONE packed fetch (the round-7 pipeline paid one ~100ms
    # tunneled round trip per wave — the dominant ceiling PROFILE.md
    # names), and `wave_size` now sizes the COMMIT windows the host
    # consumes out of the single fetched block (bounded store/event
    # batches, same failure granularity as the pipelined rounds). Bursts
    # above B_CAP chunk at the kernel cap; chunk k+1's device execution
    # still overlaps chunk k's fetch+commit (the old pipeline, one level
    # up).
    wave_size = 4096
    # the shell passes a per-wave commit callback when the algorithm
    # advertises this (Scheduler._burst_segment)
    supports_wave_commit = True
    # -- N-deep launch queue (round 16) --------------------------------------
    # The round-7 pipeline kept ONE chunk in flight ahead of the chunk
    # being committed (2-deep). Serving at arrival rate needs the tunnel
    # RTT hidden ACROSS windows, not just inside one burst: launch_depth
    # is the number of launch windows planned+encoded+dispatched at once
    # (2 = the historical behavior), and launch_cap (None = B_CAP) caps
    # the chunk size so a serve window IS a launch chunk — while window k
    # commits, windows k+1..k+depth-1 are already on the device. Each
    # window stays ONE dispatch + ONE packed fetch (TestDeviceFetchContract
    # pins it at depth >= 3), and the rewind contract extends unchanged: a
    # refused/failed/aborted window cancels its in-flight successors
    # UNFETCHED and replans from the packed-block boundaries.
    launch_depth = 2
    launch_cap: Optional[int] = None
    # live launch-queue occupancy (windows dispatched, not yet consumed) —
    # the serving backpressure gate's inflight_fn reads it lock-free
    inflight_launches = 0
    # encode-at-admission pod-row cache (ops.pod_rows.PodRowCache),
    # attached by the scheduler shell: window planning gathers prebuilt
    # per-pod rows/signatures instead of re-encoding at line rate. None =
    # the pre-round-17 per-window encode (identical decisions either way)
    pod_rows = None

    def _fetch_pool_get(self):
        pool = self._fetch_pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor
            # two workers = the pipeline's in-flight window: wave k+1's
            # readback round trip can start while wave k's is still on the
            # wire (per-wave results are consumed strictly in wave order
            # via their own futures, so completion order doesn't matter)
            pool = self._fetch_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="tpu-fetch")
        return pool

    def _submit_fetch(self, tree):
        """Start the device->host readback of `tree` in the background:
        kick the async copy where the backend supports it, then hand the
        blocking sync to a fetch worker so the main thread stays free to
        commit the previous wave."""
        for leaf in jax.tree_util.tree_leaves(tree):
            cth = getattr(leaf, "copy_to_host_async", None)
            if cth is not None:
                try:
                    cth()
                except Exception:
                    pass   # backend without async copy: the worker blocks
        return self._fetch_pool_get().submit(jax.device_get, tree)

    def schedule_burst(self, pods: list[Pod], node_infos: dict[str, NodeInfo],
                       all_node_names: list[str],
                       bucket: Optional[int] = None,
                       commit=None) -> Optional[list[Optional[str]]]:
        """Schedule `pods` against one snapshot; returns per-pod host (or
        None when unschedulable). Decisions are serially equivalent to
        calling schedule() per pod with cache assumes in between. Returns
        None (whole-burst refusal) when burst semantics can't be made
        serial-equivalent here — the shell then runs the pods serially.

        The folded state persists on device: the caller MUST apply the
        returned placements to its cache (as the scheduler shell does via
        assume + note_burst_assumed) before the next cycle.

        `commit(lo, hosts) -> bool` (optional) is the wave-window sink:
        since round 10 the whole burst is ONE dispatch and ONE packed
        fetch, and `commit` is called with consecutive `wave_size` windows
        of DECIDED hosts (never None) consumed out of that single fetched
        block (bursts above B_CAP chunk, and a later chunk's device time
        still overlaps the earlier chunk's commit). Returning False
        signals a commit failure — the algorithm stops consuming the
        block, discards the undelivered decisions and the device folds
        (the host mirror is authoritative again), rewinds the walk
        counters to the delivered prefix, and returns that prefix with a
        None tail, exactly like the mid-burst-failure rewind contract.
        Decisions passed to `commit` are never re-returned as the
        caller's responsibility twice: the returned list still contains
        them, but the caller knows how far its own callback committed."""
        if not all_node_names or not pods:
            return [None] * len(pods)
        self.commit_marker = None
        if not self.breaker.allow_device():
            # circuit open (host-only mode): refuse the whole burst BEFORE
            # any dispatch — the shell runs the pods serially, where
            # schedule() picks the host twin under the same open circuit
            ORACLE_FALLBACKS.labels("circuit-open").inc()
            return None
        import time as _time
        _t0 = _time.perf_counter()
        _keys = [p.key for p in pods]

        def _obs(phase: str, t_start: float) -> float:
            now = _time.perf_counter()
            if self.metrics is not None:
                self.metrics.observe_phase(phase, now - t_start)
            name, cat = _PHASE_SPANS[phase]
            obs_trace.add_span(name, t_start, now, cat=cat)
            obs_ledger.LEDGER.stamp_many(_keys, _PHASE_SLOTS[phase], t=now)
            return now
        # stable-axis mode: keep the resident mirror/device axis when this
        # enumeration is a proven rotation of it (cycle 0 rides order id
        # start0) — the serving lane's windows skip the per-window permute
        # + full re-upload entirely
        axis_order, start0 = self._axis_order(all_node_names)
        b = self.encoder.encode(node_infos, axis_order)
        nodes = self._node_arrays(b)
        enc = PodEncoder(node_infos, b, self.services_fn(), self.replicasets_fn(),
                         hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                         enabled=self.enabled_predicates,
                         volume_listers=self.volume_listers,
                         volume_binder=self.volume_binder,
                         state_encoder=self.encoder)
        n = b.n_real
        num_to_find = num_feasible_nodes_to_find(n, self.percentage_of_nodes_to_score)
        bucket = _pad_pow2(bucket if bucket else len(pods), 16)
        uniform = None
        feats: Optional[list] = None
        # signatures from the encode-at-admission row cache (interned —
        # the identity fast path below) or the batched native build
        sigs = self._signatures(pods)
        s0 = sigs[0]
        uniform_spec = all(s is s0 or s == s0 for s in sigs)
        # tensor mode: per-pod profile ids (row-cache gather); a uniform
        # window must be single-PROFILE too — different weight rows change
        # the tie structure the K-batch modes rely on, so mixed-profile
        # windows ride the generic scan (which gathers rows per pod)
        pids = self._profile_ids(pods)
        pid0 = 0 if pids is None else int(pids[0])
        uniform_profile = pids is None or int(pids.min()) == int(pids.max())
        if num_to_find >= n and self.last_index == 0 and uniform_profile:
            # spec-identical pods produce identical encoder output against a
            # fixed snapshot, so the uniform path encodes ONE pod — per-pod
            # feature encoding (IPA topology counting in particular) is the
            # dominant host cost for affinity bursts
            if uniform_spec:
                uniform = self._uniform_class(pods[0], enc.encode(pods[0]),
                                              b, node_infos)
        if uniform is not None:
            # K-pods-per-pass kernel: dynamic pod count (one compile for any
            # burst size), carried int32 scores, consecutive-tie-rank batch
            # resolution with exact prefix validation (kernels.py K_BATCH)
            cls, extra_ok, ban = uniform
            rotation = self._burst_rotation(b, len(pods), start0)
            # flight recorder: capture BEFORE any wave commit can mutate
            # the cache's NodeInfos (deep capture clones the world here)
            fl = obs_flight.RECORDER.begin("uniform", self, [(pods, False)],
                                           all_node_names, node_infos)
            _t = _obs("encode", _t0)
            sel = self._uniform_waves(pods, b, cls, extra_ok, ban, rotation,
                                      n, commit, _obs, _t, bucket, fl=fl,
                                      pid=pid0)
            if sel is None:
                # device fault during a commit-less trial: whole-burst
                # refusal (nothing committed, counters rewound)
                return None
            return [b.names[s] for s in sel] \
                + [None] * (len(pods) - len(sel))
        from kubernetes_tpu.api.types import (
            has_pod_affinity_terms, get_container_ports)
        if any(has_pod_affinity_terms(p) or get_container_ports(p)
               for p in pods):
            # the generic scan encodes per-node masks ONCE per burst; pods
            # whose masks depend on in-burst placements (affinity/ports)
            # are only safe on the uniform path above — refuse, the shell
            # runs them serially
            ORACLE_FALLBACKS.labels("burst-affinity-mixed").inc()
            return None
        # spec-identical pods produce identical encoder output against a
        # fixed snapshot: encode ONE pod per signature and share (the O(N)
        # python feature loops — spread counting especially — dominate
        # otherwise; interned sigs make the memo an identity-hit dict)
        if uniform_spec:
            feats = [enc.encode(pods[0])] * len(pods)
        else:
            feat_by_sig: dict = {}
            feats = []
            for p, sig in zip(pods, sigs):
                f = feat_by_sig.get(sig)
                if f is None:
                    f = feat_by_sig[sig] = enc.encode(p)
                feats.append(f)
        # selector-spread counts change with every in-burst placement; the
        # scan carries them only for spec-identical pods (one selector set)
        carry_spread = any(f.spread_counts is not None for f in feats)
        if carry_spread and not uniform_spec:
            ORACLE_FALLBACKS.labels("burst-spread-mixed").inc()
            return None
        rotation = None
        rotation_pos = None
        if self._tree_rotates():
            # per-cycle rotated enumeration orders: ship the <= L distinct
            # permutations + each cycle's order id. In the full-scan regime
            # (num_to_find >= n) the gather-free position mode applies —
            # one [N] sort per cycle instead of three [N] gathers, which
            # serialize ~30x slower on TPU at 1k nodes. The rotation program
            # is selected from CLUSTER shape (uneven zones), not from
            # whether THIS burst's walk happens to be the identity: the
            # identity is just data (order id 0), while flip-flopping the
            # jit signature between bursts costs a fresh 10s+ XLA compile
            # mid-workload each time the zone cursor lands on a fixed point
            rot = self._generic_rotation(b, bucket, start0)
            if num_to_find >= n:
                rotation_pos = (rot[1], rot[2])   # inv_perms ARE positions
            else:
                rotation = rot
        spread0 = None
        if carry_spread:
            # the scan carries ONE [N] count vector; the stacked per-pod
            # field stays inert so no [B, N] upload happens
            spread0 = feats[0].spread_counts
        if uniform_spec:
            base = self._pod_arrays(feats[0], b.n_pad, upd_fields=True,
                                    pod=pods[0])
            if carry_spread:
                base["spread_counts"] = self._defaults["zeros_i64"]
            per_pod = [base] * len(pods)   # _stack_pods broadcasts by identity
        else:
            # one device-array dict per SIGNATURE (equal sigs -> identical
            # _pod_arrays output by construction), so _stack_pods
            # broadcasts repeated specs by identity instead of stacking B
            # copies — the mixed-window twin of the uniform fast path
            arr_by_sig: dict = {}
            per_pod = []
            for p, f, sig in zip(pods, feats, sigs):
                pp = arr_by_sig.get(sig)
                if pp is None:
                    pp = arr_by_sig[sig] = self._pod_arrays(
                        f, b.n_pad, upd_fields=True, pod=p)
                per_pod.append(pp)
        if carry_spread and (spread0 is None
                             or spread0.shape[-1] != b.n_pad):
            # inert/dense mix — shouldn't happen, stay exact
            ORACLE_FALLBACKS.labels("burst-spread-shape").inc()
            return None
        z_pad = _pad_pow2(len(b.zone_names), 4)
        # mesh mode rides the SAME _scan_waves driver below: since round 15
        # the generic scan kernel is one code path parameterized by the
        # sharding spec (K.schedule_batch(mesh=...)), so rotation, carried
        # spread counts, and the single-dispatch/single-fetch contract all
        # run sharded — the old burst-sharded-rotation / burst-sharded-
        # spread oracle fallbacks are deleted, not dodged.
        if pids is not None:
            # per-pod weight-row selection: shallow per-pod dicts so the
            # varying profile_id stacks while every other field keeps its
            # identity-broadcast (equal sigs still share field objects)
            per_pod = [dict(pp, profile_id=np.int64(pids[i]))
                       for i, pp in enumerate(per_pod)]
        fl = obs_flight.RECORDER.begin("scan", self, [(pods, False)],
                                       all_node_names, node_infos)
        _t = _obs("encode", _t0)
        return self._scan_waves(pods, b, per_pod, spread0, rotation,
                                rotation_pos, num_to_find, n, z_pad, bucket,
                                commit, _obs, _t, fl=fl)

    def _uniform_waves(self, pods: list[Pod], b: NodeBatch, cls, extra_ok,
                       ban: bool, rotation, n: int, commit, _obs,
                       _t: float, bucket: int,
                       fl=None, pid: int = 0) -> Optional[list]:
        """Single-launch driver for the uniform kernel: the ENTIRE burst
        (up to B_CAP; larger bursts chunk, with chunk k's fetch+commit
        overlapping chunk k+1's device execution) is ONE dispatch and ONE
        packed [cap+1] fetch, which the commit then consumes wave-by-wave
        (`wave_size` windows — the same bounded store/event batches the
        pipelined rounds used). Returns the decided selection prefix
        (device axis indices, all >= 0); the caller pads the undecided
        tail with None.

        Rewind contract, re-derived from the single fetched block: the
        uniform kernel's failures are a frozen-state suffix (F==0
        persists for identical pods), so the decided prefix is exactly
        the block's leading non-negative run. A commit failure (callback
        returned False) stops consumption — the rest of the block is
        discarded along with the resident folds, and the returned prefix
        ends at the last window handed to the callback."""
        # the launch cap IS the caller's burst bucket (clamped to B_CAP,
        # and to launch_cap when the serve loop pinned window-sized
        # chunks): the warmup burst rides the same bucket, so the one
        # compile per (bucket, class-flags) signature happens outside any
        # timed loop
        hard = K.B_CAP if not self.launch_cap \
            else min(K.B_CAP, int(self.launch_cap))
        cap = _pad_pow2(max(1, min(bucket, hard)), 16)
        W = max(1, min(int(self.wave_size), cap))
        n_pods = len(pods)
        chunks = [(lo, min(cap, n_pods - lo))
                  for lo in range(0, n_pods, cap)]
        lni_dev = self.last_node_index   # device scalar after chunk 0
        li_entry, lni_entry = self.last_index, self.last_node_index
        sel: list[int] = []
        inflight: list[tuple] = []

        def dispatch(ci: int) -> None:
            nonlocal lni_dev, _t
            lo, chunk = chunks[ci]
            rot = rotation
            if rotation is not None:
                win = np.empty(cap + K.K_BATCH, dtype=np.int32)
                piece = rotation[1][lo: lo + len(win)]
                win[: len(piece)] = piece
                win[len(piece):] = piece[-1] if len(piece) else 0
                rot = (rotation[0], win)
            t_d = obs_trace.now()
            chaos.check("device.dispatch")
            tensor = self._ptab is not None
            rows, packed, lni_out = K.schedule_batch_uniform(
                self._dev_nodes, dict(cls), chunk, lni_dev, n,
                self.check_resources,
                weights=self._union_weights if tensor else self.weights,
                rotation=rot, extra_ok=extra_ok, ban=ban, mesh=self.mesh,
                cap=cap, wtab=self._wtab() if tensor else None, pid=pid)
            self._note_ici("burst_uniform", chunk, b.n_pad)
            lni_dev = lni_out
            self._dev_nodes = {**self._dev_nodes, **rows}
            DEVICE_DISPATCH.labels("burst_uniform").inc()
            _t = _obs("kernel", _t)   # dispatch (async; fetch waits)
            inflight.append((ci, lo, chunk, self._submit_fetch(packed),
                             t_d))
            self.inflight_launches = len(inflight)

        aborted = False
        failed = False
        faulted = False
        depth = max(1, int(self.launch_depth))
        next_ci = 1
        try:
            dispatch(0)
            while inflight:
                # N-deep launch queue: keep up to `depth` windows
                # planned/encoded/dispatched while the oldest commits
                # (depth=2 is the historical one-ahead pipeline)
                while len(inflight) < depth and next_ci < len(chunks):
                    dispatch(next_ci)
                    next_ci += 1
                ci, lo, chunk, fut, t_d = inflight.pop(0)
                self.inflight_launches = len(inflight)
                chaos.node_dead_point("dispatch-fetch")
                chaos.check("device.fetch")
                h = fut.result()  # ONE fetch per launch: selections + lni
                chaos.node_dead_point("fetch-commit")
                t_done = obs_trace.now()
                DEVICE_FETCHES.labels("burst_uniform").inc()
                DEVICE_FETCHED_BYTES.labels("burst_uniform").inc(h.nbytes)
                obs_trace.add_span("burst.wave.device", t_d, t_done,
                                   cat="device", args={"chunk": ci})
                obs_flight.RECORDER.note_block(fl, h)
                _t = _obs("fetch", _t)
                chunk_sel = h[:chunk].tolist()
                bad = next((i for i, s in enumerate(chunk_sel) if s < 0),
                           chunk)
                if commit is not None and self.stale_scan is not None:
                    # mid-burst node death: none of THIS chunk's decisions
                    # have committed and its lni advance is not yet
                    # applied, so earlier (already-committed) chunks stand
                    # and this chunk refuses whole — the shell invalidates
                    # the dead rows and replans the remainder post-churn
                    decided = [b.names[s] for s in chunk_sel[:bad]]
                    dead = self.stale_scan(decided, b.names[:n])
                    if dead:
                        for item in inflight:
                            item[3].cancel()
                        inflight.clear()
                        self.discard_burst_folds()
                        obs_flight.RECORDER.note_outcome(fl, {
                            "hosts": [b.names[s] for s in sel],
                            "failed": False, "aborted": True})
                        raise StaleNodeRefusal(
                            dead,
                            max(1, sum(1 for hn in decided if hn in dead)))
                lni_chunk_start = self.last_node_index
                self.last_node_index += int(h[cap])
                # commit consumes the single fetched block wave-by-wave
                for wlo in range(0, bad, W):
                    hi = min(wlo + W, bad)
                    BURST_WAVES.labels("uniform").inc()
                    sel.extend(chunk_sel[wlo:hi])
                    if commit is not None:
                        # crash-restart checkpoint marker (the shell's
                        # recovery context source): exact walk counters at
                        # this window's two boundaries where the block
                        # carries them. The uniform kernel never advances
                        # last_index, and the packed block only holds the
                        # CHUNK's lni advance — so mid-chunk window
                        # boundaries have no exact lni (None; recovery
                        # degrades to reconcile-only there).
                        self.commit_marker = {
                            "li0": li_entry,
                            "lni0": (lni_chunk_start if wlo == 0 else None),
                            "li1": li_entry,
                            "lni1": (self.last_node_index if hi == chunk
                                     else None),
                            "committed0": lo + wlo, "committed1": lo + hi,
                        }
                        t_c0 = obs_trace.now()
                        ok = commit(lo + wlo,
                                    [b.names[s] for s in chunk_sel[wlo:hi]])
                        t_c1 = obs_trace.now()
                        obs_trace.add_span("burst.wave.commit", t_c0, t_c1,
                                           cat="host", args={"chunk": ci})
                        if inflight:
                            PIPELINE_OVERLAP.inc(t_c1 - t_c0)
                        _t = t_c1
                        if not ok:
                            aborted = True
                            break
                if bad < chunk or aborted:
                    for item in inflight:
                        item[3].cancel()
                    inflight.clear()
                    if aborted:
                        self.discard_burst_folds()
                    if bad < chunk:
                        failed = True
                    break
        except _DEVICE_FAULTS as e:
            # a failed launch/fetch: everything already committed stands
            # (its counters landed with its chunk); the faulted chunk
            # decided nothing, so the remainder of the burst degrades to
            # the serial oracle path via the undecided-tail contract
            self._device_fault(e)
            ORACLE_FALLBACKS.labels("device-fault").inc()
            for item in inflight:
                item[3].cancel()
            inflight.clear()
            self.discard_burst_folds()
            faulted = True
            if commit is None:
                # pure trial (gang): nothing was committed — rewind the
                # walk counters consumed by already-fetched chunks and
                # refuse outright, so the caller reruns the WHOLE trial
                # through the serial referee instead of misreading the
                # undecided tail as a rejected gang
                self.last_index, self.last_node_index = li_entry, lni_entry
                obs_flight.RECORDER.note_outcome(fl, {
                    "hosts": [], "failed": False, "aborted": True})
                return None
        finally:
            self.inflight_launches = 0
        if not (failed or aborted or faulted):
            self.breaker.record_success()
        obs_flight.RECORDER.note_outcome(fl, {
            # device-decided hosts up to the last commit/abort boundary;
            # `failed` marks that the NEXT pod found no node on device
            "hosts": [b.names[s] for s in sel],
            "failed": failed,
            "aborted": aborted,
        })
        return sel

    def _scan_waves(self, pods: list[Pod], b: NodeBatch, per_pod: list,
                    spread0, rotation, rotation_pos, num_to_find: int,
                    n: int, z_pad: int, bucket: int, commit, _obs,
                    _t: float, fl=None) -> list[Optional[str]]:
        """Single-launch driver for the generic lax.scan burst: the whole
        burst runs as ONE scan launch (scan length = the caller's bucket,
        so the warmup burst compiles the same program) and the host
        fetches ONE packed [3B] block — selections plus the per-pod walk
        counters. Commit then consumes the block wave-by-wave.

        Rewind contract, re-derived from slices of the single block: the
        scan keeps deciding after a failed pod, so everything from the
        first failure on is undecided and the committed-prefix counters
        are read straight out of the block (li_after/lni_delta at the
        last decided pod) — the failure path's second fetch is gone. A
        commit failure stops consumption at that window; the counters
        rewind to the last window handed to the callback and the resident
        folds drop either way (the host mirror is authoritative again)."""
        B = bucket
        n_pods = len(pods)
        W = max(1, min(int(self.wave_size), B))
        wave = list(per_pod)
        if len(wave) < B:
            pad = dict(wave[-1])
            pad["skip"] = self._true
            wave.extend([pad] * (B - len(wave)))
        stacked = self._stack_pods(wave)
        rot = rotp = None
        if rotation is not None:
            perms, inv_perms, seq = rotation
            rot = (perms, inv_perms, np.asarray(seq[:B], dtype=np.int32))
        elif rotation_pos is not None:
            rotp = (rotation_pos[0],
                    np.asarray(rotation_pos[1][:B], dtype=np.int32))
        t_d = obs_trace.now()
        try:
            chaos.check("device.dispatch")
            tensor = self._ptab is not None
            state, _li_out, _lni_out, _spread, outs = K.schedule_batch(
                self._dev_nodes, stacked, self.last_index,
                self.last_node_index, num_to_find, n, z_pad,
                weights=self._union_weights if tensor else self.weights,
                rotation=rot, spread0=spread0, rotation_pos=rotp,
                mesh=self.mesh, wtab=self._wtab() if tensor else None)
            self._note_ici("burst_scan", n_pods, b.n_pad)
            DEVICE_DISPATCH.labels("burst_scan").inc()
            _t = _obs("kernel", _t)
            chaos.node_dead_point("dispatch-fetch")
            chaos.check("device.fetch")
            h = np.asarray(self._submit_fetch(outs["packed"]).result())
            chaos.node_dead_point("fetch-commit")
        except _DEVICE_FAULTS as e:
            # the single dispatch+fetch happens BEFORE any commit or
            # counter update: refuse the whole burst — the shell reruns
            # the pods serially (host twin under an open circuit) against
            # the untouched host mirror, decisions identical
            self._device_fault(e)
            self.discard_burst_folds()
            ORACLE_FALLBACKS.labels("device-fault").inc()
            obs_flight.RECORDER.note_outcome(fl, {
                "hosts": [], "failed": False, "aborted": True})
            return None
        self.breaker.record_success()
        t_done = obs_trace.now()
        DEVICE_FETCHES.labels("burst_scan").inc()
        DEVICE_FETCHED_BYTES.labels("burst_scan").inc(h.nbytes)
        obs_trace.add_span("burst.wave.device", t_d, t_done, cat="device")
        obs_flight.RECORDER.note_block(fl, h)
        _t = _obs("fetch", _t)
        sel_arr = h[:n_pods]
        li_after = h[B:2 * B]
        lni_delta = h[2 * B:3 * B]
        lni0 = self.last_node_index
        neg = sel_arr < 0
        bad = int(np.argmax(neg)) if neg.any() else n_pods
        committed = bad
        aborted = False
        li_entry = self.last_index
        if commit is not None and self.stale_scan is not None:
            # mid-burst node death: a node from this launch's world is
            # gone from the store. NOTHING has committed (single fetch
            # precedes the first wave commit) and the walk counters are
            # untouched — drop the folds and refuse the launch whole; the
            # shell invalidates the dead rows and replans against the
            # post-churn world
            decided = [b.names[s] for s in sel_arr[:bad].tolist()]
            dead = self.stale_scan(decided, b.names[:n])
            if dead:
                self.discard_burst_folds()
                obs_flight.RECORDER.note_outcome(fl, {
                    "hosts": [], "failed": False, "aborted": True})
                raise StaleNodeRefusal(
                    dead, max(1, sum(1 for hn in decided if hn in dead)))
        if commit is not None:
            committed = 0
            for wlo in range(0, bad, W):
                hi = min(wlo + W, bad)
                BURST_WAVES.labels("scan").inc()
                # crash-restart checkpoint marker: the packed block carries
                # per-pod walk counters, so BOTH boundaries of every window
                # are exact on this path (recovery picks the side matching
                # what the store says actually landed)
                self.commit_marker = {
                    "li0": (li_entry if wlo == 0
                            else int(li_after[wlo - 1])),
                    "lni0": (lni0 if wlo == 0
                             else lni0 + int(lni_delta[wlo - 1])),
                    "li1": int(li_after[hi - 1]),
                    "lni1": lni0 + int(lni_delta[hi - 1]),
                    "committed0": wlo, "committed1": hi,
                }
                t_c0 = obs_trace.now()
                ok = commit(wlo,
                            [b.names[s] for s in sel_arr[wlo:hi].tolist()])
                t_c1 = obs_trace.now()
                obs_trace.add_span("burst.wave.commit", t_c0, t_c1,
                                   cat="host")
                _t = t_c1
                committed = hi
                if not ok:
                    aborted = True
                    break
        # walk counters at the consumed boundary, straight from the block
        if committed > 0:
            self.last_index = int(li_after[committed - 1])
            self.last_node_index = lni0 + int(lni_delta[committed - 1])
        if bad < n_pods or aborted:
            # post-failure scan folds (or folds for decisions a failed
            # commit discarded) never became decisions: drop the device
            # matrix — the host mirror reflects exactly the committed
            # prefix after note_burst_assumed
            self.discard_burst_folds()
        else:
            # persist the folds: the device-resident matrix is
            # authoritative for rows the scan mutated (the host mirror
            # catches up via note_burst_assumed; external changes still
            # arrive via dirty rows)
            self._dev_nodes = {**self._dev_nodes, **state}
        obs_flight.RECORDER.note_outcome(fl, {
            # the full device-decided prefix (commit aborts shorten the
            # RETURNED prefix but not what the device decided)
            "hosts": [b.names[s] for s in sel_arr[:bad].tolist()],
            "failed": bad < n_pods,
            "aborted": aborted,
        })
        return [b.names[s] for s in sel_arr[:committed].tolist()] \
            + [None] * (n_pods - committed)

    # -- fused segmented burst: one launch per drain window -------------------
    # The shell advertises gang segments to this entry so a whole drain
    # window — singleton runs AND PodGroups — rides ONE dispatch and ONE
    # packed fetch (kernels.schedule_batch_segments): gang boundaries are
    # scan segment boundaries, and the round-8 gang_checkpoint/gang_rewind
    # contract runs inside the device carry instead of as one launch per
    # gang trial.
    supports_fused_segments = True

    def schedule_burst_fused(self, segments, node_infos: dict[str, NodeInfo],
                             all_node_names: list[str],
                             bucket: Optional[int] = None):
        """Schedule a segmented drain window in ONE launch + ONE packed
        fetch. `segments` = [(pods, is_gang), ...] in queue order.

        Gang segments are all-or-nothing ON DEVICE: a member that finds no
        node rewinds the carry (mutable rows, li, lni, rotation cursor) to
        the segment checkpoint in-scan, the rest of the segment is
        skipped, and the window continues against the rewound state —
        exactly the serial trial→reject→park→continue sequence, with zero
        extra round trips and no discarded in-flight device work.

        Returns None when the window isn't expressible on this path (the
        caller falls back to the per-segment machinery), else
        {"segments": [...], "consumed": n_enumerations} with per-segment
        records:
          {"status": "decided",  "hosts": [...], "li", "lni", "t"}
          {"status": "rejected", "placed": k,    "li", "lni", "t"}  (gang)
          {"status": "failed",   "hosts": [decided prefix], "li","lni","t"}
          {"status": "undecided"}   (at/after a singleton failure)
        The li/lni/t triple is the carry at that segment's END boundary —
        the caller's abort target (fused_rewind) when a later commit comes
        up short. On return, last_index/lastNodeIndex are already set to
        the end of the decided prefix (a singleton failure's prefix is
        re-derived from per-pod slices of the single fetched block), and
        the resident folds persist unless that failure polluted them."""
        from kubernetes_tpu.api.types import (has_pod_affinity_terms,
                                              get_container_ports)
        n_total = sum(len(p) for p, _g in segments)
        if not all_node_names or n_total == 0:
            return None
        self.commit_marker = None
        if not self.breaker.allow_device():
            # circuit open (host-only mode): refuse the window before any
            # dispatch — the shell's per-segment fallback runs the serial
            # loop, where schedule() picks the host twin
            ORACLE_FALLBACKS.labels("circuit-open").inc()
            return None
        if self.nominated is not None and self.nominated.has_any():
            ORACLE_FALLBACKS.labels("fused-nominated-ghosts").inc()
            return None
        flat = [p for seg_pods, _g in segments for p in seg_pods]
        if any(has_pod_affinity_terms(p) or get_container_ports(p)
               or p.volumes for p in flat):
            # per-node masks that depend on in-burst placements (and volume
            # reservations) have no segment-rewind story on device
            ORACLE_FALLBACKS.labels("fused-pod-features").inc()
            return None
        import time as _time
        _t0 = _time.perf_counter()
        _keys = [p.key for p in flat]

        def _obs(phase: str, t_start: float) -> float:
            now = _time.perf_counter()
            if self.metrics is not None:
                self.metrics.observe_phase(phase, now - t_start)
            name, cat = _PHASE_SPANS[phase]
            obs_trace.add_span(name, t_start, now, cat=cat)
            obs_ledger.LEDGER.stamp_many(_keys, _PHASE_SLOTS[phase], t=now)
            return now

        axis_order, start0 = self._axis_order(all_node_names)
        b = self.encoder.encode(node_infos, axis_order)
        nodes = self._node_arrays(b)
        enc = PodEncoder(node_infos, b, self.services_fn(),
                         self.replicasets_fn(),
                         hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                         enabled=self.enabled_predicates,
                         volume_listers=self.volume_listers,
                         volume_binder=self.volume_binder,
                         state_encoder=self.encoder)
        feat_by_sig: dict = {}
        arr_by_sig: dict = {}
        per_pod = []
        for p, sig in zip(flat, self._signatures(flat)):
            f = feat_by_sig.get(sig)
            if f is None:
                f = feat_by_sig[sig] = enc.encode(p)
            if f.spread_counts is not None:
                # selector-spread counts carry through rewinds only with a
                # checkpointed spread vector the shell's plain-class gate
                # already excludes; refuse rather than drift
                ORACLE_FALLBACKS.labels("fused-spread-selectors").inc()
                return None
            pp = arr_by_sig.get(sig)
            if pp is None:
                # one array dict per signature: repeated specs broadcast
                # by identity through _stack_pods (same values — equal
                # sigs imply identical _pod_arrays output)
                pp = arr_by_sig[sig] = self._pod_arrays(
                    f, b.n_pad, upd_fields=True, pod=p)
            per_pod.append(pp)
        pids = self._profile_ids(flat)
        if pids is not None:
            # tensor mode: each pod selects its weight row in-kernel; the
            # shallow dict keeps every other field identity-broadcastable
            per_pod = [dict(pp, profile_id=np.int64(pids[i]))
                       for i, pp in enumerate(per_pod)]
        n = b.n_real
        num_to_find = num_feasible_nodes_to_find(
            n, self.percentage_of_nodes_to_score)
        B = _pad_pow2(max(bucket or 16, n_total), 16)
        rotation = rotation_pos = None
        if self._tree_rotates():
            # one burst-wide walk, indexed by enumerations CONSUMED inside
            # the kernel (the carried t) — a rejected gang rewinds the
            # cursor, so the walk must NOT be pre-sliced by pod position
            rot = self._generic_rotation(b, B, start0)
            if num_to_find >= n:
                rotation_pos = (rot[1], rot[2])
            else:
                rotation = rot
        seg_start = np.zeros(B, dtype=bool)
        gang = np.zeros(B, dtype=bool)
        idx = 0
        for seg_pods, is_gang in segments:
            seg_start[idx] = True
            if is_gang:
                gang[idx: idx + len(seg_pods)] = True
            BURST_SEGMENTS.labels("gang" if is_gang else "run").inc()
            idx += len(seg_pods)
        if idx < B:
            seg_start[idx] = True   # padding: its own inert segment
            pad = dict(per_pod[-1])
            pad["skip"] = self._true
            per_pod.extend([pad] * (B - idx))
        stacked = self._stack_pods(per_pod)
        z_pad = _pad_pow2(len(b.zone_names), 4)
        # flight recorder: the fused window is THE canonical record — gang
        # boundaries, rewinds and rotation state all ride one launch
        fl = obs_flight.RECORDER.begin("fused", self, segments,
                                       all_node_names, node_infos)
        _t = _obs("encode", _t0)
        t_d = obs_trace.now()
        try:
            chaos.check("device.dispatch")
            tensor = self._ptab is not None
            state, _li, _lni, _spread, packed = K.schedule_batch_segments(
                nodes, stacked, seg_start, gang, n_total, self.last_index,
                self.last_node_index, num_to_find, n, z_pad,
                weights=self._union_weights if tensor else self.weights,
                rotation=rotation, rotation_pos=rotation_pos,
                mesh=self.mesh, wtab=self._wtab() if tensor else None,
                gang_score=self._gang_score)
            self._note_ici("burst_fused", n_total, b.n_pad)
            DEVICE_DISPATCH.labels("burst_fused").inc()
            _t = _obs("kernel", _t)
            chaos.node_dead_point("dispatch-fetch")
            chaos.check("device.fetch")
            h = np.asarray(self._submit_fetch(packed).result())
            chaos.node_dead_point("fetch-commit")
        except _DEVICE_FAULTS as e:
            # the single dispatch+fetch happens BEFORE any counter update
            # or commit: refuse the window — the shell reruns every entry
            # through the per-segment machinery against the untouched host
            # mirror (which cascades to the serial loop under an open
            # circuit), decisions identical
            self._device_fault(e)
            self.discard_burst_folds()
            ORACLE_FALLBACKS.labels("device-fault").inc()
            obs_flight.RECORDER.note_outcome(fl, {
                "segments": [], "consumed": 0, "aborted": True})
            return None
        self.breaker.record_success()
        t_done = obs_trace.now()
        DEVICE_FETCHES.labels("burst_fused").inc()
        DEVICE_FETCHED_BYTES.labels("burst_fused").inc(h.nbytes)
        obs_trace.add_span("burst.wave.device", t_d, t_done, cat="device")
        obs_flight.RECORDER.note_block(fl, h)
        _obs("fetch", _t)
        sel = h[:B]
        li_after = h[B:2 * B]
        lni_delta = h[2 * B:3 * B]
        t_after = h[3 * B:4 * B]
        li0, lni0 = self.last_index, self.last_node_index

        def boundary(j: int) -> tuple[int, int, int]:
            if j < 0:
                return li0, lni0, 0
            return (int(li_after[j]), lni0 + int(lni_delta[j]),
                    int(t_after[j]))

        results = []
        fail_at = None   # first SINGLETON failure: everything after is
        idx = 0          # undecided (its serial rerun may preempt)
        for seg_pods, is_gang in segments:
            L = len(seg_pods)
            if fail_at is not None:
                results.append({"status": "undecided"})
                idx += L
                continue
            ss = sel[idx: idx + L]
            end_li, end_lni, end_t = boundary(idx + L - 1)
            def seqs(k: int) -> dict:
                # per-member walk counters (window-grain rewind targets for
                # a short commit inside a singleton run)
                return {"li_seq": li_after[idx: idx + k],
                        "lni_seq": lni0 + lni_delta[idx: idx + k],
                        "t_seq": t_after[idx: idx + k]}

            if is_gang:
                if (ss < 0).any():
                    # the kernel already rewound the carry; the boundary is
                    # the (restored) pre-gang state
                    results.append({"status": "rejected",
                                    "placed": int((ss >= 0).sum()),
                                    "li": end_li, "lni": end_lni,
                                    "t": end_t})
                else:
                    results.append({"status": "decided",
                                    "hosts": [b.names[s]
                                              for s in ss.tolist()],
                                    "li": end_li, "lni": end_lni,
                                    "t": end_t, **seqs(L)})
            elif (ss < 0).any():
                k = int(np.argmax(ss < 0))
                fail_at = idx + k
                end_li, end_lni, end_t = boundary(idx + k - 1)
                results.append({"status": "failed",
                                "hosts": [b.names[s]
                                          for s in ss[:k].tolist()],
                                "li": end_li, "lni": end_lni, "t": end_t,
                                **seqs(k)})
            else:
                results.append({"status": "decided",
                                "hosts": [b.names[s] for s in ss.tolist()],
                                "li": end_li, "lni": end_lni, "t": end_t,
                                **seqs(L)})
            idx += L
        if fail_at is not None:
            li_f, lni_f, consumed = boundary(fail_at - 1)
            # post-failure folds never became decisions: drop the matrix
            self.discard_burst_folds()
        else:
            li_f, lni_f, consumed = boundary(n_total - 1)
            self._dev_nodes = {**self._dev_nodes, **state}
        self.last_index, self.last_node_index = li_f, lni_f
        obs_flight.RECORDER.note_outcome(fl, {
            "segments": [{k: r[k] for k in ("status", "hosts", "placed")
                          if k in r} for r in results],
            "consumed": consumed,
        })
        return {"segments": results, "consumed": consumed}

    def fused_rewind(self, li: int, lni: int) -> None:
        """Abort handler for a fused window: a SHORT segment commit (pods
        vanished between decision and commit) makes the shell stop
        consuming the block — the walk counters rewind to the segment
        boundary it got from schedule_burst_fused and the resident folds
        drop (decisions past the boundary are discarded; the host mirror
        is authoritative again)."""
        self.last_index = int(li)
        self.last_node_index = int(lni)
        self.discard_burst_folds()

    # -- device preemption ---------------------------------------------------
    def preempt(self, pod: Pod, node_infos: dict[str, NodeInfo],
                all_node_names: list[str], fit_error, pdbs: list):
        """Device victim scan (kernels.preemption_scan): one launch replaces
        the reference's 16-goroutine fan-out over candidate nodes
        (generic_scheduler.go:966). Returns a PreemptionResult with
        decisions identical to the oracle Preemptor, or None when this
        preemption isn't expressible as resources + static masks (the
        caller falls back to the oracle).

        Eligible when: no active nominations, the incoming pod carries no
        volumes or extended-resource requests, and every POTENTIAL VICTIM
        (lower-priority pod on a candidate node) is mask-inert: it has no
        (anti-)affinity terms, declares no host ports when the incoming pod
        wants one, and matches none of the incoming pod's required
        (anti-)affinity term selectors. Affinity-bearing BYSTANDERS
        (priority >= the preemptor, or off the candidate set) are fine —
        they are never removed, so the pod's masks (selector/taints/ports/
        inter-pod-affinity) are invariant under victim removal and fold
        into the static feasibility vector."""
        from kubernetes_tpu.oracle.preemption import (
            pod_eligible_to_preempt_others, nodes_where_preemption_might_help,
            PreemptionResult, no_possible_victims)
        from kubernetes_tpu.api.types import (
            get_container_ports, get_resource_request)
        if not all_node_names:
            return None
        if not self.breaker.allow_device():
            # circuit open: the oracle Preemptor runs this scan instead
            ORACLE_FALLBACKS.labels("circuit-open").inc()
            return None
        if self.nominated is not None and self.nominated.has_any():
            ORACLE_FALLBACKS.labels("preempt-nominated-ghosts").inc()
            return None
        if pod.volumes:
            ORACLE_FALLBACKS.labels("preempt-pod-volumes").inc()
            return None
        req = get_resource_request(pod)
        if req.scalar:
            ORACLE_FALLBACKS.labels("preempt-scalar-request").inc()
            return None
        pod_ports = bool(get_container_ports(pod))
        a = pod.affinity
        pod_terms = []
        if a is not None:
            for grp in (a.pod_affinity, a.pod_anti_affinity):
                if grp is not None and grp.required:
                    pod_terms.extend(grp.required)
        if not pod_eligible_to_preempt_others(pod, node_infos):
            return PreemptionResult(None, [], [])
        candidates = nodes_where_preemption_might_help(
            node_infos, all_node_names, fit_error.failed_predicates)
        if not candidates:
            # preemption can't help anywhere: clear the pod's own stale
            # nomination (generic_scheduler.go:330-333)
            return PreemptionResult(None, [], [pod])
        if no_possible_victims(pod, node_infos, candidates):
            # same fast path as the oracle Preemptor — skip the device launch
            return PreemptionResult(None, [], [])
        b = self.encoder.encode(node_infos, all_node_names)
        nodes = self._node_arrays(b)
        vic, slots, gate = self._victim_inputs(
            node_infos, b, candidates, pod.priority, pdbs, pod=pod,
            pod_ports=pod_ports, pod_terms=pod_terms)
        if vic is None:
            ORACLE_FALLBACKS.labels(f"preempt-victims-{gate}").inc()
            return None
        enc = PodEncoder(node_infos, b, self.services_fn(),
                         self.replicasets_fn(),
                         hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                         enabled=self.enabled_predicates,
                         volume_listers=self.volume_listers,
                         volume_binder=self.volume_binder,
                         state_encoder=self.encoder)
        f = enc.encode(pod)
        if f.unknown_scalars:
            ORACLE_FALLBACKS.labels("preempt-unknown-scalars").inc()
            return None
        n_pad = b.n_pad
        feas = np.zeros(n_pad, bool)
        order_rank = np.full(n_pad, 1 << 30, np.int64)
        for order, name in enumerate(candidates):
            i = b.index[name]
            feas[i] = True
            order_rank[i] = order
        for mask in (f.sel_ok, f.taints_ok, f.unsched_ok, f.host_ok,
                     f.ports_ok):
            if mask is not None:
                feas &= np.asarray(mask, bool)
        if f.interpod_code is not None:
            # static under victim removal: no victim carries terms or
            # matches the pod's (gated above), so the full-cluster IPA
            # verdict holds for every mutated candidate
            feas &= np.asarray(f.interpod_code) == 0
        pod_in = {"req_cpu": np.int64(req.milli_cpu),
                  "req_mem": np.int64(req.memory),
                  "req_eph": np.int64(req.ephemeral_storage)}
        t_scan = obs_trace.now()
        try:
            chaos.check("device.dispatch")
            chaos.check("device.fetch")
            out = np.asarray(K.preemption_scan(
                nodes, vic, pod_in, feas, order_rank, b.n_real,
                self.check_resources, f.has_request, pod.priority,
                mesh=self.mesh))
            self._note_ici("preempt_scan", 1, b.n_pad)
        except _DEVICE_FAULTS as e:
            # the scan reads resident state and mutates nothing: refuse —
            # the caller falls back to the oracle Preemptor, whose
            # decisions are identical by the parity contract
            self._device_fault(e)
            ORACLE_FALLBACKS.labels("device-fault").inc()
            return None
        self.breaker.record_success()
        DEVICE_DISPATCH.labels("preempt_scan").inc()
        DEVICE_FETCHES.labels("preempt_scan").inc()
        DEVICE_FETCHED_BYTES.labels("preempt_scan").inc(out.nbytes)
        obs_trace.add_span("preempt.scan", t_scan, obs_trace.now(),
                           cat="device")
        winner = int(out[0])
        if winner < 0:
            return PreemptionResult(None, [], [])
        name = b.names[winner]
        flags = out[3:].astype(bool)
        # a zero-victim winner has no slots entry (preemption can still
        # pick it when another pod's nomination freed nothing — rare)
        victims = [p for j, p in enumerate(slots.get(name, ())) if flags[j]]
        return PreemptionResult(node_infos[name].node, victims, [])

    # victim-table planes the kernels read, device key <- host field
    _VIC_FIELDS = (("cpu", "cpu"), ("mem", "mem"), ("eph", "eph"),
                   ("prio", "prio"), ("start", "start"),
                   ("valid", "valid"), ("violating", "viol"))

    def _victim_inputs(self, node_infos: dict[str, NodeInfo], b: NodeBatch,
                       names, max_prio: int, pdbs: list,
                       pod: Optional[Pod] = None, pod_ports: bool = False,
                       pod_terms=()):
        """Resident [N, P] victim planes + slots map for a preemption scan.

        The table itself is persistent (encoder.victim_table: cached per
        node generation, re-sorted only for dirty rows, permuted on
        NodeTree rotation) and stays in HBM — a scan uploads only dirty
        rows. The eligibility gates that used to abort a per-scan Python
        encode midway are O(1) mask reads over the cached inertness-class
        planes, checked over exactly the candidate set: a potential victim
        (priority < max_prio on a candidate node) carrying affinity terms,
        conflicting ports, scalar resources, or matching the incoming
        pod's required terms — or a node the slot cap can't represent —
        still refuses, per-reason (VICTIM_GATE_REASONS), and the caller
        falls back to the oracle. Returns (vic dict, slots, None) or
        (None, None, reason)."""
        vt = self.encoder.victim_table(node_infos, b, pdbs,
                                       cap=K.PREEMPT_P)
        if len(names) == b.n_real and (names is b.names or
                                       list(names) == b.names):
            # whole-axis candidate set (the pressure path): skip the
            # per-name index gather
            cand = np.arange(b.n_real, dtype=np.int64)
        else:
            cand = np.fromiter((b.index[nm] for nm in names), np.int64,
                               len(names))
        # the overflow gate EXTENDS the old one: it fires on total pod
        # count > cap, a superset of the old potential-victim count check —
        # a dropped slot could be anyone's victim, so refuse outright
        if bool(vt.overflow[cand].any()):
            return None, None, "overflow"
        pot = vt.valid[cand] & (vt.prio[cand] < max_prio)
        if bool((pot & vt.aff[cand]).any()):
            return None, None, "affinity-terms"
        if pod_ports and bool((pot & vt.ports[cand]).any()):
            return None, None, "ports"
        if bool((pot & vt.scalar[cand]).any()):
            return None, None, "scalar"
        if pod_terms:
            from kubernetes_tpu.oracle.predicates import (
                pod_matches_any_term_mask)
            t = vt.table
            is_cand = np.zeros(b.n_pad, bool)
            is_cand[cand] = True
            hr = t.holder_row
            on_cand = (hr >= 0) & is_cand[np.where(hr >= 0, hr, 0)]
            pot_rows = on_cand & (t.prio < max_prio)
            if bool(pot_rows.any()) and bool(
                    (pod_matches_any_term_mask(pod, pod_terms, t)
                     & pot_rows).any()):
                return None, None, "term-match"
        return self._upload_victims(vt), vt.slots, None

    def _upload_victims(self, vt) -> dict:
        """Sync the device-resident victim planes from the host table:
        full upload on rebuild/permute (dirty_rows None), dirty-row scatter
        otherwise, nothing at all in the steady state — the same delta
        contract as the node matrix."""
        key = (vt.P, vt.valid.shape[0])
        if (self._dev_vic is None or self._dev_vic_key != key
                or vt.dirty_rows is None):
            host = {k: getattr(vt, f) for k, f in self._VIC_FIELDS}
            if self.mesh is not None:
                # the round-9 victim table under NamedSharding(mesh,
                # P("nodes")): [N, P] slot planes split on the node axis,
                # same residency/delta contract as the node matrix
                from kubernetes_tpu.parallel import sharding as S
                self._dev_vic = S.shard_victim_planes(self.mesh, host)
            else:
                self._dev_vic = {k: jnp.asarray(v) for k, v in host.items()}
            self._dev_vic_key = key
            DEVICE_DISPATCH.labels("vic_upload").inc()
            vt.dirty_rows = []
            return self._dev_vic
        if vt.dirty_rows:
            rows = np.asarray(sorted(set(vt.dirty_rows)), dtype=np.int32)
            bucket = _pad_pow2(len(rows), 16)
            rows = np.concatenate(
                [rows, np.full(bucket - len(rows), rows[0], dtype=np.int32)])
            upd = {k: getattr(vt, f)[rows] for k, f in self._VIC_FIELDS}
            self._dev_vic = _scatter_rows(self._dev_vic, rows, upd)
            DEVICE_DISPATCH.labels("vic_scatter").inc()
            vt.dirty_rows = []
        return self._dev_vic

    def prewarm_preempt(self, node_infos: dict[str, NodeInfo],
                        all_node_names: list[str], pdbs: list) -> None:
        """Build + upload the node matrix and the persistent victim table
        outside any timed/decision window — the steady-state condition:
        in production the table is maintained incrementally across cycles,
        so a preemption wave never pays the cold build. Consumes no
        rotation state and folds nothing."""
        b = self.encoder.encode(node_infos, all_node_names)
        self._node_arrays(b)
        self._upload_victims(
            self.encoder.victim_table(node_infos, b, pdbs, cap=K.PREEMPT_P))

    # batched pressure chunks: bounds the [B, ...] upload and lets chunk
    # k+1's launch overlap chunk k's on-device execution
    PRESSURE_B_CAP = 128

    def preempt_pressure_burst(self, pods: list[Pod],
                               node_infos: dict[str, NodeInfo],
                               all_node_names: list[str], pdbs: list):
        """Schedule-else-preempt a failed burst tail in ONE launch
        (kernels.pressure_batch) instead of one ~100ms round trip per failed
        pod. Replays the serial loop exactly: per pod in queue order, a
        ghost-aware schedule attempt (podFitsOnNode two-pass,
        generic_scheduler.go:598,627), then the victim scan + 5-criteria
        node pick (:966,1054,837), accumulating nominations as ghost load
        for the pods behind it.

        Eligible when: no pre-existing nominations, the NodeTree enumeration
        is the device axis every cycle (even zones), pod priorities are
        non-increasing (queue pop order — so every accumulated ghost counts
        for every later pod), each pod is resource-only (no volumes /
        affinity terms / host ports / scalars / stale nomination / spread
        selector match), and every potential victim is mask-inert. Returns
        None to refuse (shell falls back to the serial loop) or a per-pod
        outcome list:
          ("bound", host_name)           — scheduled, delta folded on device
          ("nominated", node, victims)   — preemption chose `node`
          ("failed", any_candidates)     — no fit, no preemption; the flag
            distinguishes "no candidate nodes" (the oracle clears the pod's
            own stale nomination, :330-333) from "candidates but no fit"."""
        from kubernetes_tpu.api.types import (has_pod_affinity_terms,
                                              get_container_ports,
                                              get_resource_request)
        if not pods or not all_node_names:
            return None
        import time as _time
        _t0 = _time.perf_counter()
        if not self.breaker.allow_device():
            # circuit open: the serial loop (host twin + oracle Preemptor)
            # runs the tail instead — decisions identical
            PRESSURE_GATES.labels("circuit-open").inc()
            return None
        if self.nominated is not None and self.nominated.has_any():
            PRESSURE_GATES.labels("nominated-ghosts").inc()
            return None
        if self._tree_rotates():
            PRESSURE_GATES.labels("tree-rotation").inc()
            return None
        prios = [p.priority for p in pods]
        if any(a < bb for a, bb in zip(prios, prios[1:])):
            PRESSURE_GATES.labels("priority-order").inc()
            return None
        for p in pods:
            if p.volumes or p.nominated_node_name:
                PRESSURE_GATES.labels("pod-features").inc()
                return None
            if has_pod_affinity_terms(p) or get_container_ports(p):
                PRESSURE_GATES.labels("pod-features").inc()
                return None
            if get_resource_request(p).scalar:
                PRESSURE_GATES.labels("pod-features").inc()
                return None
        axis_order, start0 = self._axis_order(all_node_names)
        b = self.encoder.encode(node_infos, axis_order)
        nodes = self._node_arrays(b)
        enc = PodEncoder(node_infos, b, self.services_fn(),
                         self.replicasets_fn(),
                         hard_pod_affinity_weight=self.hard_pod_affinity_weight,
                         enabled=self.enabled_predicates,
                         volume_listers=self.volume_listers,
                         volume_binder=self.volume_binder,
                         state_encoder=self.encoder)
        feat_by_sig: dict = {}
        feats = []
        for p in pods:
            sig = self._class_signature(p)
            f = feat_by_sig.get(sig)
            if f is None:
                f = feat_by_sig[sig] = enc.encode(p)
            feats.append(f)
        for f in feats:
            if f.unknown_scalars:
                PRESSURE_GATES.labels("pod-features").inc()
                return None
            if f.spread_counts is not None:
                # selector-spread scoring depends on in-burst placements;
                # the pressure scan doesn't carry spread counts
                PRESSURE_GATES.labels("spread-selectors").inc()
                return None
        press_weights = self.weights
        if self._ptab is not None:
            # tensor mode: the pressure kernel scores with ONE static
            # per-profile row (its ghost/victim machinery has no per-pod
            # row gather); a mixed-profile tail degrades to the serial
            # loop, whose per-pod twin configs are exact
            pids = self._profile_ids(pods)
            if int(pids.min()) != int(pids.max()):
                PRESSURE_GATES.labels("profile-mixed").inc()
                return None
            press_weights = self._profile_static[int(pids[0])]
        vic, slots, gate = self._victim_inputs(node_infos, b, all_node_names,
                                               prios[0], pdbs)
        if vic is None:
            PRESSURE_GATES.labels(f"victims-{gate}").inc()
            return None
        per_pod = []
        for p, f in zip(pods, feats):
            d = self._pod_arrays(f, b.n_pad, upd_fields=True, pod=p)
            d["pprio"] = np.int64(p.priority)
            per_pod.append(d)
        n = b.n_real
        num_to_find = num_feasible_nodes_to_find(
            n, self.percentage_of_nodes_to_score)
        z_pad = _pad_pow2(len(b.zone_names), 4)
        mut0 = {k: nodes[k] for k in K._MUTABLE}
        ghost_key = (b.n_pad, self.mesh)
        ghost0 = self._ghost_zeros.get(ghost_key)
        if ghost0 is None:
            ghost0 = {k: jnp.zeros(b.n_pad, jnp.int64)
                      for k in ("cpu", "mem", "eph", "cnt")}
            if self.mesh is not None:
                # ghost load lives on the node axis: split it like the rows
                from kubernetes_tpu.parallel import sharding as S
                ghost0 = {k: jax.device_put(v, S.node_sharding(self.mesh))
                          if v.shape[0] % self.mesh.devices.size == 0 else v
                          for k, v in ghost0.items()}
            self._ghost_zeros[ghost_key] = ghost0
        li, lni = self.last_index, self.last_node_index
        # flight recorder: pressure waves are dump-only records (no oracle
        # replay harness) — the digest still pins inputs + outcomes
        fl = obs_flight.RECORDER.begin("pressure", self, [(pods, False)],
                                       all_node_names, node_infos)
        # encode vs device-scan phase boundary: everything above is host
        # encode + delta upload; everything below is dispatch + the one
        # fetch that pays the round trip (bench --mode preempt reports it)
        _t_enc = _time.perf_counter()
        obs_trace.add_span("pressure.encode", _t0, _t_enc, cat="host")
        outs_chunks = []
        try:
            for lo in range(0, len(per_pod), self.PRESSURE_B_CAP):
                chaos.check("device.dispatch")
                chunk = per_pod[lo: lo + self.PRESSURE_B_CAP]
                bucket = _pad_pow2(len(chunk), 8)
                if len(chunk) < bucket:
                    pad = dict(chunk[-1])
                    pad["skip"] = self._true
                    chunk = chunk + [pad] * (bucket - len(chunk))
                stacked = self._stack_pods(chunk)
                mut0, ghost0, li, lni, outs = K.pressure_batch(
                    nodes, mut0, ghost0, stacked, vic, li, lni, num_to_find,
                    n, z_pad, weights=press_weights, mesh=self.mesh)
                self._note_ici("pressure_batch", len(chunk), b.n_pad)
                DEVICE_DISPATCH.labels("pressure_batch").inc()
                outs_chunks.append(outs)
            # ONE fetch for every chunk's outputs + the final counters
            t_fetch = obs_trace.now()
            chaos.check("device.fetch")
            h_chunks, li, lni = jax.device_get((outs_chunks, li, lni))
        except _DEVICE_FAULTS as e:
            # everything so far is device-local (the resident matrix,
            # counters, and host mirror are untouched until after the
            # fetch): refuse the wave — the shell's serial loop re-derives
            # identical schedule/preempt decisions through the oracle
            self._device_fault(e)
            PRESSURE_GATES.labels("device-fault").inc()
            obs_flight.RECORDER.note_outcome(fl, {"outcomes": [],
                                                  "aborted": True})
            return None
        self.breaker.record_success()
        # ONE synchronization for the whole wave regardless of chunk count —
        # the tunnel contract the preemption-lane test pins
        DEVICE_FETCHES.labels("pressure_batch").inc()
        DEVICE_FETCHED_BYTES.labels("pressure_batch").inc(
            _fetched_nbytes(h_chunks))
        obs_trace.add_span("pressure.fetch", t_fetch, obs_trace.now(),
                           cat="device")
        self.last_preempt_phases = {
            "encode": _t_enc - _t0,
            "scan": _time.perf_counter() - _t_enc,
        }
        outcomes = []
        k = 0
        for h in h_chunks:
            bb = len(h["selected"])
            for j in range(bb):
                if k >= len(pods):
                    break
                sel = int(h["selected"][j])
                win = int(h["winner"][j])
                if sel >= 0:
                    outcomes.append(("bound", b.names[sel]))
                elif win >= 0:
                    name = b.names[win]
                    flags = h["victims"][j].astype(bool)
                    victims = [p for s, p in enumerate(slots.get(name, []))
                               if flags[s]]
                    outcomes.append(("nominated", name, victims))
                else:
                    outcomes.append(("failed", bool(h["any_cand"][j])))
                k += 1
        # persist: the mutable rows now live on device (successes folded);
        # the shell syncs the host mirror per bound pod via
        # note_burst_assumed, exactly like the burst prefix commit
        self._dev_nodes = {**self._dev_nodes, **mut0}
        self.last_index = int(li)
        self.last_node_index = int(lni)
        obs_flight.RECORDER.note_outcome(fl, {"outcomes": [
            oc if oc[0] != "nominated"
            else ("nominated", oc[1], sorted(v.name for v in oc[2]))
            for oc in outcomes]})
        return outcomes

    # -- gang (PodGroup) checkpoint/rewind -----------------------------------
    # PR 3's rewind contract generalized from per-wave to per-GROUP: a gang
    # trial runs through the ordinary wave machinery (schedule_burst with no
    # commit callback, so nothing reaches the cache/store), and either the
    # WHOLE gang's folds persist or the carries — li, lni, the device-resident
    # node matrix, and (via the shell) the NodeTree rotation cursor — rewind
    # to this checkpoint as if the gang was never attempted.
    def gang_checkpoint(self) -> dict:
        """Snapshot the device carries at a group boundary. The matrix
        snapshot is kernels.gang_carry_checkpoint's zero-copy pin: trial
        folds build new arrays, so the pre-gang rows stay resident and a
        same-epoch rewind restores them without a re-upload."""
        return {"li": self.last_index, "lni": self.last_node_index,
                "dev": K.gang_carry_checkpoint(self._dev_nodes),
                "key": self._dev_key, "epoch": self._dev_epoch}

    def gang_rewind(self, chk: dict) -> None:
        """Discard everything since `chk`: in-flight folds are dropped and
        last_index/lastNodeIndex rewind to the pre-gang prefix. When no
        host upload/scatter happened since the checkpoint (the epoch
        matches), the pinned pre-gang matrix is restored in place — the
        common case pays ZERO device traffic for a rejected gang; otherwise
        the matrix is discarded and re-uploads from the host mirror (which
        never saw the trial: gang folds only commit on success)."""
        self.last_index = chk["li"]
        self.last_node_index = chk["lni"]
        if self._dev_nodes is not None:
            GANG_REWIND_FOLDS.inc()
        if chk["dev"] is not None and self._dev_epoch == chk["epoch"]:
            self._dev_nodes = chk["dev"]
            self._dev_key = chk["key"]
        else:
            self.discard_burst_folds()

    def discard_burst_folds(self) -> None:
        """Forget the device-resident node matrix: in-scan folds for burst
        decisions the shell discarded (the serial tail after a mid-burst
        failure) must not leak into later cycles — the next use re-uploads
        from the host mirror, which only reflects consumed decisions."""
        if self._dev_nodes is not None:
            DISCARDED_FOLDS.inc()
        self._dev_nodes = None

    def invalidate_node(self, host: str) -> None:
        """Mid-burst node death (the shell's _invalidate_dead_node): the
        device-resident node matrix and victim table carry a row for a
        node the store no longer has — drop both, and forget the
        encoder's per-node generation entries for `host` so nothing
        keyed to the dead row survives. The cache removal (which the
        shell performs first) changed NodeTree membership, so the next
        encode() sees a different node_order and rebuilds the mirror;
        the victim table rebuilds from its generation cache on the next
        scan. In-flight burst decisions past the detection point are
        discarded by the driver's abort/rewind contract."""
        self.discard_burst_folds()
        self._dev_vic = None
        self._dev_vic_key = None
        enc = self.encoder
        enc._generations.pop(host, None)
        enc._vt_gens.pop(host, None)

    def recover_device(self, li: Optional[int] = None,
                       lni: Optional[int] = None) -> None:
        """Crash-restart device reset (Scheduler.recover): drop every
        device-resident structure — the node matrix (in-flight folds for
        decisions that never committed must not survive the crash) and the
        victim table — and rewind the walk counters to the recovered
        commit boundary. The next encode re-uploads from the host mirror,
        which the cache reconcile has already made authoritative; the
        victim table rebuilds from its generation cache."""
        self.discard_burst_folds()
        self._dev_vic = None
        self._dev_vic_key = None
        if li is not None:
            self.last_index = int(li)
        if lni is not None:
            self.last_node_index = int(lni)
        self.commit_marker = None

    def debug_state(self) -> dict:
        """The /debug/sched device section: mirror shape + epochs, walk
        counters, victim-table generations/dirty rows, serial-path
        latencies — everything a stuck-scheduler triage reads first."""
        dev = self._dev_nodes
        mirror = None
        if dev is not None:
            any_field = dev.get("valid")
            mirror = {"fields": len(dev),
                      "n_pad": (None if any_field is None
                                else int(any_field.shape[-1]))}
        vt = getattr(self.encoder, "_vt", None)
        vic = None
        if vt is not None:
            vic = {"P": int(vt.P), "rows": int(vt.valid.shape[0]),
                   "generations": len(getattr(self.encoder, "_vt_gens", {})),
                   "dirty_rows": (None if vt.dirty_rows is None
                                  else len(vt.dirty_rows)),
                   "resident": self._dev_vic is not None}
        return {
            "mirror": mirror,
            "dev_epoch": self._dev_epoch,
            "breaker": self.breaker.debug_state(),
            "last_index": self.last_index,
            "last_node_index": self.last_node_index,
            "victim_table": vic,
            "mesh": self.mesh is not None,
            "devices": (1 if self.mesh is None
                        else int(self.mesh.devices.size)),
            "serial_path": self.serial_path,
            "serial_lat_ms": {
                "host_twin": (None if self._lat_ora is None
                              else round(self._lat_ora * 1e3, 3)),
                "device": (None if self._lat_dev is None
                           else round(self._lat_dev * 1e3, 3))},
        }

    def note_burst_assumed(self, pod: Pod, host: str, generation: int) -> None:
        """Post-burst bookkeeping for one placed pod: fold the same delta
        the device scan applied into the host numpy mirror and sync the
        encoder's generation map to the cache's post-assume generation, so
        the next encode() neither re-encodes nor re-uploads the row."""
        b = self.encoder._batch
        if b is None or host not in b.index:
            return
        self.encoder.note_assumed(b, host, pod, generation=generation,
                                  mark_dirty=False)

    def note_burst_assumed_many(self, pods: list[Pod], hosts: list[str],
                                generations: list) -> None:
        """Batched note_burst_assumed for a committed wave: one vectorized
        mirror scatter + one generation-map update instead of a Python call
        chain per pod (encoder.note_assumed_many). Entries whose node left
        the mirror or the cache (generation None) are skipped, matching the
        per-pod path's guard."""
        b = self.encoder._batch
        if b is None:
            return
        keep = [(p, h, g) for p, h, g in zip(pods, hosts, generations)
                if g is not None and h in b.index]
        if not keep:
            return
        kp, kh, kg = zip(*keep)
        self.encoder.note_assumed_many(b, list(kp), list(kh), list(kg))
