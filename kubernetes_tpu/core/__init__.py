"""Core device-scheduling package.

`StaleNodeRefusal` lives here (not in tpu_scheduler) so the shell can
import it without pulling jax into oracle-only processes.
"""


class StaleNodeRefusal(Exception):
    """A burst wave driver fetched a decision block that references nodes
    the store no longer has (mid-burst node death). Raised AFTER the
    committed prefix is reconciled and the device folds are discarded,
    BEFORE any decision from the block commits: the shell invalidates the
    dead nodes and replans the uncommitted remainder against the
    post-churn world, so the decision stream stays bit-identical to a
    serial loop that observed the death at the same boundary."""

    def __init__(self, dead: set, n_stale: int):
        super().__init__(
            f"{n_stale} in-flight decisions target vanished nodes "
            f"{sorted(dead)}")
        self.dead = dead
        self.n_stale = n_stale
