"""Partition math + shard claims for the active-active fleet.

A profile's namespaces hash onto a fixed ring of `n_shards` shards
(`shard_of`, crc32 — stable across processes and runs, so every
instance, the replay harness, and the bench agree on ownership without
coordination). Each shard is one `Lease` (`shard_lease_name`) claimed
through the PR 9 `LeaderElector`; rendezvous hashing over the LIVE
instance set (`preferred_owner`) assigns each shard a preferred owner,
so the claim layout is deterministic given membership, rebalances
automatically when an instance joins or dies, and moves only the dead
instance's shards on failover (rendezvous stability).

The fencing token of a claim is the shard Lease's resourceVersion at
ACQUISITION: the store assigns strictly increasing rvs, so every later
claimant's token is strictly greater, and the store's fence table
(commit core, native + twin) rejects a superseded claimant's writes
whole. `ShardClaimSet.step()` advances the fence through the store's
`advance_fence` verb BEFORE reporting a gain, so the instance replays
its new shard only after any zombie predecessor is already fenced out.
"""
from __future__ import annotations

import zlib
from typing import Optional

from kubernetes_tpu.api.types import Lease
from kubernetes_tpu.store.store import LEASES, NotFoundError
from kubernetes_tpu.utils.clock import Clock, RealClock
from kubernetes_tpu.utils.leader_election import (
    LeaderElectionConfig, LeaderElector,
)

DEFAULT_SHARDS = 8


def shard_of(namespace: str, n_shards: int = DEFAULT_SHARDS) -> int:
    """Stable namespace -> shard hash (crc32: identical across processes,
    Python versions, and runs — PYTHONHASHSEED never enters)."""
    return zlib.crc32(namespace.encode()) % max(1, n_shards)


def shard_lease_name(profile: str, shard: int) -> str:
    return f"fleet-{profile}-s{shard}"


def heartbeat_lease_name(profile: str, identity: str) -> str:
    return f"fleet-hb-{profile}-{identity}"


def preferred_owner(shard: int, live: list) -> Optional[str]:
    """Rendezvous (highest-random-weight) hash: each live instance scores
    crc32("{identity}:{shard}") and the max wins. Removing one instance
    moves ONLY its shards; adding one steals ~1/n of each peer's."""
    if not live:
        return None
    return max(sorted(live),
               key=lambda ident: zlib.crc32(f"{ident}:{shard}".encode()))


class ShardClaimSet:
    """One instance's live shard claims over the shared store.

    Composition of existing pieces, as the roadmap prescribes: a
    heartbeat `Lease` (node-heartbeat analog) makes the instance's
    liveness observable; one PR 9 `LeaderElector` per shard does the
    acquire/renew/step-down CAS dance on the shard Lease; rendezvous
    preference gates WHICH electors an instance steps, so claims
    converge to the deterministic layout without thundering herds.

    `step()` returns (gained, lost) shard lists after: renewing the
    heartbeat, computing the live set, stepping/releasing electors, and
    — for every gain — advancing the store's fence to the new claim
    token (the handoff write that makes a predecessor's late wave dead
    on arrival). The chaos seam `fleet.lease-loss` is consumed by
    `FleetInstance`, not here: a paused instance simply stops calling
    step() while continuing to schedule, which is exactly the zombie
    the fence exists to kill."""

    def __init__(self, store, profile: str, identity: str, peers: list,
                 n_shards: int = DEFAULT_SHARDS,
                 clock: Optional[Clock] = None,
                 lease_duration: float = 6.0,
                 renew_deadline: float = 4.0,
                 retry_period: float = 0.5):
        self.store = store
        self.profile = profile
        self.identity = identity
        self.peers = sorted(set(peers) | {identity})
        self.n_shards = int(n_shards)
        self.clock = clock or RealClock()
        self.lease_duration = float(lease_duration)
        self._electors = {
            shard: LeaderElector(store, LeaderElectionConfig(
                lock_name=shard_lease_name(profile, shard),
                identity=identity,
                lease_duration=lease_duration,
                renew_deadline=renew_deadline,
                retry_period=retry_period), clock=self.clock)
            for shard in range(self.n_shards)
        }
        #: shard -> fencing token (claim Lease rv at acquisition)
        self._tokens: dict[int, int] = {}
        #: shards reclaimed from an EXPIRED holder (failover accounting)
        self.failovers = 0

    # -- liveness ------------------------------------------------------------
    def _heartbeat(self, now: float) -> None:
        key = heartbeat_lease_name(self.profile, self.identity)
        try:
            def renew(lease):
                lease.renew_time = now
                return lease
            self.store.guaranteed_update(LEASES, key, renew)
        except NotFoundError:
            try:
                self.store.create(LEASES, Lease(
                    name=key, holder=self.identity, acquire_time=now,
                    renew_time=now, lease_duration=self.lease_duration))
            except Exception:   # noqa: BLE001 — lost create race: renew next step
                pass
        except Exception:       # noqa: BLE001 — store blip: retry next step
            pass

    def live_peers(self, now: float) -> list:
        """Peers (self included) whose heartbeat Lease is unexpired."""
        live = [self.identity]
        for peer in self.peers:
            if peer == self.identity:
                continue
            try:
                lease = self.store.get(
                    LEASES, heartbeat_lease_name(self.profile, peer))
            except Exception:   # noqa: BLE001 — absent or unreadable: not live
                continue
            if lease.renew_time + lease.lease_duration > now:
                live.append(peer)
        return sorted(live)

    # -- the claim step ------------------------------------------------------
    def _claim_token(self, shard: int) -> int:
        """The fencing token of a fresh acquisition: the shard Lease's rv
        right after the acquire CAS landed."""
        try:
            return int(self.store.get(
                LEASES, shard_lease_name(self.profile, shard))
                .resource_version)
        except Exception:   # noqa: BLE001 — vanished: poison token
            return 0

    def _advance_fence(self, shard: int, token: int) -> bool:
        advance = getattr(self.store, "advance_fence", None)
        if advance is None:
            return True   # store without fencing: partitioning + CAS only
        try:
            return bool(advance(shard_lease_name(self.profile, shard),
                                int(token)))
        except Exception:   # noqa: BLE001 — store blip: treat as lost
            return False

    def step(self) -> tuple[list, list]:
        """One claim-maintenance round. Returns (gained, lost) shards."""
        now = self.clock.now()
        self._heartbeat(now)
        live = self.live_peers(now)
        gained: list = []
        lost: list = []
        for shard, elector in self._electors.items():
            preferred = preferred_owner(shard, live) == self.identity
            was = elector.is_leader
            if preferred:
                had_holder = False
                if not was:
                    # failover accounting: acquiring a shard whose lease
                    # EXISTS with another (expired) holder is a reclaim
                    try:
                        cur = self.store.get(
                            LEASES, shard_lease_name(self.profile, shard))
                        had_holder = bool(cur.holder) \
                            and cur.holder != self.identity
                    except Exception:   # noqa: BLE001 — fresh shard
                        had_holder = False
                leading = elector.step()
                if leading and not was:
                    token = self._claim_token(shard)
                    if token <= 0 or not self._advance_fence(shard, token):
                        # a newer claimant already fenced past us: the
                        # acquire CAS we won is stale — give it back
                        elector.release()
                        continue
                    self._tokens[shard] = token
                    gained.append(shard)
                    if had_holder:
                        self.failovers += 1
                elif was and not leading:
                    self._tokens.pop(shard, None)
                    lost.append(shard)
            else:
                if was:
                    elector.release()
                if shard in self._tokens:
                    self._tokens.pop(shard, None)
                    lost.append(shard)
        return gained, lost

    def release_all(self) -> list:
        """Voluntary surrender of every claim (clean shutdown)."""
        lost = []
        for shard, elector in self._electors.items():
            if elector.is_leader:
                elector.release()
            if shard in self._tokens:
                self._tokens.pop(shard, None)
                lost.append(shard)
        return lost

    # -- the read surface the scheduler consumes -----------------------------
    def owned(self) -> set:
        return set(self._tokens)

    def tokens(self) -> dict:
        return dict(self._tokens)

    def owns(self, namespace: str) -> bool:
        return shard_of(namespace, self.n_shards) in self._tokens

    def fences(self) -> list:
        """[(scope, token), ...] for every live claim — what each wave or
        serial bind presents to the store's fence check."""
        return [(shard_lease_name(self.profile, shard), token)
                for shard, token in sorted(self._tokens.items())]


class ScriptedClaims:
    """Replay-side claim driver: the differential harness feeds it the
    RECORDED per-step claim map (shard -> token) instead of running
    electors, so a replayed instance observes exactly the ownership
    timeline the live instance did — lease traffic and all its store
    writes excluded by construction."""

    def __init__(self, profile: str, n_shards: int = DEFAULT_SHARDS):
        self.profile = profile
        self.n_shards = int(n_shards)
        self._tokens: dict[int, int] = {}

    def set_claims(self, tokens: dict) -> tuple[list, list]:
        """Install the recorded claim map; returns (gained, lost) exactly
        like ShardClaimSet.step()."""
        new = {int(s): int(t) for s, t in tokens.items()}
        gained = sorted(s for s in new if s not in self._tokens)
        lost = sorted(s for s in self._tokens if s not in new)
        self._tokens = new
        return gained, lost

    def step(self) -> tuple[list, list]:
        return [], []   # externally driven

    def release_all(self) -> list:
        lost = sorted(self._tokens)
        self._tokens = {}
        return lost

    def owned(self) -> set:
        return set(self._tokens)

    def tokens(self) -> dict:
        return dict(self._tokens)

    def owns(self, namespace: str) -> bool:
        return shard_of(namespace, self.n_shards) in self._tokens

    def fences(self) -> list:
        return [(shard_lease_name(self.profile, shard), token)
                for shard, token in sorted(self._tokens.items())]
