"""kubernetes_tpu.fleet — the active-active scheduler fleet (round 18).

N `Scheduler` instances share ONE apiserver/store (ROADMAP item 3; the
reference's multi-scheduler `spec.schedulerName` contract). Work is
partitioned two ways:

- BY PROFILE: a pod's `spec.schedulerName` names the scheduler class
  that owns it (per-tenant scheduler classes — PAPERS.md 2008.09213's
  heterogeneous per-tenant policies made deployable);
- WITHIN a profile, BY NAMESPACE-HASH SHARD: the profile's namespaces
  hash into a fixed shard ring, and each shard is claimed through a
  `Lease` (the PR 10 kind) via the PR 9 elector — rendezvous hashing
  over the LIVE instance set (heartbeat leases) picks each shard's
  preferred owner, so claims rebalance when instances join, die, or
  pause.

Three layers make "no double-bind, ever" an invariant rather than a
probability:

1. PARTITIONING keeps two instances from even queueing the same pod
   (informer-delivery filter on profile + claimed shard);
2. FENCING kills the zombie window: every shard claim carries a fencing
   token (the claim Lease's resourceVersion at acquisition — strictly
   greater for every later claimant), every wave/bind write presents its
   tokens, and the store — native commit core and Python twin alike —
   rejects a superseded token's write WHOLE (`FencedError`: no bind, no
   event, no rv) before anything lands. A new claimant advances the
   fence BEFORE replaying its partition, so a paused instance's late
   wave is dead on arrival;
3. rv-CAS BINDS backstop whatever slips past both (claim handoff
   windows, nominated pods): a bind for an already-bound pod is refused
   by the store's already-bound check and the loser re-queues with
   backoff in creation order — the existing binding is never
   overwritten.

Failover is the PR 9 recovery contract scoped to a shard: a dead
instance's heartbeat goes stale, its shard leases expire, a survivor
acquires each lease, advances the fence, and replays the shard from the
store (bound pods are already adopted through the assigned-pod informer
path; unbound pods re-enter the queue in creation order) — so the
reclaimed partition's post-failover decision stream is bit-identical to
a solo scheduler that observed the same pod subset, which
`FleetManager`'s timeline recorder + `replay_instance` verify
differentially (tests/test_fleet.py, tests/sweep_fleet_seeds.py).
"""
from __future__ import annotations

from kubernetes_tpu import obs

# -- observability (registered BEFORE the submodule imports so the
# scheduler's lazy `from kubernetes_tpu.fleet import BIND_CONFLICTS`
# works even mid-import of this package) ------------------------------------
SHARD_CLAIMS = obs.gauge(
    "fleet_partition_shards",
    "Namespace-hash shards currently claimed, by instance.",
    ("instance",))
BIND_CONFLICTS = obs.counter(
    "fleet_bind_conflicts_total",
    "Cross-instance bind races resolved without a double-bind, by "
    "outcome: requeued (rv-CAS loser — the existing binding stood and "
    "the pod re-queued with backoff in creation order), fenced (a whole "
    "wave/bind rejected because its partition-lease fencing token was "
    "superseded; the pods were dropped to the claim's new holder).",
    ("outcome",))
DOUBLE_BINDS = obs.counter(
    "fleet_double_binds_total",
    "TRIPWIRE, pinned at zero: a pod's nodeName observed changing from "
    "one non-empty value to a different one on the shared store's watch "
    "stream. Partitioning + fencing + rv-CAS binds make this "
    "structurally impossible; any increment is a released invariant and "
    "fails every fleet sweep, test, and bench audit.")
FAILOVERS = obs.counter(
    "fleet_failovers_total",
    "Partition shards reclaimed from an expired holder (the previous "
    "holder's lease ran out — crash, pause, or partition), by the "
    "claiming instance.", ("instance",))
CLAIM_CHANGES = obs.counter(
    "fleet_claim_transitions_total",
    "Shard claim transitions, by kind: gained (acquired a shard and "
    "advanced its fence), lost (released or lost a shard and purged its "
    "pods from the queue).", ("kind",))

from kubernetes_tpu.fleet.partition import (   # noqa: E402
    DEFAULT_SHARDS, ScriptedClaims, ShardClaimSet, heartbeat_lease_name,
    preferred_owner, shard_lease_name, shard_of,
)
from kubernetes_tpu.fleet.instance import (    # noqa: E402
    FleetInstance, FleetScheduler,
)
from kubernetes_tpu.fleet.manager import (     # noqa: E402
    BindAuditor, FleetManager, replay_instance,
)

__all__ = [
    "BIND_CONFLICTS", "BindAuditor", "CLAIM_CHANGES", "DEFAULT_SHARDS",
    "DOUBLE_BINDS", "FAILOVERS", "FleetInstance", "FleetManager",
    "FleetScheduler", "SHARD_CLAIMS", "ScriptedClaims", "ShardClaimSet",
    "heartbeat_lease_name", "preferred_owner", "replay_instance",
    "shard_lease_name", "shard_of",
]
