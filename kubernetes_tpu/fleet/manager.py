"""Fleet orchestration: round-robin driver, double-bind tripwire, and
the differential replay harness behind the per-partition parity claim.

`FleetManager` steps N `FleetInstance`s against one store in a
DETERMINISTIC round-robin (the sweeps' requirement: trial N always
interleaves the same way), catches the `sched.crash` seam as a mid-burst
instance kill, and — with `record=True` — writes a timeline of
everything that is an INPUT to any one instance's decisions: the initial
store snapshot, every arrival batch, every clock step, and per step the
stepping instance's claim map, the store's fence table, and the binds
that landed (attributed exactly, because steps are serialized).

`BindAuditor` is the zero-double-bind tripwire: it folds the shared
store's pod watch stream and counts any nodeName transition from one
non-empty value to a different one on `fleet_double_binds_total` — the
counter every fleet test, sweep, and bench audit pins at zero.

`replay_instance` is the parity referee: re-run ONE instance's recorded
trajectory in a fresh world — same initial snapshot, same arrivals, same
clock, same claim timeline (ScriptedClaims; no lease traffic), every
OTHER instance's binds applied verbatim as store writes at the recorded
points, the recorded fence table re-applied before each step — and
require the solo re-run's bind stream to be bit-identical, step by step,
to what the live instance committed. A reclaimed partition's
post-failover stream therefore equals a solo scheduler that observed the
same pod subset, which is the tentpole's recovery contract. Steps where
the live instance was killed MID-BURST (`crashed`) are applied as
foreign writes instead of compared: a partial wave is real history for
the survivors, but not a deterministic program point to re-derive.
"""
from __future__ import annotations

from typing import Callable, Optional

from kubernetes_tpu import chaos
from kubernetes_tpu.fleet import DOUBLE_BINDS
from kubernetes_tpu.store.store import (
    DELETED, NODES, PODS, ExpiredError, Store,
)


class BindAuditor:
    """Fold the shared store's pod watch into (a) the per-scan list of
    fresh bindings, in commit order, and (b) the double-bind tripwire."""

    def __init__(self, store):
        # seed current nodeName state, THEN attach: a pod bound before
        # the auditor existed must not read as freshly bound
        self._node = {p.key: p.node_name for p in store.list(PODS)[0]}
        self._watch = store.watch(PODS)
        self.violations: list = []

    def scan(self) -> list:
        """Drain the watch; returns [(pod_key, node), ...] for bindings
        that landed since the last scan, in commit (rv) order."""
        try:
            events = self._watch.drain()
        except ExpiredError as e:
            # the audit window is load-bearing: a dropped auditor cannot
            # certify zero double-binds, so fail the harness loudly
            raise RuntimeError(
                f"bind auditor fell behind the watch window: {e}") from e
        binds = []
        for ev in events:
            key = ev.obj.key
            if ev.type == DELETED:
                self._node.pop(key, None)
                continue
            prev = self._node.get(key, "")
            cur = ev.obj.node_name
            if cur and not prev:
                binds.append((key, cur))
            elif cur and prev and cur != prev:
                DOUBLE_BINDS.inc()
                self.violations.append((key, prev, cur))
            self._node[key] = cur
        return binds

    def stop(self) -> None:
        self._watch.stop()


class FleetManager:
    """Deterministic round-robin driver over one shared store."""

    def __init__(self, store: Store, identities: list,
                 make_instance: Callable[[str], object],
                 clock=None, record: bool = False, profiles=None):
        self.store = store
        self.clock = clock
        self.identities = list(identities)
        self.make_instance = make_instance
        # round-19 scheduling profiles: when the fleet serves a
        # ProfileSet, create_pods REPORTS arrivals whose schedulerName no
        # fleet profile claims (scheduler_profile_unknown_total + event)
        # — such a pod would otherwise sit unowned forever, silently
        self.profiles = profiles
        self._recorder = None
        self.instances = {}
        for ident in self.identities:
            inst = make_instance(ident)
            inst.sync()
            self.instances[ident] = inst
        self.timeline: Optional[list] = [] if record else None
        if self.timeline is not None:
            self.timeline.append({
                "op": "start",
                "t": float(clock.now()) if clock is not None else 0.0,
                "nodes": [n.clone() for n in store.list(NODES)[0]],
                "pods": [p.clone() for p in store.list(PODS)[0]],
            })
        self.auditor = BindAuditor(store)
        self.crashes: list = []

    # -- recorded world inputs ----------------------------------------------
    def create_pods(self, pods: list) -> None:
        """Arrival batch: written to the store AND recorded (clones), so
        the replay feeds the identical sequence. Pods whose schedulerName
        no fleet profile claims are reported (never default-scored; they
        stay pending until a profile claims them)."""
        if self.timeline is not None:
            self.timeline.append(
                {"op": "create", "pods": [p.clone() for p in pods]})
        for pod in pods:
            self.store.create(PODS, pod)
            if self.profiles is not None \
                    and self.profiles.index_of(pod.scheduler_name) is None:
                if self._recorder is None:
                    from kubernetes_tpu.store.record import EventRecorder
                    self._recorder = EventRecorder(
                        self.store, component="fleet-manager")
                self.profiles.report_unknown(pod, recorder=self._recorder)

    def advance_clock(self, dt: float) -> None:
        if self.clock is None:
            raise RuntimeError("advance_clock needs the shared FakeClock")
        self.clock.step(dt)
        if self.timeline is not None:
            self.timeline.append({"op": "clock", "dt": float(dt)})

    # -- stepping ------------------------------------------------------------
    def step(self, ident: str) -> int:
        """Step one instance; attribute the binds that landed; record.
        A SchedulerCrash (the sched.crash seam) is the mid-burst kill:
        the instance is marked dead where it stood — a partial wave may
        have landed, which the auditor attributes faithfully."""
        inst = self.instances[ident]
        if inst.dead:
            return 0
        crashed = False
        bound = 0
        try:
            bound = inst.step()
        except chaos.SchedulerCrash:
            crashed = True
            inst.kill()
            self.crashes.append(ident)
        binds = self.auditor.scan()
        if self.timeline is not None:
            entry = {
                "op": "step",
                "inst": ident,
                "claims": dict(inst.claims.tokens()),
                "fences": (self.store.fence_table()
                           if hasattr(self.store, "fence_table") else {}),
                "binds": list(binds),
            }
            if crashed:
                entry["crashed"] = True
            self.timeline.append(entry)
        return bound

    def step_all(self) -> int:
        bound = 0
        for ident in self.identities:
            bound += self.step(ident)
        return bound

    def kill(self, ident: str) -> None:
        """Silent process death: leases expire on their own."""
        self.instances[ident].kill()
        if self.timeline is not None:
            self.timeline.append({"op": "kill", "inst": ident})

    def restart(self, ident: str) -> None:
        """Fresh process under the same identity: new scheduler, full
        re-list, empty claims (re-acquired through the normal protocol)."""
        inst = self.make_instance(ident)
        inst.sync()
        self.instances[ident] = inst
        if self.timeline is not None:
            self.timeline.append({"op": "restart", "inst": ident})

    def live_instances(self) -> list:
        return [i for i in self.instances.values() if not i.dead]

    def owned_disjoint(self) -> bool:
        """No shard is BELIEVED-owned by two live, claim-maintaining
        instances (partition sanity — the lease CAS makes true overlap
        impossible; this is the cheap assertion sweeps run every round).
        An instance whose claim maintenance is PAUSED (the
        fleet.lease-loss zombie window) is excluded: its stale belief is
        EXPECTED to overlap the usurper's fresh claim — that window is
        precisely what the store's fencing covers, and the zombie's
        writes are rejected there, not here."""
        seen: set = set()
        for inst in self.live_instances():
            if getattr(inst, "paused_claims", 0) > 0:
                continue
            owned = inst.claims.owned()
            if owned & seen:
                return False
            seen |= owned
        return True

    def stats(self) -> dict:
        return {
            "instances": {i: inst.stats()
                          for i, inst in self.instances.items()},
            "double_binds": len(self.auditor.violations),
            "crashes": list(self.crashes),
        }


def replay_instance(timeline: list, target: str,
                    make_solo: Callable[[Store, object], object]) -> dict:
    """Differential replay of one instance's recorded trajectory (see
    module docstring). `make_solo(store, clock)` must build a
    FleetInstance for `target` with ScriptedClaims and the same
    scheduler configuration the live run used. Returns
    {"compared": n, "mismatches": [...]} — an empty mismatch list is the
    per-partition bit-identity verdict."""
    from kubernetes_tpu.utils.clock import FakeClock
    store: Optional[Store] = None
    clock = None
    solo = None
    auditor: Optional[BindAuditor] = None
    mismatches: list = []
    compared = 0
    for i, entry in enumerate(timeline):
        op = entry["op"]
        if op == "start":
            clock = FakeClock(entry["t"])
            store = Store(watch_log_size=1 << 17)
            for node in entry["nodes"]:
                store.create(NODES, node.clone())
            for pod in entry["pods"]:
                store.create(PODS, pod.clone())
            solo = make_solo(store, clock)
            solo.sync()
            auditor = BindAuditor(store)
        elif op == "nodes":
            for node in entry["nodes"]:
                store.create(NODES, node.clone())
        elif op == "create":
            for pod in entry["pods"]:
                store.create(PODS, pod.clone())
        elif op == "clock":
            clock.step(entry["dt"])
        elif op == "restart" and entry["inst"] == target:
            # the live run restarted the instance as a fresh process:
            # rebuild the solo the same way (full re-list, empty claims)
            solo = make_solo(store, clock)
            solo.sync()
        elif op == "step":
            # the store-side fence evolution is an input to every
            # instance's decisions: re-apply the recorded table BEFORE
            # the step (advance is monotonic, so replaying a snapshot
            # is idempotent)
            if hasattr(store, "advance_fence"):
                for scope, token in sorted(entry["fences"].items()):
                    store.advance_fence(scope, token)
            binds = [tuple(b) for b in entry["binds"]]
            if entry["inst"] == target and not entry.get("crashed"):
                solo.apply_claims(entry["claims"])
                solo.loop.step()
                got = auditor.scan()
                compared += 1
                if got != binds:
                    mismatches.append({
                        "step": i,
                        "want": binds,
                        "got": got,
                    })
            elif binds:
                # every other instance's committed decisions (and the
                # target's own crashed partial wave) are foreign store
                # writes, applied verbatim at the recorded point
                store.bind_pods(binds)
                auditor.scan()
    if auditor is not None:
        auditor.stop()
    return {
        "compared": compared,
        "mismatches": mismatches,
        "replay_double_binds": list(auditor.violations)
        if auditor is not None else [],
    }
