"""One fleet member: a partition-filtered Scheduler + its shard claims.

`FleetScheduler` narrows the reference's multi-scheduler responsibility
check (`spec.schedulerName == name`) with the live namespace-hash claim
set, so the informer-delivery filter keeps unowned pods out of the queue
entirely. `FleetInstance` wires the claims into the scheduler's fence
provider (every wave/bind write carries the claim tokens), runs the
serve-style step loop, and implements the two ownership transitions:

- GAIN (claim acquired, fence already advanced by the claim protocol):
  replay the shard from the authoritative store — the PR 9 recovery
  contract scoped to one shard. Bound pods are already adopted through
  the assigned-pod informer path (the cache watches ALL bound pods,
  cluster-wide — capacity math needs every binding, whoever made it);
  unbound owned pods re-enter the queue in creation order (the store
  lists in insertion order), exactly the arrival order a never-failed
  owner's informer would have fed its queue.
- LOSE (claim released, expired, or superseded): purge the shard's pods
  from the queue and row cache — the new owner replays them; holding
  them would only manufacture rv-CAS conflicts.

The `fleet.lease-loss` chaos seam fires here: the instance PAUSES claim
maintenance for a few steps while continuing to schedule — the zombie
window. Its leases expire, a peer claims + advances the fence, and the
store rejects the zombie's next wave whole (FencedError), which the
scheduler answers by dropping the wave's pods to the new owner.
"""
from __future__ import annotations

from typing import Optional

from kubernetes_tpu import chaos
from kubernetes_tpu.fleet import (
    CLAIM_CHANGES, FAILOVERS, SHARD_CLAIMS,
)
from kubernetes_tpu.fleet.partition import (
    DEFAULT_SHARDS, ShardClaimSet, shard_of,
)
from kubernetes_tpu.scheduler import DEFAULT_SCHEDULER_NAME, Scheduler
from kubernetes_tpu.serve.loop import ServeLoop
from kubernetes_tpu.store.store import PODS

#: steps of claim maintenance skipped when the lease-loss seam fires —
#: long enough (with the harness stepping the clock) for the leases to
#:  expire and a peer to claim + fence, i.e. a real zombie window
LEASE_LOSS_PAUSE_STEPS = 3


class FleetScheduler(Scheduler):
    """Scheduler whose responsibility = profile AND live shard claims.

    `_partition_filter` defaults to owning everything (a solo
    FleetScheduler is just a Scheduler); FleetInstance swaps in the
    claim check. The filter is consulted at informer delivery time
    through `_responsible_for`, so claim changes take effect at the next
    pump without re-registering handlers.

    With a round-19 ProfileSet attached, responsibility stays pinned to
    the instance's CLAIMED profile (self.name) — the set only supplies
    scoring: the claimed profile's weight-tensor row scores every owned
    pod, so fleet tenants get real per-tenant scheduler classes while
    partitioning semantics are untouched."""

    _partition_filter = staticmethod(lambda pod: True)

    def _responsible_for(self, pod) -> bool:
        return pod.scheduler_name == self.name \
            and self._partition_filter(pod)


class FleetInstance:
    """One active-active fleet member (see module docstring)."""

    def __init__(self, store, identity: str, peers: list,
                 profile: str = DEFAULT_SCHEDULER_NAME,
                 n_shards: int = DEFAULT_SHARDS,
                 use_tpu: bool = False,
                 clock=None,
                 window: int = 8, depth: int = 2,
                 lease_duration: float = 6.0,
                 renew_deadline: float = 4.0,
                 claims=None,
                 profiles=None,
                 **sched_kw):
        self.identity = identity
        self.profile = profile
        self.n_shards = int(n_shards)
        if profiles is not None and profiles.index_of(profile) is None:
            raise ValueError(
                f"claimed profile {profile!r} is not in the ProfileSet")
        self.sched = FleetScheduler(
            store, scheduler_name=profile, use_tpu=use_tpu, clock=clock,
            profiles=profiles, **sched_kw)
        self.claims = claims if claims is not None else ShardClaimSet(
            store, profile, identity, peers, n_shards=n_shards,
            clock=self.sched.clock, lease_duration=lease_duration,
            renew_deadline=renew_deadline)
        self.sched._partition_filter = \
            lambda pod: self.claims.owns(pod.namespace)
        self.sched.fence_provider = self._fences
        self.loop = ServeLoop(self.sched, window_size=window, depth=depth)
        self.dead = False
        #: >0 while the lease-loss seam has claim maintenance paused (the
        #: zombie window: scheduling continues on stale claims)
        self.paused_claims = 0

    # -- scheduler wiring ----------------------------------------------------
    def _fences(self) -> Optional[list]:
        return self.claims.fences() or None

    def owns_pod(self, pod) -> bool:
        return pod.scheduler_name == self.profile \
            and self.claims.owns(pod.namespace)

    # -- ownership transitions -----------------------------------------------
    def _adopt_shard(self, shard: int) -> int:
        """Shard replay on claim gain (PR 9 recovery, shard-scoped): list
        the authoritative store and re-enter every unbound owned pod in
        creation order. Returns pods enqueued."""
        CLAIM_CHANGES.labels("gained").inc()
        pods = [p for p in self.sched.store.list(PODS)[0]
                if not p.node_name and not p.deleted
                and p.scheduler_name == self.profile
                and shard_of(p.namespace, self.n_shards) == shard]
        if pods:
            # the informer batch-delivery verb: one queue lock + one
            # heap push + row-cache encode per batch, same as arrival
            self.sched._add_pods_to_queue(pods)
        return len(pods)

    def _drop_shard(self, shard: int) -> int:
        """Purge a lost shard's pods from queue + row cache. Returns pods
        dropped."""
        CLAIM_CHANGES.labels("lost").inc()
        dropped = 0
        pending = self.sched.queue.pending_pods()
        for bucket in pending.values():
            for pod in bucket:
                if pod.scheduler_name == self.profile \
                        and shard_of(pod.namespace, self.n_shards) == shard:
                    self.sched.queue.delete(pod)
                    if self.sched.pod_rows is not None:
                        self.sched.pod_rows.invalidate(pod)
                    dropped += 1
        return dropped

    def maintain_claims(self) -> tuple[list, list]:
        """One claim round + the gain/loss transitions. Split from
        step() so the manager (and the replay harness, via
        ScriptedClaims) can drive it at the recorded points."""
        before = self.claims.failovers if hasattr(self.claims, "failovers") \
            else 0
        gained, lost = self.claims.step()
        after = getattr(self.claims, "failovers", before)
        if after > before:
            FAILOVERS.labels(self.identity).inc(after - before)
        for shard in lost:
            self._drop_shard(shard)
        for shard in gained:
            self._adopt_shard(shard)
        SHARD_CLAIMS.labels(self.identity).set(
            float(len(self.claims.owned())))
        return gained, lost

    def apply_claims(self, tokens: dict) -> None:
        """Replay-side transition driver: install a recorded claim map
        (ScriptedClaims) and run the same gain/loss transitions the live
        instance ran."""
        gained, lost = self.claims.set_claims(tokens)
        for shard in lost:
            self._drop_shard(shard)
        for shard in gained:
            self._adopt_shard(shard)

    # -- the step loop -------------------------------------------------------
    def sync(self) -> None:
        self.sched.sync()

    def step(self) -> int:
        """One fleet tick: claim maintenance (unless paused by the
        lease-loss seam), then one serve tick (pump + cut windows).
        Returns pods bound."""
        if self.dead:
            return 0
        if chaos.take("fleet.lease-loss"):
            # the GC-pause / network-partition stand-in: claims freeze,
            # scheduling continues — the fence must kill what follows
            self.paused_claims = max(self.paused_claims,
                                     LEASE_LOSS_PAUSE_STEPS)
        if self.paused_claims > 0:
            self.paused_claims -= 1
        if self.paused_claims == 0:
            # claim maintenance resumes IN the step the pause ends, so
            # an unpaused instance never schedules on stale belief (the
            # manager's disjointness probe relies on exactly this)
            self.maintain_claims()
        return self.loop.step()

    def kill(self) -> None:
        """Process-death stand-in: stop stepping WITHOUT releasing
        anything — the leases expire on their own and a survivor
        reclaims (the failover the sweeps drive)."""
        self.dead = True

    def stats(self) -> dict:
        return {
            "identity": self.identity,
            "profile": self.profile,
            "shards": sorted(self.claims.owned()),
            "dead": self.dead,
            "paused_claims": self.paused_claims,
            "fenced_waves": self.sched.fenced_waves,
            "pods_bound": self.loop.pods_bound,
            "windows_cut": self.loop.windows_cut,
        }
