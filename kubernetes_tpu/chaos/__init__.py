"""kubernetes_tpu.chaos — seeded, deterministic fault-injection plane.

The paper's claim is 50x throughput WITH identical binding decisions, and
that contract is only worth anything if it survives the failure modes a
sustained soak actually produces: tunnel hiccups mid-burst, store write
failures, slow watchers, native-extension faults, and scheduler restarts.
This module is the single switchboard for injecting those failures
DETERMINISTICALLY (per-seam seeded RNG streams — trial N of a chaos sweep
always injects the same faults at the same call sites) so every
degradation path in the repo is testable, reproducible, and benchmarkable.

Named seams (each consumer calls `chaos.check(seam)` / `chaos.take(seam)`
at the exact point the real failure would surface):

- ``device.dispatch`` / ``device.fetch`` — the TPU drivers raise a
  tunnel-style fault before a kernel launch / packed-block readback
  (core/tpu_scheduler.py; the device circuit breaker consumes these).
- ``store.commit_wave`` — Store.commit_wave fails BEFORE the core write
  lands (the retry loop re-runs the wave).
- ``store.commit_wave.ambiguous`` — the wave LANDED but the "response" is
  lost; the retry must dedupe on the wave token, never double-land.
- ``store.fanout`` — watch fan-out delivery is deferred (delivered by the
  next flush or the next consumer poll; events are never lost).
- ``native.commitcore`` / ``native.heapcore`` — a native extension call
  faults; the consumer demotes to its pure-Python twin mid-run.
- ``remote.http`` — RemoteStore requests raise a connection-reset-style
  transient (the per-verb-class retry layer consumes it).
- ``watch.drop`` — an embedded-store watch poll raises ExpiredError as if
  the consumer outran the log window (informer re-lists).
- ``clock.jump`` — a ChaosClock-wrapped clock jumps forward (lease-expiry
  / backoff-timer stress; opt-in via `wrap_clock`).
- ``sched.crash`` — a scheduler-crash seam for crash-restart tests: the
  consumer (tests) raises SchedulerCrash at a commit boundary and then
  exercises Scheduler.recover().
- ``node.dead`` — node churn at the WORST moments: the pipeline calls
  `node_dead_point(point)` at its churn-vulnerable crossings —
  ``dispatch-fetch`` / ``fetch-commit`` around every burst launch's
  packed fetch (a kill there is caught by the launch-level stale scan,
  which refuses the launch WHOLE and replans post-churn), ``pre-bind``
  inside the wave commit (caught by the per-wave stale filter:
  requeue-with-backoff), and ``pre-cycle`` before a serial cycle's
  decision (caught by the pre-decision reconciliation sweep). A firing
  seam invokes the harness-registered node hook (`set_node_hook`), which
  deletes a node from the store. Opt-in: blanket ``all=`` rates skip it
  (it needs a hook and — unlike every other seam — legitimately changes
  the post-churn world, so the churn parity harnesses drive the SAME
  kill schedule through their serial-oracle referee).
- ``serve.shed`` — the serving admission gate sheds a pod create it
  would otherwise have admitted (429 + Retry-After with the gate's
  normal suggested backoff): deterministic backpressure injection for
  the serve parity/chaos harnesses. Opt-in: it only fires where a
  BackpressureGate is attached, and — like node.dead — it legitimately
  changes which pods enter the cluster, so a blanket ``all=`` rate must
  not seed it (the serve referee drives the SAME shed schedule through
  both worlds).
- ``fleet.lease-loss`` — a fleet scheduler instance PAUSES its partition
  claim maintenance for a few steps (the GC-pause / network-partition
  stand-in) while still scheduling: its shard leases expire, a peer
  claims them and advances the fence, and the zombie's next wave must be
  rejected WHOLE by the store's fencing-token check (zero double-binds).
  Opt-in: it needs the fleet claim plumbing, and it legitimately moves
  partition ownership, so a blanket ``all=`` rate must not seed it.

Configuration:
- programmatic: ``chaos.plan(seed=42, rates={"device.fetch": 0.1})`` or
  ``chaos.plan(seed=42, all_rate=0.05)``;
- environment: ``KTPU_CHAOS="seed=42,all=0.05,device.fetch=0.2,limit=100"``
  (comma/space-separated key=value; ``all`` sets every seam, named seams
  override, ``limit`` caps injections per seam).

Every injection is recorded on ``chaos_injections_total{seam}`` and
annotated onto the flight recorder's live burst record, and the active
plan publishes a ``/debug/sched`` section — a chaos run's artifact trail
names exactly which faults fired where.
"""
from __future__ import annotations

import os
import random
import threading
import urllib.error
from typing import Optional

from kubernetes_tpu import obs

#: every named injection seam (the fault plane's public surface; tests pin
#: this set so a new seam cannot land unnamed)
SEAMS = (
    "device.dispatch",
    "device.fetch",
    "store.commit_wave",
    "store.commit_wave.ambiguous",
    "store.update_many",
    "store.evict_many",
    "store.fanout",
    "native.commitcore",
    "native.heapcore",
    "remote.http",
    "watch.drop",
    "clock.jump",
    "sched.crash",
    "node.dead",
    "serve.shed",
    "fleet.lease-loss",
)

#: seams a blanket `all=<rate>` never seeds: they need explicit opt-in
#: plumbing (a wrapped clock, a crash-driving harness, a node-kill hook,
#: an attached serving backpressure gate)
OPT_IN_SEAMS = ("clock.jump", "sched.crash", "node.dead", "serve.shed",
                "fleet.lease-loss",
                # batched-mutation seams (round 23): pre-land StoreFaults
                # at update_many / evict_many. Opt-in because the batched
                # verbs' callers (churn actors, the zone evictor) surface
                # the raise to their own tick loop — a blanket `all=`
                # plan must not start failing paths that round-13 chaos
                # runs never armed
                "store.update_many", "store.evict_many")

INJECTIONS = obs.counter(
    "chaos_injections_total",
    "Faults injected by the chaos plane, by seam. Zero outside chaos "
    "runs; in a chaos bench/sweep this is the denominator of every "
    "degraded-mode claim.", ("seam",))
DEMOTIONS = obs.counter(
    "native_demotions_total",
    "Native-extension consumers swapped to their pure-Python twin "
    "mid-run after a fault, by core (commitcore / heapcore). The "
    "store_commit_waves_total{impl} split proves post-demotion waves "
    "ride the twin without a wave being dropped.", ("core",))


class InjectedFault(Exception):
    """Base of every chaos-injected failure; `seam` names the injection
    point. Messages deliberately avoid the bench's transient-error markers
    so an injected fault is never silently retried by machinery that was
    not built to consume it."""

    def __init__(self, seam: str, message: Optional[str] = None):
        super().__init__(message or f"chaos: injected fault at seam {seam}")
        self.seam = seam


class DeviceFault(InjectedFault):
    """Tunnel-style device failure (the JaxRuntimeError stand-in): raised
    at the dispatch/fetch seams; consumed by the device circuit breaker."""


class StoreFault(InjectedFault):
    """Store write failure (commit_wave seams)."""


class FanoutFault(InjectedFault):
    """Watch fan-out delivery failure (delivery deferred, never lost)."""


class NativeFault(InjectedFault):
    """Native-extension fault; consumers demote to the Python twin."""


class SchedulerCrash(InjectedFault):
    """Scheduler process death stand-in (crash-restart tests raise it at a
    commit boundary, then drive Scheduler.recover())."""


class RemoteFault(InjectedFault, urllib.error.URLError):
    """Connection-reset-style transport failure: subclasses URLError so the
    remote client's existing transient handlers catch it unmodified."""

    def __init__(self, seam: str):
        InjectedFault.__init__(self, seam,
                               f"chaos: injected transport fault ({seam})")
        self.reason = "chaos: injected transport fault"


_FAULT_FOR = {
    "device.dispatch": DeviceFault,
    "device.fetch": DeviceFault,
    "store.commit_wave": StoreFault,
    "store.commit_wave.ambiguous": StoreFault,
    "store.update_many": StoreFault,
    "store.evict_many": StoreFault,
    "store.fanout": FanoutFault,
    "native.commitcore": NativeFault,
    "native.heapcore": NativeFault,
    "remote.http": RemoteFault,
    "watch.drop": InjectedFault,
    "clock.jump": InjectedFault,
    "sched.crash": SchedulerCrash,
    "node.dead": InjectedFault,
    "serve.shed": InjectedFault,
    "fleet.lease-loss": InjectedFault,
}


def device_fault_types() -> tuple:
    """Exception classes the device circuit breaker treats as a tunnel
    fault: the injected DeviceFault plus jax's runtime error (the type a
    real dropped dispatch/readback surfaces as)."""
    types: tuple = (DeviceFault,)
    try:
        from jax.errors import JaxRuntimeError
        types = types + (JaxRuntimeError,)
    except Exception:   # pragma: no cover — ancient jax without the alias
        pass
    return types


class ChaosPlan:
    """One deterministic injection schedule.

    Each seam draws from its OWN `random.Random(f"{seed}:{seam}")` stream,
    so injections at one seam never shift another seam's sequence — adding
    a new seam (or a consumer adding a call site) leaves every other
    seam's trial-N behavior bit-identical. `limit` bounds injections per
    seam (0 = unlimited); `limits` overrides it for named seams — the
    parity harnesses cap `store.commit_wave` BELOW the commit retry
    budget, because a wave whose every retry fails must re-queue its pods
    with backoff (correctness holds, bit-parity cannot)."""

    def __init__(self, seed: int = 0, rates: Optional[dict] = None,
                 limit: int = 0, jump_range: tuple = (0.5, 30.0),
                 limits: Optional[dict] = None):
        self.seed = int(seed)
        self.rates = {s: float(r) for s, r in (rates or {}).items()}
        self.limits = {s: int(n) for s, n in (limits or {}).items()}
        unknown = (set(self.rates) | set(self.limits)) - set(SEAMS)
        if unknown:
            raise ValueError(f"unknown chaos seams: {sorted(unknown)}")
        self.limit = int(limit)
        self.jump_range = jump_range
        self._rng = {s: random.Random(f"{self.seed}:{s}") for s in SEAMS}
        self._fired: dict[str, int] = {}
        self._lock = threading.Lock()

    def should(self, seam: str) -> bool:
        """One deterministic draw for `seam`; records the injection when it
        fires. Never raises — `check()` maps firing seams to exceptions."""
        rate = self.rates.get(seam, 0.0)
        if rate <= 0.0:
            return False
        cap = self.limits.get(seam, self.limit)
        with self._lock:
            if cap and self._fired.get(seam, 0) >= cap:
                return False
            if self._rng[seam].random() >= rate:
                return False
            self._fired[seam] = self._fired.get(seam, 0) + 1
        INJECTIONS.labels(seam).inc()
        try:
            from kubernetes_tpu.obs import flight
            flight.RECORDER.note_crash(f"chaos:{seam}")
        except Exception:   # observability must never break injection
            pass
        return True

    def jump(self, seam: str = "clock.jump") -> float:
        """Deterministic jump magnitude for a firing clock seam."""
        lo, hi = self.jump_range
        with self._lock:
            return lo + (hi - lo) * self._rng[seam].random()

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._fired)

    def describe(self) -> dict:
        return {"seed": self.seed, "rates": dict(self.rates),
                "limit": self.limit, "limits": dict(self.limits),
                "fired": self.counts()}


_PLAN: Optional[ChaosPlan] = None
_ENV_LOADED = False
_ENV_LOCK = threading.Lock()


def _parse_spec(spec: str) -> ChaosPlan:
    """KTPU_CHAOS grammar: comma/space-separated key=value pairs.
    `seed=<int>`, `limit=<int>` (per-seam injection cap), `all=<rate>`
    (every seam), any seam name as `<seam>=<rate>` (overrides `all`), and
    `limit.<seam>=<int>` (per-seam cap overriding `limit`)."""
    seed, limit, all_rate = 0, 0, None
    rates: dict[str, float] = {}
    limits: dict[str, int] = {}
    for tok in spec.replace(",", " ").split():
        if "=" not in tok:
            raise ValueError(f"KTPU_CHAOS: bad token {tok!r} (want k=v)")
        k, v = tok.split("=", 1)
        if k == "seed":
            seed = int(v)
        elif k == "limit":
            limit = int(v)
        elif k == "all":
            all_rate = float(v)
        elif k.startswith("limit.") and k[len("limit."):] in SEAMS:
            limits[k[len("limit."):]] = int(v)
        elif k in SEAMS:
            rates[k] = float(v)
        else:
            raise ValueError(f"KTPU_CHAOS: unknown seam {k!r}")
    if all_rate is not None:
        for s in SEAMS:
            # opt-in seams need dedicated plumbing; blanket rates skip them
            if s in OPT_IN_SEAMS:
                continue
            rates.setdefault(s, all_rate)
    return ChaosPlan(seed=seed, rates=rates, limit=limit, limits=limits)


def _load_env() -> None:
    global _PLAN, _ENV_LOADED
    with _ENV_LOCK:
        if _ENV_LOADED:
            return
        _ENV_LOADED = True
        spec = os.environ.get("KTPU_CHAOS")
        if spec:
            _PLAN = _parse_spec(spec)


def active() -> Optional[ChaosPlan]:
    """The installed plan (programmatic wins; else KTPU_CHAOS, parsed
    once). None = the fault plane is inert (the fast path: one global
    read per seam call)."""
    if not _ENV_LOADED:
        _load_env()
    return _PLAN


def plan(seed: int = 0, rates: Optional[dict] = None, limit: int = 0,
         all_rate: Optional[float] = None,
         jump_range: tuple = (0.5, 30.0),
         limits: Optional[dict] = None) -> ChaosPlan:
    """Install a deterministic injection plan (replaces any active one).
    `all_rate` seeds every seam except the opt-in clock/crash seams;
    explicit `rates` entries override it. `limits` caps injections for
    named seams (overriding the blanket `limit`)."""
    global _PLAN, _ENV_LOADED
    merged = dict(rates or {})
    if all_rate is not None:
        for s in SEAMS:
            if s in OPT_IN_SEAMS:
                continue
            merged.setdefault(s, all_rate)
    _ENV_LOADED = True          # programmatic plan overrides the env
    _PLAN = ChaosPlan(seed=seed, rates=merged, limit=limit,
                      jump_range=jump_range, limits=limits)
    return _PLAN


def disable() -> None:
    """Remove the active plan (and suppress KTPU_CHAOS re-parsing); the
    node-death hook is cleared too — it is plan-scoped harness plumbing."""
    global _PLAN, _ENV_LOADED, _NODE_HOOK
    _ENV_LOADED = True
    _PLAN = None
    _NODE_HOOK = None


def take(seam: str) -> bool:
    """True when the seam fires this call (recorded); the caller raises
    its own native exception type (e.g. the store's ExpiredError)."""
    p = active()
    return p is not None and p.should(seam)


def check(seam: str) -> None:
    """Raise the seam's mapped fault when the plan fires it; no-op (one
    global read) when the plane is inert."""
    p = active()
    if p is not None and p.should(seam):
        raise _FAULT_FOR[seam](seam)


def counts() -> dict[str, int]:
    p = active()
    return p.counts() if p is not None else {}


# -- node.dead: churn at the worst moments -----------------------------------
_NODE_HOOK = None


def set_node_hook(fn) -> None:
    """Install the node-death hook (None to clear): `fn(point)` is called
    when the node.dead seam fires at a pipeline point ("dispatch-fetch"
    or "fetch-commit") and performs the actual store deletion. The hook
    owns victim choice and any pending-kill bookkeeping — the seam only
    supplies deterministic timing."""
    global _NODE_HOOK
    _NODE_HOOK = fn


def node_dead_point(point: str) -> None:
    """Called by the pipeline at its node-churn-vulnerable moments
    (dispatch-fetch / fetch-commit / pre-bind / pre-cycle). Inert (one
    global read) without a hook AND a plan rating the seam — the hot
    path cost matches every other seam."""
    hook = _NODE_HOOK
    if hook is None:
        return
    p = active()
    if p is None or p.rates.get("node.dead", 0.0) <= 0.0:
        return
    if p.should("node.dead"):
        hook(point)


class ChaosClock:
    """Clock wrapper whose now() occasionally jumps forward (the
    fake-clock-jump seam): lease renewals, backoff expiries, and assume
    TTLs all see sudden time loss, exactly like a GC pause or a suspended
    VM. Wrap explicitly: `chaos.wrap_clock(clock)`."""

    def __init__(self, base):
        self._base = base
        self._skew = 0.0

    def now(self) -> float:
        p = active()
        if p is not None and p.should("clock.jump"):
            self._skew += p.jump()
        return self._base.now() + self._skew

    def sleep(self, seconds: float) -> None:
        self._base.sleep(seconds)

    def step(self, seconds: float) -> None:   # FakeClock passthrough
        self._base.step(seconds)


def wrap_clock(clock) -> ChaosClock:
    return ChaosClock(clock)


def _debug_section():
    p = active()
    return p.describe() if p is not None else None


obs.register_debug("chaos", _debug_section)
