"""Cache debugger: dump + cache-vs-informer comparison.

Mirrors pkg/scheduler/internal/cache/debugger/: CacheDumper.DumpAll
(dumper.go:39), CacheComparer.Compare (comparer.go:41) — the SIGUSR2
diagnostics that catch cache drift against the authoritative informers.
"""
from __future__ import annotations

import json
import signal
from typing import Optional

from kubernetes_tpu.store.informer import SharedInformer


class CacheComparer:
    """Compare the scheduler cache (+ queue) against informer truth."""

    def __init__(self, cache, queue, pod_informer: SharedInformer,
                 node_informer: SharedInformer):
        self.cache = cache
        self.queue = queue
        self.pod_informer = pod_informer
        self.node_informer = node_informer

    def compare_nodes(self) -> list[str]:
        informer_nodes = {n.name for n in self.node_informer.list()}
        cached = set(self.cache.dump()["nodes"])
        problems = []
        for name in informer_nodes - cached:
            problems.append(f"node {name} in informer but not in cache")
        for name in cached - informer_nodes:
            problems.append(f"node {name} in cache but not in informer")
        return problems

    def compare_pods(self) -> list[str]:
        """Assigned/assumed pods must match informer + queue state
        (comparer.go ComparePods: cached = assigned ∪ assumed; informer
        assigned ∪ queued must cover it)."""
        informer_assigned = {p.key for p in self.pod_informer.list()
                            if p.node_name}
        dump = self.cache.dump()
        cached_pods = {key for node in dump["nodes"].values()
                       for key in node["pods"]}
        assumed = set(dump["assumed_pods"])
        problems = []
        for key in informer_assigned - cached_pods:
            problems.append(f"pod {key} assigned in informer but not in cache")
        for key in cached_pods - informer_assigned - assumed:
            problems.append(f"pod {key} in cache but not assigned in informer")
        return problems

    def compare(self) -> list[str]:
        return self.compare_nodes() + self.compare_pods()


class CacheDumper:
    def __init__(self, cache, queue):
        self.cache = cache
        self.queue = queue

    def dump_all(self) -> str:
        pending = self.queue.pending_pods()
        return json.dumps({
            "cache": self.cache.dump(),
            "queue": {name: [p.key for p in pods]
                      for name, pods in pending.items()},
        }, indent=2)


class CacheDebugger:
    """debugger.go:29 — wires comparer+dumper, optionally onto SIGUSR2."""

    def __init__(self, cache, queue, pod_informer, node_informer):
        self.comparer = CacheComparer(cache, queue, pod_informer, node_informer)
        self.dumper = CacheDumper(cache, queue)

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        def handler(_sig, _frame):
            problems = self.comparer.compare()
            print(self.dumper.dump_all())
            for p in problems:
                print("CACHE DRIFT:", p)
        signal.signal(signum, handler)
