"""Per-node aggregate state — the host-side twin of the device row.

Mirrors the semantics of the reference's NodeInfo
(pkg/scheduler/nodeinfo/node_info.go:47): per-node resource sums, port set,
affinity-pod tracking, image states, and a monotonically increasing
generation used for incremental snapshotting (cache.go:210).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.types import (
    Node, Pod, ResourceAgg, get_pod_nonzero_requests, get_container_ports,
    has_pod_affinity_terms,
)


def calculate_resource(pod: Pod) -> ResourceAgg:
    """Reference: node_info.go:578 calculateResource — sums *regular*
    containers only. Init containers affect the incoming pod's request
    (predicates.GetResourceRequest) but NOT the node's usage aggregate."""
    r = ResourceAgg()
    for c in pod.containers:
        r.add_requests(c.requests_dict())
    return r

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


def normalized_image_name(name: str) -> str:
    """Reference: nodeinfo.node_info.go — append :latest when no tag/digest."""
    if ":" not in name.rsplit("/", 1)[-1] and "@" not in name:
        name = name + ":latest"
    return name


@dataclass(frozen=True)
class ImageStateSummary:
    size_bytes: int
    num_nodes: int


def _sanitize_ip(ip: str) -> str:
    return ip if ip else "0.0.0.0"


class HostPortInfo:
    """Set of used (protocol, hostIP, port) with 0.0.0.0 wildcard conflict
    semantics (reference: nodeinfo/host_ports.go:47 CheckConflict)."""

    def __init__(self):
        # ip -> set of (protocol, port)
        self._by_ip: dict[str, set[tuple[str, int]]] = {}

    def add(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        self._by_ip.setdefault(_sanitize_ip(ip), set()).add((protocol or "TCP", port))

    def remove(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        ip = _sanitize_ip(ip)
        s = self._by_ip.get(ip)
        if s is not None:
            s.discard((protocol or "TCP", port))
            if not s:
                del self._by_ip[ip]

    def check_conflict(self, ip: str, protocol: str, port: int) -> bool:
        if port <= 0:
            return False
        ip = _sanitize_ip(ip)
        key = (protocol or "TCP", port)
        if ip == "0.0.0.0":
            return any(key in s for s in self._by_ip.values())
        return key in self._by_ip.get(ip, set()) or key in self._by_ip.get("0.0.0.0", set())

    def clone(self) -> "HostPortInfo":
        out = HostPortInfo()
        out._by_ip = {ip: set(s) for ip, s in self._by_ip.items()}
        return out

    def __len__(self):
        return sum(len(s) for s in self._by_ip.values())





class NodeInfo:
    """Aggregated node state (reference: node_info.go:47)."""

    def __init__(self, node: Optional[Node] = None):
        self.node: Optional[Node] = None
        self.pods: list[Pod] = []
        self.pods_with_affinity: list[Pod] = []
        self.used_ports = HostPortInfo()
        self.requested = ResourceAgg()
        self.nonzero_cpu = 0
        self.nonzero_mem = 0
        self.allocatable = ResourceAgg()
        self.taints: tuple = ()
        self.image_states: dict[str, ImageStateSummary] = {}
        # per-cycle transient volume counts, written by the Max*VolumeCount
        # predicates under the BalanceAttachedNodeVolumes gate and read by
        # balanced-allocation's variance scorer (reference: node_info.go
        # TransientInfo; predicates.go:517-521)
        self.transient_allocatable_volumes: Optional[int] = None
        self.transient_requested_volumes: Optional[int] = None
        self.generation = next_generation()
        if node is not None:
            self.set_node(node)

    # -- node ---------------------------------------------------------------
    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = ResourceAgg.from_allocatable(node.allocatable)
        self.taints = node.taints
        # Standalone default: each image counts as present on 1 node. The
        # scheduler Cache overwrites these with cluster-wide summaries
        # (reference: cache.go:88 imageStates).
        self.image_states = {
            normalized_image_name(name): ImageStateSummary(img.size_bytes, 1)
            for img in node.images for name in img.names
        }
        self.generation = next_generation()

    def remove_node(self) -> None:
        self.node = None
        self.allocatable = ResourceAgg()
        self.taints = ()
        self.generation = next_generation()

    # -- pods ---------------------------------------------------------------
    def add_pod(self, pod: Pod) -> None:
        req = calculate_resource(pod)
        self.requested.milli_cpu += req.milli_cpu
        self.requested.memory += req.memory
        self.requested.ephemeral_storage += req.ephemeral_storage
        for k, v in req.scalar.items():
            self.requested.scalar[k] = self.requested.scalar.get(k, 0) + v
        ncpu, nmem = get_pod_nonzero_requests(pod)
        self.nonzero_cpu += ncpu
        self.nonzero_mem += nmem
        self.pods.append(pod)
        if has_pod_affinity_terms(pod):
            self.pods_with_affinity.append(pod)
        for p in get_container_ports(pod):
            self.used_ports.add(p.host_ip, p.protocol, p.host_port)
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        for i, p in enumerate(self.pods):
            if p.uid == pod.uid:
                del self.pods[i]
                break
        else:
            return False
        for i, p in enumerate(self.pods_with_affinity):
            if p.uid == pod.uid:
                del self.pods_with_affinity[i]
                break
        req = calculate_resource(pod)
        self.requested.milli_cpu -= req.milli_cpu
        self.requested.memory -= req.memory
        self.requested.ephemeral_storage -= req.ephemeral_storage
        for k, v in req.scalar.items():
            self.requested.scalar[k] = self.requested.scalar.get(k, 0) - v
        ncpu, nmem = get_pod_nonzero_requests(pod)
        self.nonzero_cpu -= ncpu
        self.nonzero_mem -= nmem
        for p in get_container_ports(pod):
            self.used_ports.remove(p.host_ip, p.protocol, p.host_port)
        self.generation = next_generation()
        return True

    def clone(self) -> "NodeInfo":
        out = NodeInfo()
        out.node = self.node
        out.pods = list(self.pods)
        out.pods_with_affinity = list(self.pods_with_affinity)
        out.used_ports = self.used_ports.clone()
        out.requested = self.requested.clone()
        out.nonzero_cpu = self.nonzero_cpu
        out.nonzero_mem = self.nonzero_mem
        out.allocatable = self.allocatable.clone()
        out.taints = self.taints
        out.image_states = dict(self.image_states)
        out.generation = self.generation
        return out


def cluster_utilization(node_infos) -> dict:
    """Requested/allocatable fill fractions over a NodeInfo snapshot —
    the `cluster_resource_utilization{resource}` gauge family's source
    and the tuner reward's live input (round 22). Resources with zero
    cluster allocatable read 0.0 (an empty snapshot is 0, not NaN: the
    scraper treats NaN as no-data and the gate must see "empty", not
    "absent")."""
    req = {"cpu": 0, "memory": 0, "ephemeral_storage": 0}
    alloc = {"cpu": 0, "memory": 0, "ephemeral_storage": 0}
    for ni in (node_infos.values() if hasattr(node_infos, "values")
               else node_infos):
        if ni.node is None:
            continue
        req["cpu"] += ni.requested.milli_cpu
        req["memory"] += ni.requested.memory
        req["ephemeral_storage"] += ni.requested.ephemeral_storage
        alloc["cpu"] += ni.allocatable.milli_cpu
        alloc["memory"] += ni.allocatable.memory
        alloc["ephemeral_storage"] += ni.allocatable.ephemeral_storage
    return {r: (req[r] / alloc[r] if alloc[r] > 0 else 0.0)
            for r in req}
