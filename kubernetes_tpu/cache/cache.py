"""Scheduler cache: authoritative in-memory cluster state with optimistic
assume/confirm/expire and generation-based incremental snapshots.

Mirrors the semantics of pkg/scheduler/internal/cache/cache.go:
- AssumePod (:274) — optimistically place a pod before binding completes;
  FinishBinding (:295) starts a TTL; cleanup (:632) expires it.
- AddPod (:385) — informer confirmation of an assumed pod.
- Per-node recency: nodes whose NodeInfo changed move to the head of a
  doubly-linked list (:134), so UpdateNodeInfoSnapshot (:210) only clones
  nodes whose generation is newer than the snapshot's.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.cache.node_info import NodeInfo, next_generation
from kubernetes_tpu.cache.node_tree import NodeTree
from kubernetes_tpu.utils.clock import Clock, RealClock

DEFAULT_ASSUME_TTL = 30.0  # seconds (reference: factory.go:250)


class CacheError(Exception):
    pass


class _ListItem:
    """Doubly-linked recency list node (reference: nodeInfoListItem :53)."""

    __slots__ = ("info", "prev", "next")

    def __init__(self, info: NodeInfo):
        self.info = info
        self.prev: Optional[_ListItem] = None
        self.next: Optional[_ListItem] = None


@dataclass
class _PodState:
    pod: Pod
    deadline: Optional[float] = None     # assumed-pod expiry once binding finished
    binding_finished: bool = False


@dataclass
class Snapshot:
    """NodeInfoSnapshot (reference: interface.go:125)."""
    node_infos: dict[str, NodeInfo] = field(default_factory=dict)
    generation: int = 0


class SchedulerCache:
    def __init__(self, ttl: float = DEFAULT_ASSUME_TTL,
                 clock: Optional[Clock] = None):
        self.ttl = ttl
        self.clock = clock or RealClock()
        self._lock = threading.RLock()
        self._nodes: dict[str, _ListItem] = {}
        self._head: Optional[_ListItem] = None
        self._pod_states: dict[str, _PodState] = {}   # uid -> state
        self._assumed: set[str] = set()               # uids
        self.node_tree = NodeTree()

    # -- recency list -------------------------------------------------------
    def _move_to_head(self, item: _ListItem) -> None:
        if self._head is item:
            return
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        item.prev = None
        item.next = self._head
        if self._head is not None:
            self._head.prev = item
        self._head = item

    def _remove_from_list(self, item: _ListItem) -> None:
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        if self._head is item:
            self._head = item.next
        item.prev = item.next = None

    def _touch(self, name: str) -> NodeInfo:
        """NodeInfo for mutation; creates a placeholder (node=None) like the
        reference does for pods that arrive before their node (:389)."""
        item = self._nodes.get(name)
        if item is None:
            item = _ListItem(NodeInfo())
            self._nodes[name] = item
        self._move_to_head(item)
        return item.info

    # -- pods ---------------------------------------------------------------
    def assume_pod(self, pod: Pod) -> None:
        """Reference: cache.go:274 — pod.node_name must already be set."""
        with self._lock:
            if pod.uid in self._pod_states:
                raise CacheError(f"pod {pod.key} already assumed/added")
            self._touch(pod.node_name).add_pod(pod)
            self._pod_states[pod.uid] = _PodState(pod)
            self._assumed.add(pod.uid)

    def assume_pods(self, pods: list) -> None:
        """Batched assume_pod for a committed burst wave: ONE lock
        acquisition for the wave instead of one per pod. Per-pod semantics
        are assume_pod's exactly (same placeholder creation, same recency
        touch, same already-assumed error — raised after the earlier pods
        of the batch landed, matching what the serial loop would have
        done)."""
        with self._lock:
            for pod in pods:
                if pod.uid in self._pod_states:
                    raise CacheError(f"pod {pod.key} already assumed/added")
                self._touch(pod.node_name).add_pod(pod)
                self._pod_states[pod.uid] = _PodState(pod)
                self._assumed.add(pod.uid)

    def finish_binding(self, pod: Pod, now: Optional[float] = None) -> None:
        """Reference: cache.go:295 — start the expiry TTL."""
        with self._lock:
            state = self._pod_states.get(pod.uid)
            if state is None or pod.uid not in self._assumed:
                return
            state.binding_finished = True
            state.deadline = (now if now is not None else self.clock.now()) + self.ttl

    def finish_bindings(self, pods: list, now: Optional[float] = None) -> None:
        """Batched finish_binding: one lock, one clock read for the wave."""
        now = now if now is not None else self.clock.now()
        deadline = now + self.ttl
        with self._lock:
            for pod in pods:
                state = self._pod_states.get(pod.uid)
                if state is None or pod.uid not in self._assumed:
                    continue
                state.binding_finished = True
                state.deadline = deadline

    def forget_pod(self, pod: Pod) -> None:
        """Reference: cache.go:319 — undo a failed assume."""
        with self._lock:
            state = self._pod_states.get(pod.uid)
            if state is None or pod.uid not in self._assumed:
                raise CacheError(f"pod {pod.key} wasn't assumed so cannot be forgotten")
            self._remove_pod_from_node(state.pod)
            del self._pod_states[pod.uid]
            self._assumed.discard(pod.uid)

    def add_pod(self, pod: Pod) -> None:
        """Informer ADDED for an assigned pod (reference: cache.go:385).
        Confirms an assumed pod, or inserts one the cache didn't assume."""
        with self._lock:
            state = self._pod_states.get(pod.uid)
            if state is not None and pod.uid in self._assumed:
                if state.pod.node_name != pod.node_name:
                    # binding went elsewhere than assumed: fix up
                    self._remove_pod_from_node(state.pod)
                    self._touch(pod.node_name).add_pod(pod)
                self._assumed.discard(pod.uid)
                state.deadline = None
                state.pod = pod
            elif state is None:
                self._touch(pod.node_name).add_pod(pod)
                self._pod_states[pod.uid] = _PodState(pod)
            # duplicate ADDED for confirmed pod: no-op

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            state = self._pod_states.get(old.uid)
            if state is not None and old.uid not in self._assumed:
                self._remove_pod_from_node(state.pod)
                self._touch(new.node_name).add_pod(new)
                state.pod = new

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            state = self._pod_states.get(pod.uid)
            if state is None:
                return
            self._remove_pod_from_node(state.pod)
            del self._pod_states[pod.uid]
            self._assumed.discard(pod.uid)

    def _remove_pod_from_node(self, pod: Pod) -> None:
        item = self._nodes.get(pod.node_name)
        if item is not None:
            item.info.remove_pod(pod)
            # drop an empty placeholder once its last pod is gone
            # (reference: cache.go removePod -> removeNodeInfoFromList)
            if item.info.node is None and not item.info.pods:
                self._remove_from_list(item)
                del self._nodes[pod.node_name]
            else:
                self._move_to_head(item)

    def is_assumed_pod(self, pod: Pod) -> bool:
        with self._lock:
            return pod.uid in self._assumed

    def assumed_pods(self) -> list[Pod]:
        """Snapshot of every currently assumed pod (crash-restart
        recovery reconciles these against the store: landed bindings are
        finished/adopted, the rest forgotten and re-queued)."""
        with self._lock:
            return [self._pod_states[uid].pod for uid in self._assumed
                    if uid in self._pod_states]

    def get_pod(self, pod: Pod) -> Optional[Pod]:
        with self._lock:
            state = self._pod_states.get(pod.uid)
            return state.pod if state else None

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_states)

    # -- nodes --------------------------------------------------------------
    def add_node(self, node: Node) -> None:
        with self._lock:
            item = self._nodes.get(node.name)
            if item is None:
                item = _ListItem(NodeInfo())
                self._nodes[node.name] = item
            else:
                # re-add: refresh tree zone membership
                if item.info.node is not None:
                    self.node_tree.remove_node(item.info.node)
            item.info.set_node(node)
            self._move_to_head(item)
            self.node_tree.add_node(node)

    def update_node(self, old: Node, new: Node) -> None:
        with self._lock:
            item = self._nodes.get(new.name)
            if item is None:
                self.add_node(new)
                return
            if item.info.node is not None:
                self.node_tree.update_node(item.info.node, new)
            else:
                self.node_tree.add_node(new)
            item.info.set_node(new)
            self._move_to_head(item)

    def remove_node(self, node: Node) -> None:
        with self._lock:
            item = self._nodes.get(node.name)
            if item is None:
                return
            item.info.remove_node()
            # keep placeholder if pods still reference the node (reference :520)
            if not item.info.pods:
                self._remove_from_list(item)
                del self._nodes[node.name]
            else:
                self._move_to_head(item)
            self.node_tree.remove_node(node)

    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def get_node(self, name: str) -> Optional[Node]:
        """The cached Node object (None when absent or a placeholder) —
        the dead-node invalidation path needs the real object so the
        NodeTree removal lands in the right zone."""
        with self._lock:
            item = self._nodes.get(name)
            return item.info.node if item is not None else None

    def node_generation(self, name: str) -> Optional[int]:
        """Current generation of one node's NodeInfo (None when absent);
        lets the TPU mirror sync after self-inflicted mutations."""
        with self._lock:
            item = self._nodes.get(name)
            return item.info.generation if item is not None else None

    def node_generations(self, names: list) -> list:
        """Batched node_generation (one lock for a committed burst wave)."""
        with self._lock:
            return [item.info.generation if item is not None else None
                    for item in map(self._nodes.get, names)]

    # -- snapshot -----------------------------------------------------------
    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """Incremental clone of changed nodes (reference: cache.go:210).
        Walks the recency list head→tail, stopping at the first item whose
        generation is not newer than the snapshot's."""
        with self._lock:
            balanced_gen = self._head.info.generation if self._head else snapshot.generation
            item = self._head
            while item is not None and item.info.generation > snapshot.generation:
                info = item.info
                if info.node is not None:
                    snapshot.node_infos[info.node.name] = info.clone()
                item = item.next
            # drop nodes deleted from the cache; placeholders (node=None)
            # don't count as live, so compare against the node tree
            # (reference: cache.go:210 compares against nodeTree.numNodes)
            if len(snapshot.node_infos) > self.node_tree.num_nodes:
                live = {n for n, it in self._nodes.items() if it.info.node is not None}
                for name in list(snapshot.node_infos):
                    if name not in live:
                        del snapshot.node_infos[name]
            snapshot.generation = balanced_gen
            return snapshot

    # -- expiry -------------------------------------------------------------
    def cleanup_assumed_pods(self, now: Optional[float] = None) -> list[Pod]:
        """Reference: cache.go:632 — expire assumed pods past their deadline."""
        now = now if now is not None else self.clock.now()
        expired = []
        with self._lock:
            for uid in list(self._assumed):
                state = self._pod_states[uid]
                if state.binding_finished and state.deadline is not None \
                        and now >= state.deadline:
                    expired.append(state.pod)
                    self._remove_pod_from_node(state.pod)
                    del self._pod_states[uid]
                    self._assumed.discard(uid)
        return expired

    # -- debugging (reference: internal/cache/debugger) ----------------------
    def dump(self) -> dict:
        with self._lock:
            return {
                "nodes": {
                    name: {
                        "pods": [p.key for p in item.info.pods],
                        "requested_milli_cpu": item.info.requested.milli_cpu,
                        "requested_memory": item.info.requested.memory,
                        "generation": item.info.generation,
                    }
                    for name, item in self._nodes.items()
                },
                "assumed_pods": sorted(
                    self._pod_states[uid].pod.key for uid in self._assumed),
            }
