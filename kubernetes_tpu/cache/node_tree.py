"""Zone-aware node enumeration (reference: internal/cache/node_tree.go:31).

Nodes are grouped by zone; `next()` round-robins across zones so the
scheduler's node walk interleaves failure domains (node_tree.go:165). A full
enumeration of num_nodes names exhausts every zone and resets, so each
scheduling cycle sees the same interleaved order — that order is the node
axis of the device matrix.
"""
from __future__ import annotations

from kubernetes_tpu.api.types import Node, get_zone_key


class NodeTree:
    def __init__(self):
        self._tree: dict[str, list[str]] = {}   # zone -> node names
        self._zones: list[str] = []             # insertion-ordered zone keys
        self._zone_index = 0
        self._last_index: dict[str, int] = {}   # per-zone cursor
        self._exhausted: set[str] = set()
        self.num_nodes = 0

    def add_node(self, node: Node) -> None:
        zone = get_zone_key(node)
        names = self._tree.get(zone)
        if names is None:
            names = []
            self._tree[zone] = names
            self._zones.append(zone)
            self._last_index[zone] = 0
        if node.name in names:
            return
        names.append(node.name)
        self.num_nodes += 1

    def remove_node(self, node: Node) -> None:
        zone = get_zone_key(node)
        names = self._tree.get(zone)
        if names is None or node.name not in names:
            return
        names.remove(node.name)
        self.num_nodes -= 1
        if not names:
            del self._tree[zone]
            self._zones.remove(zone)
            del self._last_index[zone]
            self._exhausted.discard(zone)
        self._zone_index = 0

    def update_node(self, old: Node, new: Node) -> None:
        if get_zone_key(old) == get_zone_key(new):
            return
        self.remove_node(old)
        self.add_node(new)

    def _reset_exhausted(self) -> None:
        for zone in self._exhausted:
            self._last_index[zone] = 0
        self._exhausted.clear()

    def next(self) -> str:
        """Next node name in zone-interleaved round-robin order."""
        if not self._zones:
            return ""
        while True:
            if len(self._exhausted) == len(self._zones):
                self._reset_exhausted()
            zone = self._zones[self._zone_index]
            self._zone_index = (self._zone_index + 1) % len(self._zones)
            if zone in self._exhausted:
                continue
            idx = self._last_index[zone]
            names = self._tree[zone]
            if idx >= len(names) - 1:
                self._exhausted.add(zone)
            if idx < len(names):
                self._last_index[zone] = idx + 1
                return names[idx]

    def list_names(self) -> list[str]:
        """One full interleaved enumeration — the per-cycle node order."""
        return [self.next() for _ in range(self.num_nodes)]
