"""Zone-aware node enumeration (reference: internal/cache/node_tree.go:31).

Nodes are grouped by zone; `next()` round-robins across zones so the
scheduler's node walk interleaves failure domains (node_tree.go:165). A full
enumeration of num_nodes names exhausts every zone and resets, so each
scheduling cycle sees the same interleaved order — that order is the node
axis of the device matrix.
"""
from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.types import Node, get_zone_key


class NodeTree:
    def __init__(self):
        self._tree: dict[str, list[str]] = {}   # zone -> node names
        self._zones: list[str] = []             # insertion-ordered zone keys
        self._zone_index = 0
        self._last_index: dict[str, int] = {}   # per-zone cursor
        self._exhausted: set[str] = set()
        self.num_nodes = 0
        self._rotation_cache: Optional[list[int]] = None  # keyed by membership
        # start-zone-index -> full enumeration order (membership-keyed,
        # like the rotation map): a serving loop consumes one enumeration
        # per window against a stable tree, and there are at most
        # len(zones) distinct orders — list_names serves boundary-state
        # enumerations from here instead of walking next() N times
        self._order_cache: dict[int, list[str]] = {}
        # start index of the most recent boundary-state list_names() (None
        # when the last enumeration was mid-state or membership moved):
        # lets the burst driver prove "this enumeration IS
        # order_for_start(r)" in O(1) and keep its device axis stable
        # across rotated windows (cycle 0 rides the rotation program
        # instead of forcing a mirror permute + full re-upload per window)
        self.last_enum_start: Optional[int] = None
        # membership epoch: bumps on add/remove — burst records pin it so a
        # replayed burst can prove the tree it captured is the tree it ran
        self.epoch = 0

    def add_node(self, node: Node) -> None:
        zone = get_zone_key(node)
        names = self._tree.get(zone)
        if names is None:
            names = []
            self._tree[zone] = names
            self._zones.append(zone)
            self._last_index[zone] = 0
        if node.name in names:
            return
        names.append(node.name)
        self.num_nodes += 1
        self._rotation_cache = None
        self._order_cache = {}
        self.last_enum_start = None
        self.epoch += 1

    def remove_node(self, node: Node) -> None:
        zone = get_zone_key(node)
        names = self._tree.get(zone)
        if names is None or node.name not in names:
            return
        names.remove(node.name)
        self.num_nodes -= 1
        self._rotation_cache = None
        self._order_cache = {}
        self.last_enum_start = None
        self.epoch += 1
        if not names:
            del self._tree[zone]
            self._zones.remove(zone)
            del self._last_index[zone]
            self._exhausted.discard(zone)
        self._zone_index = 0

    def update_node(self, old: Node, new: Node) -> None:
        if get_zone_key(old) == get_zone_key(new):
            return
        self.remove_node(old)
        self.add_node(new)

    def _reset_exhausted(self) -> None:
        for zone in self._exhausted:
            self._last_index[zone] = 0
        self._exhausted.clear()

    def next(self) -> str:
        """Next node name in zone-interleaved round-robin order."""
        if not self._zones:
            return ""
        while True:
            if len(self._exhausted) == len(self._zones):
                self._reset_exhausted()
            zone = self._zones[self._zone_index]
            self._zone_index = (self._zone_index + 1) % len(self._zones)
            if zone in self._exhausted:
                continue
            idx = self._last_index[zone]
            names = self._tree[zone]
            if idx >= len(names) - 1:
                self._exhausted.add(zone)
            if idx < len(names):
                self._last_index[zone] = idx + 1
                return names[idx]

    def list_names(self) -> list[str]:
        """One full interleaved enumeration — the per-cycle node order.

        At an enumeration BOUNDARY (pristine cursors, or the
        post-enumeration state every full enumeration leaves — the
        scheduling loop's steady state), the order is a pure function of
        the starting zone index, so it is served from the membership-keyed
        order cache and the cursor state advances to exactly what N
        next() calls would leave (cursors at their ends, every zone
        exhausted, zone index at rotation_map()[start]). Mid-enumeration
        states (a consumer that mixed in bare next() calls) keep the
        step-by-step walk."""
        if not self._zones:
            return []
        at_boundary = (len(self._exhausted) == len(self._zones)
                       or (not self._exhausted
                           and not any(self._last_index.values())))
        if not at_boundary:
            self.last_enum_start = None   # mid-state order: not a pure
            return [self.next() for _ in range(self.num_nodes)]
        start = self._zone_index
        order = self._order_cache.get(start)
        if order is None:
            order = self._order_cache[start] = self._simulate(start)[0]
        self._last_index = {z: len(self._tree[z]) for z in self._zones}
        self._exhausted = set(self._zones)
        self._zone_index = self.rotation_map()[start]
        self.last_enum_start = start
        return list(order)

    def all_names(self) -> list[str]:
        """Every member name WITHOUT advancing the enumeration cursor
        (the node-death reconciliation sweep's view)."""
        return [n for ns in self._tree.values() for n in ns]

    # -- rotation structure (device-burst support) ---------------------------
    # A full enumeration's order is determined entirely by the zone index it
    # starts from (cursors reset lazily at the first next() of each
    # enumeration), so there are at most len(zones) distinct per-cycle
    # orders. Burst kernels replay the per-cycle rotation from these.

    def _simulate(self, start: int) -> tuple[list[str], int]:
        """Order + end zone-index of one full enumeration starting at zone
        index `start` with fresh cursors (exact mirror of next())."""
        if not self._zones:
            return [], 0
        z = len(self._zones)
        cursor = {zone: 0 for zone in self._zones}
        exhausted: set[str] = set()
        zi = start
        names: list[str] = []
        while len(names) < self.num_nodes:
            zone = self._zones[zi]
            zi = (zi + 1) % z
            if zone in exhausted:
                continue
            idx = cursor[zone]
            nodes = self._tree[zone]
            if idx >= len(nodes) - 1:
                exhausted.add(zone)
            if idx < len(nodes):
                cursor[zone] = idx + 1
                names.append(nodes[idx])
        return names, zi

    def rotation_map(self) -> list[int]:
        """next_start[r]: the zone index the enumeration AFTER one starting
        at r begins from. next_start[r] == r for all r iff the per-cycle
        order is stable (e.g. equal-size zones). Cached until membership
        changes — burst segments consult this on every launch."""
        if self._rotation_cache is None:
            self._rotation_cache = [
                self._simulate(r)[1] for r in range(max(len(self._zones), 1))]
        return self._rotation_cache

    def order_for_start(self, start: int) -> list[str]:
        return self._simulate(start)[0]

    @property
    def zone_index(self) -> int:
        return self._zone_index

    # -- gang checkpoint/rewind ----------------------------------------------
    def checkpoint(self) -> tuple:
        """Snapshot the enumeration cursor (zone index + per-zone cursors +
        exhausted set). A discarded gang trial restores it so the rotation
        walk replays EXACTLY as if the gang was never attempted — the next
        cycle (gang retry or the singleton behind it) sees the same
        interleaved order either way. Exact across a window with no
        membership changes (the single-threaded scheduling loop's case);
        restore() additionally survives nodes/zones added or REMOVED in
        between (mid-burst node death) by re-grounding the cursor state
        in the current membership."""
        return (self._zone_index, dict(self._last_index),
                set(self._exhausted), self.epoch)

    def restore(self, chk: tuple) -> None:
        zone_index, cursors, exhausted, epoch = chk
        if epoch == self.epoch:
            # membership unchanged: exact cursor replay (the gang/crash
            # rewind contract)
            self._zone_index = zone_index
            self._last_index = dict(cursors)
            self._exhausted = set(exhausted)
            return
        # nodes/zones were added or removed under the checkpoint (mid-burst
        # node death): the recorded cursors describe lists that no longer
        # exist, so exact replay is impossible — re-ground to the
        # post-enumeration state (every zone exhausted, cursors at their
        # ends) so the NEXT enumeration resets and walks the live
        # membership exactly once. The zone index (the rotation cursor) is
        # kept when still valid; a removal already reset it to 0 in both
        # worlds (remove_node), so post-churn rotation stays aligned with
        # a serial oracle that observed the same removal.
        self._last_index = {z: len(self._tree[z]) for z in self._zones}
        self._exhausted = set(self._zones)
        z = max(len(self._zones), 1)
        self._zone_index = zone_index if zone_index < z else 0

    def advance_enumerations(self, count: int) -> None:
        """Fast-forward the tree as if `count` more full enumerations ran.
        Valid only in the post-enumeration state (i.e. after at least one
        full list_names()), where cursors/exhausted are already at their
        end-of-enumeration values and only the zone index walks."""
        if not self._zones or count <= 0:
            return
        nxt = self.rotation_map()
        r = self._zone_index
        seen: dict[int, int] = {}
        walk: list[int] = []
        # the walk over <= z states enters a cycle; close the form
        while count > 0 and r not in seen:
            seen[r] = len(walk)
            walk.append(r)
            r = nxt[r]
            count -= 1
        if count > 0:
            cycle = walk[seen[r]:]
            r = cycle[count % len(cycle)] if cycle else r
        self._zone_index = r
