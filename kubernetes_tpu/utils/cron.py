"""Minimal 5-field cron schedule parser for the CronJob controller.

Supports the syntax the reference's vendored robfig/cron exposes for
CronJob schedules: numbers, `*`, lists (`a,b`), ranges (`a-b`), and steps
(`*/n`, `a-b/n`) across minute / hour / day-of-month / month / day-of-week
(0-6, Sunday=0; 7 also accepted as Sunday). Day-of-month and day-of-week
are OR'd when both are restricted, per cron convention.

Timezone: schedules are evaluated in **UTC** (`time.gmtime`), NOT the
process's local timezone. This is a deliberate divergence from the
reference's kube-controller-manager, which evaluates CronJob schedules in
its own local time (cronjob_controller.go — a documented footgun that
makes firing times depend on where the controller-manager pod runs).
Pinning UTC keeps `0 12 * * *` meaning 12:00 UTC on every host;
`TestCronSchedule.test_schedule_is_utc_not_localtime` enforces it.
"""
from __future__ import annotations

import time
from typing import Optional

_BOUNDS = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


class CronParseError(ValueError):
    pass


def _parse_field(spec: str, lo: int, hi: int, dow: bool = False) -> frozenset:
    out: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronParseError(f"bad step {step_s!r}")
            if step <= 0:
                raise CronParseError(f"bad step {step}")
        if part == "*" or part == "":
            a, b = lo, hi
        elif "-" in part:
            a_s, b_s = part.split("-", 1)
            try:
                a, b = int(a_s), int(b_s)
            except ValueError:
                raise CronParseError(f"bad range {part!r}")
        else:
            try:
                a = b = int(part)
            except ValueError:
                raise CronParseError(f"bad value {part!r}")
        top = 7 if dow else hi   # dow accepts 7 (= Sunday) anywhere
        if not (lo <= a <= top and lo <= b <= top and a <= b):
            raise CronParseError(f"value out of range: {part!r}")
        vals = range(a, b + 1, step)
        # normalize AFTER expanding so ranges through 7 work ('1-7', '5-7',
        # '0-7' all mean what vixie/robfig cron mean)
        out.update(v % 7 for v in vals) if dow else out.update(vals)
    return frozenset(out)


class CronSchedule:
    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) != 5:
            raise CronParseError(
                f"expected 5 fields, got {len(fields)}: {expr!r}")
        self.expr = expr
        self.minute = _parse_field(fields[0], *_BOUNDS[0])
        self.hour = _parse_field(fields[1], *_BOUNDS[1])
        self.dom = _parse_field(fields[2], *_BOUNDS[2])
        self.month = _parse_field(fields[3], *_BOUNDS[3])
        self.dow = _parse_field(fields[4], *_BOUNDS[4], dow=True)
        # robfig/vixie treat '*' AND '*/n' as star for the dom/dow OR rule
        self._dom_star = fields[2].split("/", 1)[0] == "*"
        self._dow_star = fields[4].split("/", 1)[0] == "*"

    def matches(self, ts: float) -> bool:
        """True when the UTC wall-clock minute containing `ts` matches
        (schedules are UTC by contract — see the module docstring)."""
        t = time.gmtime(ts)
        if t.tm_min not in self.minute or t.tm_hour not in self.hour \
                or t.tm_mon not in self.month:
            return False
        dom_ok = t.tm_mday in self.dom
        dow_ok = (t.tm_wday + 1) % 7 in self.dow   # tm_wday: Monday=0
        if self._dom_star or self._dow_star:
            return dom_ok and dow_ok
        return dom_ok or dow_ok   # both restricted: cron ORs them

    def next_after(self, ts: float, limit_days: int = 366) -> Optional[float]:
        """First matching minute strictly after `ts` (UTC), or None within
        the search horizon."""
        # round up to the next whole minute
        t = int(ts // 60 + 1) * 60
        end = t + limit_days * 86400
        while t < end:
            if self.matches(t):
                return float(t)
            t += 60
        return None
