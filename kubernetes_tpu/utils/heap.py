"""Keyed binary heap with in-place update/delete by key.

Mirrors the semantics of the reference's scheduler heap
(pkg/scheduler/util/heap.go:127): items are keyed objects ordered by an
arbitrary less-function; Add/Update re-sift in place, Delete removes by key.

`NumericKeyedHeap` is the hot-path variant for orderings expressible as a
numeric (a, b, c) triple — both scheduler queues are (scheduling_queue.go
podsCompare and the backoff expiry) — backed by the C++ core in
kubernetes_tpu/native/heapcore.cpp when it builds, with this module's
Python heap as the behavioral twin otherwise.
"""
from __future__ import annotations

from typing import Any, Callable, Optional


class KeyedHeap:
    def __init__(self, key_fn: Callable[[Any], str], less_fn: Callable[[Any, Any], bool]):
        self._key_fn = key_fn
        self._less = less_fn
        self._items: list[Any] = []
        self._index: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get(self, key: str) -> Optional[Any]:
        i = self._index.get(key)
        return self._items[i] if i is not None else None

    def list(self) -> list[Any]:
        return list(self._items)

    def add(self, item: Any) -> None:
        """Insert or replace by key, restoring heap order."""
        key = self._key_fn(item)
        i = self._index.get(key)
        if i is not None:
            self._items[i] = item
            self._sift_down(self._sift_up(i))
        else:
            self._items.append(item)
            self._index[key] = len(self._items) - 1
            self._sift_up(len(self._items) - 1)

    update = add

    def add_if_not_present(self, item: Any) -> None:
        if self._key_fn(item) not in self._index:
            self.add(item)

    def delete(self, key: str) -> Optional[Any]:
        i = self._index.get(key)
        if i is None:
            return None
        item = self._items[i]
        last = len(self._items) - 1
        self._swap(i, last)
        self._items.pop()
        del self._index[key]
        if i < last:
            self._sift_down(self._sift_up(i))
        return item

    def peek(self) -> Optional[Any]:
        return self._items[0] if self._items else None

    def pop(self) -> Optional[Any]:
        if not self._items:
            return None
        return self.delete(self._key_fn(self._items[0]))

    def pop_many(self, limit: int) -> list[Any]:
        """Up to `limit` ascending pops as one call (the native core's
        batched-drain twin)."""
        out = []
        while len(out) < limit and self._items:
            out.append(self.pop())
        return out

    def add_many(self, items: list) -> None:
        """Batched insert (the native core's push_many twin): per-item
        add() semantics, one call."""
        for item in items:
            self.add(item)

    # -- internals ----------------------------------------------------------
    def _swap(self, i: int, j: int) -> None:
        items = self._items
        items[i], items[j] = items[j], items[i]
        self._index[self._key_fn(items[i])] = i
        self._index[self._key_fn(items[j])] = j

    def _sift_up(self, i: int) -> int:
        while i > 0:
            parent = (i - 1) // 2
            if self._less(self._items[i], self._items[parent]):
                self._swap(i, parent)
                i = parent
            else:
                break
        return i

    def _sift_down(self, i: int) -> int:
        n = len(self._items)
        while True:
            smallest = i
            for c in (2 * i + 1, 2 * i + 2):
                if c < n and self._less(self._items[c], self._items[smallest]):
                    smallest = c
            if smallest == i:
                return i
            self._swap(i, smallest)
            i = smallest


class _PyHeapCore:
    """Pure-Python stand-in exposing heapcore's exact call surface — the
    DEMOTION TARGET when the native heap faults mid-run (the chaos plane's
    native.heapcore seam). Entries are (key, triple, item); ordering is
    the same ascending numeric triple, so a heap migrated item-by-item
    pops in the identical order (queue triples embed a unique sequence
    number — no ties to reorder)."""

    def __init__(self):
        self._h = KeyedHeap(lambda e: e[0], lambda x, y: x[1] < y[1])

    def add(self, key, a, b, c, item) -> None:
        self._h.add((key, (a, b, c), item))

    def get(self, key):
        e = self._h.get(key)
        return e[2] if e is not None else None

    def delete(self, key):
        e = self._h.delete(key)
        return e[2] if e is not None else None

    def peek(self):
        e = self._h.peek()
        return e[2] if e is not None else None

    def pop(self):
        e = self._h.pop()
        return e[2] if e is not None else None

    def pop_many(self, limit: int) -> list:
        return [e[2] for e in self._h.pop_many(limit)]

    def push_many(self, entries: list) -> None:
        """Batched add (native push_many twin): entries are
        (key, a, b, c, payload) tuples, inserted in order."""
        for key, a, b, c, item in entries:
            self.add(key, a, b, c, item)

    def list(self) -> list:
        return [e[2] for e in self._h.list()]

    def __len__(self) -> int:
        return len(self._h)

    def __contains__(self, key: str) -> bool:
        return key in self._h


class NumericKeyedHeap:
    """KeyedHeap specialization: ordering = ascending numeric triple.
    Uses the native core when available; falls back to KeyedHeap. A
    native core that faults mid-run (chaos seam native.heapcore, or a
    real extension fault) DEMOTES: the items migrate into _PyHeapCore and
    every later call rides the twin — no queued pod is ever lost."""

    def __new__(cls, key_fn: Callable[[Any], str],
                triple_fn: Callable[[Any], tuple]):
        from kubernetes_tpu import native
        core_mod = native.load("heapcore")
        if core_mod is None:
            return KeyedHeap(key_fn,
                             lambda x, y: triple_fn(x) < triple_fn(y))
        self = super().__new__(cls)
        self._key_fn = key_fn
        self._triple = triple_fn
        self._core = core_mod.HeapCore()
        self._native = True
        return self

    # -- demotion ------------------------------------------------------------
    def _guard(self) -> None:
        """Entry-point hook: when the chaos plane fires the heapcore seam
        against a live native core, demote BEFORE the call (injection
        precedes the fault, so the core's state is intact to migrate) —
        the operation that triggered it completes on the twin."""
        if self._native:
            from kubernetes_tpu import chaos
            if chaos.take("native.heapcore"):
                self._demote()

    def _demote(self) -> None:
        items = self._core.list()
        twin = _PyHeapCore()
        for item in items:
            a, b, c = self._triple(item)
            twin.add(self._key_fn(item), float(a), float(b), float(c), item)
        self._core = twin
        self._native = False
        from kubernetes_tpu import chaos
        chaos.DEMOTIONS.labels("heapcore").inc()

    def __len__(self) -> int:
        return len(self._core)

    def __contains__(self, key: str) -> bool:
        return key in self._core

    def get(self, key: str) -> Optional[Any]:
        return self._core.get(key)

    def list(self) -> list[Any]:
        return self._core.list()

    def add(self, item: Any) -> None:
        self._guard()
        a, b, c = self._triple(item)
        self._core.add(self._key_fn(item), float(a), float(b), float(c), item)

    update = add

    def add_many(self, items: list) -> None:
        """Batched insert: ONE native push_many call for the whole batch
        (the sifts run with the GIL released), per-item add() semantics.
        A stale pre-push_many .so degrades to per-item adds."""
        self._guard()
        key_fn, triple = self._key_fn, self._triple
        entries = []
        for item in items:
            a, b, c = triple(item)
            entries.append((key_fn(item), float(a), float(b), float(c),
                            item))
        pm = getattr(self._core, "push_many", None)
        if pm is not None:
            pm(entries)
            return
        for key, a, b, c, item in entries:
            self._core.add(key, a, b, c, item)

    def add_if_not_present(self, item: Any) -> None:
        if self._key_fn(item) not in self._core:
            self.add(item)

    def delete(self, key: str) -> Optional[Any]:
        self._guard()
        return self._core.delete(key)

    def peek(self) -> Optional[Any]:
        return self._core.peek()

    def pop(self) -> Optional[Any]:
        self._guard()
        return self._core.pop()

    def pop_many(self, limit: int) -> list[Any]:
        """Batched drain: ONE native call pops up to `limit` items with
        the GIL released during the sifts (the activeQ burst prologue). A
        stale pre-pop_many .so degrades to per-item pops."""
        self._guard()
        pm = getattr(self._core, "pop_many", None)
        if pm is not None:
            return pm(limit)
        out = []
        while len(out) < limit:
            item = self._core.pop()
            if item is None:
                return out
            out.append(item)
        return out
