"""Keyed binary heap with in-place update/delete by key.

Mirrors the semantics of the reference's scheduler heap
(pkg/scheduler/util/heap.go:127): items are keyed objects ordered by an
arbitrary less-function; Add/Update re-sift in place, Delete removes by key.
"""
from __future__ import annotations

from typing import Any, Callable, Optional


class KeyedHeap:
    def __init__(self, key_fn: Callable[[Any], str], less_fn: Callable[[Any, Any], bool]):
        self._key_fn = key_fn
        self._less = less_fn
        self._items: list[Any] = []
        self._index: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get(self, key: str) -> Optional[Any]:
        i = self._index.get(key)
        return self._items[i] if i is not None else None

    def list(self) -> list[Any]:
        return list(self._items)

    def add(self, item: Any) -> None:
        """Insert or replace by key, restoring heap order."""
        key = self._key_fn(item)
        i = self._index.get(key)
        if i is not None:
            self._items[i] = item
            self._sift_down(self._sift_up(i))
        else:
            self._items.append(item)
            self._index[key] = len(self._items) - 1
            self._sift_up(len(self._items) - 1)

    update = add

    def add_if_not_present(self, item: Any) -> None:
        if self._key_fn(item) not in self._index:
            self.add(item)

    def delete(self, key: str) -> Optional[Any]:
        i = self._index.get(key)
        if i is None:
            return None
        item = self._items[i]
        last = len(self._items) - 1
        self._swap(i, last)
        self._items.pop()
        del self._index[key]
        if i < last:
            self._sift_down(self._sift_up(i))
        return item

    def peek(self) -> Optional[Any]:
        return self._items[0] if self._items else None

    def pop(self) -> Optional[Any]:
        if not self._items:
            return None
        return self.delete(self._key_fn(self._items[0]))

    # -- internals ----------------------------------------------------------
    def _swap(self, i: int, j: int) -> None:
        items = self._items
        items[i], items[j] = items[j], items[i]
        self._index[self._key_fn(items[i])] = i
        self._index[self._key_fn(items[j])] = j

    def _sift_up(self, i: int) -> int:
        while i > 0:
            parent = (i - 1) // 2
            if self._less(self._items[i], self._items[parent]):
                self._swap(i, parent)
                i = parent
            else:
                break
        return i

    def _sift_down(self, i: int) -> int:
        n = len(self._items)
        while True:
            smallest = i
            for c in (2 * i + 1, 2 * i + 2):
                if c < n and self._less(self._items[c], self._items[smallest]):
                    smallest = c
            if smallest == i:
                return i
            self._swap(i, smallest)
            i = smallest
