"""Step tracing — the utiltrace analog.

Mirrors k8s.io/utils/trace as used in the hot path
(core/generic_scheduler.go:185-246): named steps with timestamps, logged
only when the whole operation exceeds a threshold (the scheduler uses
100ms per cycle).
"""
from __future__ import annotations

import logging
import time

log = logging.getLogger("kubernetes_tpu")

SLOW_CYCLE_THRESHOLD = 0.1  # 100ms (generic_scheduler.go:186)


class Profiler:
    """Device-level profiling — the pprof-endpoint analog.

    The reference wires pprof HTTP handlers behind EnableProfiling
    (cmd/kube-scheduler/app/server.go:301-305, DebuggingConfiguration in
    apis/config/types.go:70); the TPU equivalent is a jax.profiler trace
    session writing TensorBoard/XPlane dumps (kernel timelines, HLO cost
    breakdowns, host<->device transfers) to a directory. Use either as a
    session (`start()`/`stop()`, the CLI flag path) or as a context manager
    around a region (`with Profiler(dir).span("burst"): ...`)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._active = False

    def start(self) -> None:
        import jax
        if not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def stop(self) -> None:
        import jax
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            log.warning("profiler trace written to %s", self.log_dir)

    def span(self, name: str):
        """Annotated sub-region (shows as a named range in the trace)."""
        import jax
        return jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class Trace:
    def __init__(self, name: str, threshold: float = SLOW_CYCLE_THRESHOLD):
        self.name = name
        self.threshold = threshold
        self.start = time.perf_counter()
        self.steps: list[tuple[str, float]] = []

    def step(self, msg: str) -> None:
        self.steps.append((msg, time.perf_counter()))

    def elapsed(self) -> float:
        return time.perf_counter() - self.start

    def log_if_long(self) -> bool:
        """Emit the step timeline when the operation was slow. Returns
        whether it logged."""
        total = self.elapsed()
        if total < self.threshold:
            return False
        lines = [f"Trace {self.name!r} (total {total * 1000:.1f}ms):"]
        prev = self.start
        for msg, t in self.steps:
            lines.append(f"  +{(t - prev) * 1000:.1f}ms {msg}")
            prev = t
        log.warning("\n".join(lines))
        return True

    def emit_spans(self, cat: str = "trace") -> None:
        """Fold the step timeline into the obs span ring: one parent span
        for the whole operation plus one child per step slice, so a slow
        cycle's breakdown shows up in /debug/traces and bench --trace
        output, not only in the log."""
        from kubernetes_tpu.obs import trace as obs_trace
        end = time.perf_counter()
        obs_trace.add_span(self.name, self.start, end, cat=cat)
        prev = self.start
        for msg, t in self.steps:
            obs_trace.add_span(f"{self.name}: {msg}", prev, t, cat=cat,
                               args={"parent": self.name})
            prev = t
