"""Feature-gate registry — pkg/features/kube_features.go analog.

The reference consults a process-global gate set
(`utilfeature.DefaultFeatureGate.Enabled`, e.g. gating snapshot behavior at
cache.go:213 and balanced-allocation volume variance at
balanced_resource_allocation.go:44); this mirrors that shape: a default
table, `enabled()` lookups from anywhere, and config-time overrides
(`--feature-gates` -> SchedulerConfiguration.feature_gates -> set_gates).
"""
from __future__ import annotations

# name -> default (the subset of the reference's 66 gates this framework
# consults, with the reference's v1.15 defaults)
DEFAULT_FEATURE_GATES: dict[str, bool] = {
    # scheduler scoring runs on the TPU kernel path (the north star's gate)
    "TPUScoring": False,
    # balanced-allocation scores volume-count variance alongside cpu/mem
    # (balanced_resource_allocation.go:44; default false / alpha)
    "BalanceAttachedNodeVolumes": False,
    # node conditions surface as taints; the default provider's predicate
    # set assumes this (defaults.go:60 ApplyFeatureGates; default true)
    "TaintNodesByCondition": True,
    # kubelet-reported attach limits in node.allocatable
    # ("attachable-volumes-*"; default true in v1.15)
    "AttachVolumeLimit": True,
}

_gates: dict[str, bool] = dict(DEFAULT_FEATURE_GATES)


def enabled(name: str) -> bool:
    return _gates.get(name, False)


def set_gates(overrides: dict[str, bool]) -> None:
    """Apply config-time overrides (unknown names are kept — callers may
    consult gates this table doesn't pre-declare)."""
    _gates.update({k: bool(v) for k, v in overrides.items()})


def reset() -> None:
    """Restore defaults (test isolation)."""
    _gates.clear()
    _gates.update(DEFAULT_FEATURE_GATES)
