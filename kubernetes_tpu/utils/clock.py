"""Injectable clock — deterministic time for cache TTLs and queue backoff.

Mirrors the role of k8s.io/utils/clock in the reference (cache expiry and
backoff tests inject time; see cache.go:300 finishBinding(pod, now)).
"""
from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Settable clock; sleep() advances it (no blocking)."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds

    def set(self, t: float) -> None:
        with self._lock:
            self._now = t
