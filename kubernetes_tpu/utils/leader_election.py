"""Leader election over the versioned store — active/passive HA.

Mirrors client-go/tools/leaderelection (leaderelection.go:183) with a lease
resourcelock (resourcelock/leaselock.go): candidates CAS a lease record
through the store's optimistic concurrency; the holder renews before
renew_deadline, others acquire after lease_duration of silence. The
reference wires this at cmd/kube-scheduler/app/server.go:248-263.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.types import Lease
from kubernetes_tpu.store.store import (
    Store, LEASES, NotFoundError, ConflictError, AlreadyExistsError,
)
from kubernetes_tpu.utils.clock import Clock, RealClock

__all__ = ["Lease", "LeaderElectionConfig", "LeaderElector"]


@dataclass
class LeaderElectionConfig:
    lock_name: str = "kube-scheduler"
    identity: str = "candidate"
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    on_started_leading: Optional[Callable[[], None]] = None
    on_stopped_leading: Optional[Callable[[], None]] = None


class LeaderElector:
    def __init__(self, store: Store, config: LeaderElectionConfig,
                 clock: Optional[Clock] = None):
        # the fencing invariant (leaderelection.go:128 validation): the
        # holder must abdicate at renew_deadline, strictly BEFORE the
        # lease_duration window in which another candidate may acquire —
        # an equal or larger deadline would allow two leaders
        if config.renew_deadline >= config.lease_duration:
            raise ValueError(
                f"renew_deadline ({config.renew_deadline}) must be < "
                f"lease_duration ({config.lease_duration}): a leader must "
                "stop before its lease can be re-acquired")
        self.store = store
        self.config = config
        self.clock = clock or RealClock()
        self._leading = False
        self._observed: Optional[Lease] = None
        self._observed_at = 0.0
        # last SUCCESSFUL renew (fencing clock): step() tolerates store
        # failures only until last_renew + renew_deadline
        self._last_renew = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._leading

    # -- one acquisition/renewal attempt (leaderelection.go:287) -------------
    def try_acquire_or_renew(self) -> bool:
        now = self.clock.now()
        cfg = self.config
        new_record = Lease(
            name=cfg.lock_name, holder=cfg.identity,
            acquire_time=now, renew_time=now,
            lease_duration=cfg.lease_duration)
        try:
            current = self.store.get(LEASES, cfg.lock_name)
        except NotFoundError:
            try:
                self.store.create(LEASES, new_record)
            except AlreadyExistsError:
                return False
            self._observe(new_record, now)
            return True
        # refresh observation clock on any record change
        if self._observed is None or \
                self._observed.resource_version != current.resource_version:
            self._observe(current, now)
        if current.holder != cfg.identity:
            if self._observed_at + current.lease_duration > now and current.holder:
                return False  # current leader still valid
            new_record.acquire_time = now
            new_record.leader_transitions = current.leader_transitions + 1
        else:
            new_record.acquire_time = current.acquire_time
            new_record.leader_transitions = current.leader_transitions
        try:
            updated = self.store.update(LEASES, new_record,
                                        expect_rv=current.resource_version)
        except (ConflictError, NotFoundError):
            return False
        self._observe(updated, now)
        return True

    def _observe(self, record: Lease, now: float) -> None:
        self._observed = record
        self._observed_at = now

    # -- run loop ------------------------------------------------------------
    def step(self) -> bool:
        """One election step; returns current leadership. Suitable for
        deterministic test pumping as well as the background loop.

        Fencing (leaderelection.go renewLoop): a store failure during a
        renew is TRANSIENT — the holder keeps leading and retrying — but
        only until `renew_deadline` past the last successful renew; at the
        deadline it fires on_stopped_leading and stops, strictly before
        the lease (lease_duration > renew_deadline) becomes acquirable by
        another candidate. A definitive loss (the CAS failed because the
        record moved, or another holder is valid) steps down immediately."""
        now = self.clock.now()
        try:
            got = self.try_acquire_or_renew()
        except Exception:   # noqa: BLE001 — store unreachable: transient
            got = None
        if got:
            self._last_renew = now
            if not self._leading:
                self._leading = True
                if self.config.on_started_leading:
                    self.config.on_started_leading()
        elif self._leading:
            if got is None \
                    and now - self._last_renew < self.config.renew_deadline:
                # transient renew failure inside the deadline: keep
                # leading, the run loop retries (no split brain — the
                # lease itself is still unexpired for everyone else)
                return self._leading
            # deadline blown, or the lock definitively moved: stop
            # leading NOW, before the lease can be re-acquired
            self._leading = False
            if self.config.on_stopped_leading:
                self.config.on_stopped_leading()
        return self._leading

    def run(self) -> None:
        while not self._stop.is_set():
            self.step()
            self.clock.sleep(self.config.retry_period)

    def start(self) -> None:
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self.run, daemon=True,
                                            name=f"elector-{self.config.identity}")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def release(self) -> None:
        """Voluntarily give up the lease (leaderelection.go release)."""
        if not self._leading:
            return
        try:
            current = self.store.get(LEASES, self.config.lock_name)
            if current.holder == self.config.identity:
                current.holder = ""
                current.renew_time = 0.0
                self.store.update(LEASES, current,
                                  expect_rv=current.resource_version)
        except (NotFoundError, ConflictError):
            pass
        self._leading = False
        if self.config.on_stopped_leading:
            self.config.on_stopped_leading()
