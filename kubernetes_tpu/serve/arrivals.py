"""Arrival generator — the hollow client feeding the serving pipeline.

Creates pods against any Store surface (embedded `Store` or
`RemoteStore` — the verb is just `create(PODS, pod)`) at a target
arrival rate, batch-paced: each tick creates the number of pods the
elapsed wall time owes at `rate`, so a generator thread that loses the
GIL to the scheduler catches up instead of silently under-delivering.

Backpressure is honored exactly like a well-behaved client: a shed
create (`BackpressureError`, the 429 + Retry-After contract) books the
rejection and RE-QUEUES the arrival locally for after the server's
suggested backoff (with jitter) — arrivals are never silently dropped,
so the bench's all-admitted-or-429'd audit can account for every one.
Arrivals still pending re-admission when the run ends are reported as
`shed_final` (the client gave up, as a real client eventually would).
"""
from __future__ import annotations

import random
import time
from typing import Optional

from kubernetes_tpu import obs
from kubernetes_tpu.api.types import Container, Pod
from kubernetes_tpu.store.store import (AlreadyExistsError,
                                        BackpressureError, PODS)

MI = 1024 ** 2

INGEST_BATCH = obs.histogram(
    "arrival_ingest_batch_size",
    "Fresh arrivals per batched create_many flush (the round-17 ingest "
    "contract: one admission-gate evaluation + one ledger admission "
    "stamp per flush). Single-create fallbacks observe 1.",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))


def default_pod(name: str) -> Pod:
    """The density-shaped arrival pod (the headline bench's spec)."""
    return Pod(name=name, labels={"app": "serve"},
               containers=(Container.make(
                   name="c", requests={"cpu": 100, "memory": 500 * MI}),))


class ArrivalGenerator:
    """Paced pod creation with 429-aware retry (see module docstring).

    Drive it cooperatively: `tick()` creates whatever is due now and
    returns quickly, so a single-threaded serve bench interleaves
    arrivals with serve windows without thread scheduling noise — or
    call `run()` on a thread for wall-clock pacing. `seed` fixes the
    retry-jitter stream and the name sequence, so two generators fed the
    same accept/shed answers produce identical arrival sequences (the
    serve parity fuzz's requirement)."""

    def __init__(self, store, rate: float, total: Optional[int] = None,
                 pod_fn=default_pod, name_prefix: str = "arr-",
                 seed: int = 0, give_up_after: int = 64):
        self.store = store
        self.rate = float(rate)
        self.total = total            # None = unbounded (duration-paced)
        self.pod_fn = pod_fn
        self.name_prefix = name_prefix
        self.give_up_after = int(give_up_after)
        self._rng = random.Random(seed)
        self._seq = 0
        self._t0: Optional[float] = None
        self._owed = 0.0
        # locally re-queued sheds: (due_time, name, attempts)
        self._retry: list = []
        self.attempted = 0            # distinct arrivals tried at least once
        self.created = 0
        self.rejected = 0             # total 429 sheds (incl. retries)
        self.gave_up = 0              # arrivals dropped after give_up_after

    def _create(self, name: str, attempts: int, now: float) -> None:
        try:
            self.store.create(PODS, self.pod_fn(name))
            self.created += 1
        except BackpressureError as e:
            self._shed(name, attempts, now, e.retry_after)
        except AlreadyExistsError:
            # a retried create whose first attempt actually landed
            self.created += 1

    def _shed(self, name: str, attempts: int, now: float,
              retry_after: float) -> None:
        self.rejected += 1
        if attempts + 1 >= self.give_up_after:
            self.gave_up += 1
            return
        # capped jittered client backoff off the server's suggestion
        delay = min(retry_after, 5.0) * (0.5 + self._rng.random())
        self._retry.append((now + delay, name, attempts + 1))

    def _create_batch(self, names: list, now: float) -> None:
        """Fresh arrivals ride ONE create_many: one admission-gate
        evaluation + one batched ledger admission stamp server-side (the
        round-17 ingest contract). A partial shed (429 with `accepted`)
        books the landed prefix and re-queues the shed tail with the
        usual jittered backoff — never silently dropped. Fresh names are
        unique by construction, so the batch can't AlreadyExists.
        Retries keep the per-pod path (a retry whose first attempt landed
        must resolve individually)."""
        pods = [self.pod_fn(nm) for nm in names]
        try:
            self.store.create_many(PODS, pods)
            self.created += len(pods)
        except BackpressureError as e:
            k = max(0, min(int(getattr(e, "accepted", 0)), len(pods)))
            self.created += k
            for nm in names[k:]:
                self._shed(nm, 0, now, e.retry_after)

    def _retry_batch(self, due: list, now: float) -> None:
        try:
            self.store.create_many(
                PODS, [self.pod_fn(name) for _t, name, _a in due])
            self.created += len(due)
        except BackpressureError as e:
            k = max(0, min(int(getattr(e, "accepted", 0)), len(due)))
            self.created += k
            for _t, name, attempts in due[k:]:
                self._shed(name, attempts, now, e.retry_after)
        except AlreadyExistsError:
            # some retry's first attempt landed after all (lossy
            # transport): resolve the chunk per-pod — creates that
            # landed in the raising batch re-resolve as AlreadyExists
            # -> counted created, exactly the per-pod contract
            for _t, name, attempts in due:
                self._create(name, attempts, now)

    def tick(self, now: Optional[float] = None) -> int:
        """Create every arrival due by `now` (fresh ones owed by the rate
        plus re-queued sheds whose backoff expired). Returns creates
        attempted this tick."""
        now = time.perf_counter() if now is None else now
        if self._t0 is None:
            self._t0 = now
        n = 0
        # re-admissions first: they arrived earlier and queue earlier.
        # Batched like fresh arrivals (one gate evaluation per flush) —
        # under sustained overload the retry pool is the DOMINANT create
        # source, and per-pod retries were hammering the admission
        # surface with six figures of creates/s. A retry whose first
        # attempt actually landed (AlreadyExists) is only possible over
        # a lossy transport; that chunk falls back to per-pod creates,
        # which resolve it exactly as before.
        due = [r for r in self._retry if r[0] <= now]
        if due:
            self._retry = [r for r in self._retry if r[0] > now]
            due.sort()
            n += len(due)
            if len(due) > 1 and hasattr(self.store, "create_many"):
                self._retry_batch(due, now)
            else:
                for _t, name, attempts in due:
                    self._create(name, attempts, now)
        self._owed += (now - self._t0) * self.rate
        self._t0 = now
        fresh = int(self._owed)
        if self.total is not None:
            fresh = min(fresh, self.total - self.attempted)
        self._owed -= fresh
        fresh = max(0, fresh)
        if fresh:
            names = []
            for _ in range(fresh):
                names.append(f"{self.name_prefix}{self._seq}")
                self._seq += 1
            self.attempted += fresh
            n += fresh
            INGEST_BATCH.observe(fresh)
            if fresh > 1 and hasattr(self.store, "create_many"):
                self._create_batch(names, now)
            else:
                for name in names:
                    self._create(name, 0, now)
        return n

    def finished(self) -> bool:
        return (self.total is not None and self.attempted >= self.total
                and not self._retry)

    def flush_retries(self, timeout: float = 30.0) -> None:
        """Drive pending re-admissions to an outcome (created or given
        up) — the post-run settlement the audit runs after."""
        deadline = time.perf_counter() + timeout
        while self._retry and time.perf_counter() < deadline:
            nxt = min(t for t, _n, _a in self._retry)
            time.sleep(max(0.0, min(nxt - time.perf_counter(), 0.05)))
            self.tick()

    def run(self, duration: float, stop=None) -> None:
        """Wall-clock pacing loop (thread entry): tick until `duration`
        elapses (or `stop()` is true), sleeping between ticks."""
        end = time.perf_counter() + duration
        while time.perf_counter() < end:
            if stop is not None and stop():
                return
            self.tick()
            if self.finished():
                return
            time.sleep(min(0.002, 1.0 / max(self.rate, 1.0)))

    def stats(self) -> dict:
        return {
            "attempted": self.attempted,
            "created": self.created,
            "rejected_429": self.rejected,
            "gave_up": self.gave_up,
            "pending_retry": len(self._retry),
        }
