"""Explicit backpressure for the serving mode — admission shed with 429.

Real apiservers shed load instead of queueing unboundedly (priority &
fairness, the eviction subresource's 429 + Retry-After); this module is
that contract for the serving pipeline. A `BackpressureGate` attaches to
the store's pod-create path (`Store.admission_gate`; the apiserver maps
the refusal to HTTP 429 with Retry-After) and sheds creates when either
watermark is exceeded:

- activeQ depth: pending pods the scheduler has not popped yet — the
  direct measure of queue wait eating the startup SLO;
- in-flight launch windows: windows planned/dispatched but not yet
  committed (the N-deep launch queue's occupancy), so a stalled device
  sheds instead of stacking encoded windows.

The suggested Retry-After scales with how far over the watermark the
queue is (a deeper queue needs a longer back-off to drain), bounded by
`retry_after_max`. Shedding is observable: `admission_rejected_total
{reason}` counts sheds by cause and the `serve_activeq_depth` /
`serve_inflight_windows` gauges read the live values at scrape time.

Rejection evicts the pod's lifecycle-ledger record (the round-16 bugfix):
first-stamp-wins would otherwise carry a shed attempt's stamp into the
readmitted pod and bill the client's backoff as startup latency.
"""
from __future__ import annotations

from typing import Callable, Optional

from kubernetes_tpu import chaos, obs
from kubernetes_tpu.store.store import BackpressureError

ADMISSION_REJECTED = obs.counter(
    "admission_rejected_total",
    "Pod creates shed by the serving backpressure gate, by reason: "
    "queue-depth (activeQ over the watermark), inflight-windows (the "
    "launch queue is full), injected (the chaos serve.shed seam fired). "
    "Every shed answered 429 + Retry-After; the write never landed.",
    ("reason",))

_ACTIVEQ_DEPTH = obs.gauge(
    "serve_activeq_depth",
    "Live activeQ depth the serving admission gate keys on (the most "
    "recently attached gate wins the gauge).")
_INFLIGHT_WINDOWS = obs.gauge(
    "serve_inflight_windows",
    "Launch windows planned/dispatched but not yet fully committed "
    "(N-deep launch-queue occupancy), as seen by the most recently "
    "attached serving gate.")
_SHED_STATE = obs.gauge(
    "serve_backpressure_active",
    "1 while the most recently attached serving gate is shedding "
    "(activeQ depth at/over the watermark), else 0.")


class BackpressureGate:
    """Admission gate keyed on activeQ depth and in-flight windows.

    `depth_fn` returns the live activeQ depth (the scheduler queue's
    `active_depth`); `inflight_fn` (optional) returns the launch queue's
    in-flight window count (the ServeLoop wires its own). `admit(pod)`
    raises `BackpressureError` carrying the suggested Retry-After, after
    evicting the pod's ledger record; it is called by `Store.create`
    under no store lock (the gate reads are lock-free snapshots — an
    admit racing a pop may let one extra pod in, which the NEXT create
    sheds; watermarks are flow control, not invariants)."""

    def __init__(self, depth_fn: Callable[[], int],
                 max_depth: int = 50_000,
                 inflight_fn: Optional[Callable[[], int]] = None,
                 max_inflight: Optional[int] = None,
                 retry_after_base: float = 0.05,
                 retry_after_max: float = 2.0):
        self.depth_fn = depth_fn
        self.max_depth = int(max_depth)
        self.inflight_fn = inflight_fn
        self.max_inflight = max_inflight
        self.retry_after_base = float(retry_after_base)
        self.retry_after_max = float(retry_after_max)
        self.rejected = 0          # total sheds through THIS gate
        self.admitted = 0
        _ACTIVEQ_DEPTH.set_function(lambda: float(self.depth_fn()))
        _INFLIGHT_WINDOWS.set_function(
            lambda: float(self.inflight_fn() if self.inflight_fn else 0))
        _SHED_STATE.set_function(
            lambda: 1.0 if self.depth_fn() >= self.max_depth else 0.0)

    def suggest_retry_after(self, depth: int) -> float:
        """Backoff suggestion scaled by overload: at the watermark the
        base applies; k watermarks deep suggests ~k x base (a deeper
        queue needs proportionally longer to drain), capped."""
        over = max(1.0, depth / max(self.max_depth, 1))
        return min(self.retry_after_max, self.retry_after_base * over)

    def _shed(self, pod, reason: str, message: str) -> None:
        self.rejected += 1
        ADMISSION_REJECTED.labels(reason).inc()
        # the round-16 ledger bugfix: a shed pod's record must not
        # survive into its readmitted life with the stale first stamp
        from kubernetes_tpu.obs.ledger import LEDGER
        LEDGER.evict(pod.key)
        raise BackpressureError(
            message, retry_after=self.suggest_retry_after(self.depth_fn()))

    def admit(self, pod) -> None:
        """Raise BackpressureError to shed `pod`'s create; return to
        admit. Checked at the store/apiserver admission surface BEFORE
        anything is written."""
        if chaos.take("serve.shed"):
            self._shed(pod, "injected",
                       f"{pod.key}: chaos-injected admission shed")
        depth = self.depth_fn()
        if depth >= self.max_depth:
            self._shed(pod, "queue-depth",
                       f"{pod.key}: activeQ depth {depth} >= "
                       f"watermark {self.max_depth}")
        if self.max_inflight is not None and self.inflight_fn is not None:
            inflight = self.inflight_fn()
            if inflight >= self.max_inflight:
                self._shed(pod, "inflight-windows",
                           f"{pod.key}: {inflight} launch windows in "
                           f"flight >= cap {self.max_inflight}")
        self.admitted += 1

    def admit_many(self, pods) -> tuple:
        """ONE gate evaluation for a whole create_many batch: returns
        (n_admitted, retry_after) where pods[:n_admitted] are admitted
        and the TAIL is shed (retry_after is None when nothing shed).

        Semantics mirror per-pod admits exactly: each serial create
        grows the informer backlog by one before the next gate read, so
        pod i of the batch is evaluated against depth base+i — the depth
        watermark therefore sheds a TAIL, never a middle. The in-flight
        window count cannot change mid-batch (no window dispatches inside
        a store create), so it is read once; a chaos serve.shed draw mid-
        batch sheds from that pod on (flow control errs toward shedding —
        the seam is an opt-in chaos path, and shed arrivals re-admit).
        Ledger records of shed pods are evicted in one batch, exactly
        like the per-pod _shed path."""
        n = len(pods)
        base = self.depth_fn()
        accepted = 0
        reason = None
        if self.max_inflight is not None and self.inflight_fn is not None \
                and self.inflight_fn() >= self.max_inflight:
            reason = "inflight-windows"
        else:
            for pod in pods:
                if chaos.take("serve.shed"):
                    reason = "injected"
                    break
                if base + accepted >= self.max_depth:
                    reason = "queue-depth"
                    break
                accepted += 1
        self.admitted += accepted
        if accepted == n:
            return n, None
        shed = pods[accepted:]
        self.rejected += len(shed)
        ADMISSION_REJECTED.labels(reason).inc(len(shed))
        from kubernetes_tpu.obs.ledger import LEDGER
        LEDGER.evict_many([p.key for p in shed])
        return accepted, self.suggest_retry_after(base + accepted)

    def debug_state(self) -> dict:
        return {
            "max_depth": self.max_depth,
            "max_inflight": self.max_inflight,
            "depth": int(self.depth_fn()),
            "inflight": (int(self.inflight_fn())
                         if self.inflight_fn is not None else None),
            "admitted": self.admitted,
            "rejected": self.rejected,
        }


def fleet_gate(loops, max_depth: int,
               retry_after_base: float = 0.25,
               retry_after_max: float = 2.0) -> BackpressureGate:
    """One admission gate for an active-active fleet sharing a store
    (round 18): the store has a single `admission_gate` hook, but N
    serve loops each own a queue. The gate keys on the LEAST-loaded
    instance's depth (a create is shed only when every member is over
    the watermark — the pod's namespace-hash owner may well be the idle
    one) and the SUM of in-flight launch windows (device pressure is a
    fleet-wide resource). Attach the returned gate to
    `store.admission_gate` yourself — the fleet bench owns that wiring."""
    from kubernetes_tpu.store.store import PODS as _PODS
    informers = [loop.sched.informers.informer(_PODS) for loop in loops]

    def depth() -> int:
        depths = [loop.sched.queue.active_depth() + inf.backlog()
                  for loop, inf in zip(loops, informers)]
        return min(depths) if depths else 0

    def inflight() -> int:
        return sum(loop.inflight_windows() for loop in loops)

    return BackpressureGate(
        depth, max_depth=max_depth, inflight_fn=inflight,
        max_inflight=4 * sum(max(1, loop.depth) for loop in loops),
        retry_after_base=retry_after_base,
        retry_after_max=retry_after_max)
