"""kubernetes_tpu.serve — arrival-driven serving (ROADMAP item 2).

Every headline number before round 16 was "drain a pre-built backlog";
serving heavy traffic means pods *arrive* — over the apiserver, through
informers, forever — and the scheduler must never idle the device OR let
an unbounded queue eat the startup SLO. This package turns the burst
pipeline into a serving system:

- `loop.ServeLoop` cuts fused drain windows from the LIVE activeQ on a
  cadence instead of draining to empty, reusing the shell's
  `schedule_burst` / `schedule_burst_fused` machinery unchanged so
  per-window decisions stay oracle-parity (the serve parity fuzz pins a
  ServeLoop's decision stream bit-identical to a serial oracle observing
  the same arrivals at window boundaries).
- `backpressure.BackpressureGate` is the explicit load-shedding contract:
  pod creates are checked against activeQ-depth / in-flight-window
  watermarks at the store/apiserver admission surface and shed with
  429 + Retry-After (`store.BackpressureError`); `RemoteStore` honors the
  Retry-After with capped jittered backoff. Accepted creates stamp the
  lifecycle ledger's admission slot, so `pod_startup_seconds_p99` scores
  true accepted-create -> commit latency under arrival load.
- `arrivals.ArrivalGenerator` is the hollow arrival client: paced pod
  creation at a target rate against any Store surface (embedded or
  remote), honoring 429 sheds exactly like a well-behaved client.

The N-deep launch queue that hides the tunnel RTT at arrival rate lives
in `core.tpu_scheduler` (TPUScheduler.launch_depth / launch_cap): while
window k's decisions commit, windows k+1..k+N are already encoded and
dispatched, and a refused/failed window discards its in-flight
successors unfetched and replans from the packed-block boundaries.
"""
from kubernetes_tpu.serve.backpressure import BackpressureGate  # noqa: F401
from kubernetes_tpu.serve.loop import ServeLoop                 # noqa: F401
from kubernetes_tpu.serve.arrivals import ArrivalGenerator      # noqa: F401
