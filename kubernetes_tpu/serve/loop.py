"""ServeLoop — the continuously-fed scheduler (arrival-driven mode).

The drain loops every bench ran before round 16 pop until the queue is
empty and stop; a serving scheduler never stops. ServeLoop wraps a
`Scheduler` and, per tick, pumps the informers (admission flows in over
the store/apiserver watches WHILE the device executes) and cuts one
launch-queue's worth of fused drain windows from the live activeQ:

    step():  pump -> schedule_burst(max_pods = window_size * depth)

The shell's burst machinery is reused UNCHANGED — gang gathering, fused
segments, wave commits, refusal/rewind, node-death tolerance — so every
window's decisions are oracle-parity by the existing contracts (the
serve parity fuzz pins the stream against a serial oracle observing the
same arrivals at window boundaries).

Window pipelining: the loop sets the algorithm's `launch_cap` to
`window_size` and `launch_depth` to `depth`, so a drain above one window
chunks into window-sized launches of which up to `depth` are in flight —
while window k's decisions commit, windows k+1..k+depth-1 are already
encoded and dispatched, hiding the ~100 ms tunnel RTT at arrival rate
rather than only inside one pre-built burst. Each window stays ONE
dispatch + ONE packed fetch (TestDeviceFetchContract pins it at depth
>= 3), and the rewind contract extends unchanged: a refused or failed
window discards its in-flight successors unfetched and replans from the
packed-block boundaries.

Backpressure closes the loop: `attach_gate` installs a
`BackpressureGate` keyed on this loop's live activeQ depth and in-flight
window count as the store's admission gate, so arrivals beyond what the
device sustains are shed with 429 + Retry-After instead of eating the
startup SLO in queue wait.
"""
from __future__ import annotations

import time
from typing import Optional

from kubernetes_tpu import obs
from kubernetes_tpu.serve.backpressure import BackpressureGate

SERVE_WINDOWS = obs.counter(
    "serve_windows_total",
    "Serve-loop ticks, by outcome: scheduled (the window bound pods), "
    "empty (the activeQ had nothing ready — the device idled this "
    "tick).", ("outcome",))
SERVE_PODS = obs.counter(
    "serve_pods_scheduled_total",
    "Pods bound by the serve loop's windows.")


class ServeLoop:
    """Arrival-driven serving over one Scheduler (see module docstring).

    `window_size` is the commit/failure granularity (one launch window);
    `depth` is the launch-queue depth — windows in flight while the
    oldest commits. `tick_interval` paces idle ticks only: a tick that
    found work immediately cuts the next window (a saturated serve loop
    is a busy loop, exactly like the drain benches)."""

    def __init__(self, scheduler, window_size: int = 2048,
                 depth: int = 3, tick_interval: float = 0.002):
        self.sched = scheduler
        self.window_size = int(window_size)
        self.depth = max(1, int(depth))
        self.tick_interval = float(tick_interval)
        self.windows_cut = 0
        self.pods_bound = 0
        self.idle_ticks = 0
        self.gate: Optional[BackpressureGate] = None
        # in-flight launch windows for the gate: the algorithm's driver
        # owns the real count; between steps it is 0
        algo = scheduler.algorithm
        if hasattr(algo, "launch_depth"):
            algo.launch_depth = self.depth
        if hasattr(algo, "launch_cap"):
            algo.launch_cap = self.window_size
        if hasattr(algo, "wave_size"):
            # commit windows align with launch windows: one commit wave
            # per window keeps the failure granularity the issue names
            algo.wave_size = min(int(algo.wave_size), self.window_size)

    # -- backpressure wiring -------------------------------------------------
    def inflight_windows(self) -> int:
        """Launch windows planned/dispatched but not fully committed —
        the N-deep launch queue's live occupancy (0 between steps)."""
        return int(getattr(self.sched.algorithm, "inflight_launches", 0))

    def attach_gate(self, max_depth: int,
                    max_inflight: Optional[int] = None,
                    retry_after_base: float = 0.05,
                    retry_after_max: float = 2.0) -> BackpressureGate:
        """Install a BackpressureGate keyed on THIS loop's queue depth and
        launch-queue occupancy as the scheduler store's admission gate
        (embedded store: `Store.admission_gate`; behind an apiserver the
        same hook sheds HTTP creates with 429 + Retry-After).

        Depth = activeQ + the pod informer's unpumped watch backlog: the
        activeQ alone lags creates by one pump, so a burst of arrivals
        between pumps would pass a stale watermark unobserved. The
        backlog counts every undelivered pod event (binds included), so
        under churn the gate errs toward shedding — flow control, not an
        invariant."""
        from kubernetes_tpu.store.store import PODS
        pods_inf = self.sched.informers.informer(PODS)
        queue = self.sched.queue

        def depth() -> int:
            return queue.active_depth() + pods_inf.backlog()

        gate = BackpressureGate(
            depth, max_depth=max_depth,
            inflight_fn=self.inflight_windows,
            max_inflight=(max_inflight if max_inflight is not None
                          else 4 * self.depth),
            retry_after_base=retry_after_base,
            retry_after_max=retry_after_max)
        self.gate = gate
        store = self.sched.store
        if hasattr(store, "admission_gate"):
            store.admission_gate = gate
        return gate

    # -- the loop ------------------------------------------------------------
    def step(self) -> int:
        """One serve tick: deliver pending watch events, then cut up to
        `depth` launch windows from the live activeQ. Returns pods bound
        this tick."""
        self.sched.pump()
        bound = self.sched.schedule_burst(
            max_pods=self.window_size * self.depth)
        if bound > 0:
            self.windows_cut += 1
            self.pods_bound += bound
            SERVE_WINDOWS.labels("scheduled").inc()
            SERVE_PODS.inc(bound)
        else:
            self.idle_ticks += 1
            SERVE_WINDOWS.labels("empty").inc()
        return bound

    def run(self, duration: Optional[float] = None,
            until=None) -> dict:
        """Serve for `duration` seconds (or until `until()` is true);
        idle ticks sleep `tick_interval` so an empty queue doesn't spin
        the informer pump. Returns the loop's stats snapshot."""
        deadline = (None if duration is None
                    else time.perf_counter() + duration)
        while True:
            if until is not None and until():
                break
            if deadline is not None and time.perf_counter() >= deadline:
                break
            if self.step() == 0:
                time.sleep(self.tick_interval)
        return self.stats()

    def drain(self, timeout: float = 60.0) -> int:
        """Post-run drain: serve until the queue stays empty (arrivals
        stopped). Returns pods bound during the drain."""
        bound = 0
        deadline = time.perf_counter() + timeout
        idle = 0
        while time.perf_counter() < deadline:
            n = self.step()
            bound += n
            if n == 0:
                idle += 1
                if idle >= 3 and self.sched.queue.num_pending() == 0:
                    break
                time.sleep(self.tick_interval)
            else:
                idle = 0
        return bound

    def stats(self) -> dict:
        return {
            "windows_cut": self.windows_cut,
            "pods_bound": self.pods_bound,
            "idle_ticks": self.idle_ticks,
            "window_size": self.window_size,
            "depth": self.depth,
            "gate": (self.gate.debug_state()
                     if self.gate is not None else None),
        }
